"""Observability: log-bucketed histograms, flight recorder, exposition.

The measurement layer for ROADMAP item 2 ("publish a top-5 cost table"):
before any commit/read optimization ports (arxiv 1905.10786), the wave
loop and the commit path need per-phase timing and a post-mortem trace.
Three primitives, all safe on the hot path:

- :class:`LogHistogram` — HdrHistogram-style log-bucketed latency
  histogram (power-of-two octaves with linear sub-buckets, int64 numpy
  slots, same single-writer discipline as ``ra_tpu.counters.Counters``).
  Relative quantile error is bounded by ``1/SUB_BUCKETS`` (~3.1%).
  Values are recorded in NANOSECONDS; exports convert.

- :class:`FlightRecorder` — bounded ring buffer of structured events
  (role changes, elections, depositions, snapshot installs, watchdog
  strikes, admission rejects, failpoint fires, WAL failures, health
  transitions, phi suspect/unsuspect flips) with
  monotonic timestamps, group id and term. Appends are lock-free
  (CPython: slot assignment is atomic; sequence numbers come from an
  ``itertools.count``, whose ``next`` is atomic), so any thread —
  detector, WAL writer, step loop — may record. Reads are best-effort
  snapshots, exactly like counter reads.

- exposition — ``prometheus_text()`` renders every registered counter
  (with the kind/help from its field specs) and histogram (as a summary
  with p50/p90/p99/p99.9 quantiles in seconds) in Prometheus text
  format; ``api.system_overview`` bundles the same data as one dict
  (parity with the reference's ``ra:overview/1`` over seshat counters).

The reference keeps this layer in ``ra_counters``/seshat plus the
per-server overview (``src/ra.erl`` overview/1); a TPU-batched hot path
additionally needs distributions (one smoothed gauge cannot answer
"where do 92.5 ms go") and a wave-phase breakdown, recorded here.
"""

from __future__ import annotations

import itertools
import sys
import threading
import time
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

import numpy as np

# ---------------------------------------------------------------------------
# log-bucketed histogram

SUB_BITS = 5
SUB_BUCKETS = 1 << SUB_BITS  # linear sub-buckets per power-of-two octave
# enough buckets for any int64 nanosecond value (shift <= 63 - SUB_BITS)
N_BUCKETS = ((64 - SUB_BITS) << SUB_BITS) + SUB_BUCKETS


def bucket_of(v: int) -> int:
    """Bucket index for a non-negative int. Buckets are exact below
    ``SUB_BUCKETS`` and cover ``[lo, lo + 2**shift)`` ranges above, with
    ``SUB_BUCKETS`` linear sub-buckets per octave (HdrHistogram
    bucketing; max relative error 1/SUB_BUCKETS)."""
    if v < SUB_BUCKETS:
        return v if v >= 0 else 0
    shift = v.bit_length() - 1 - SUB_BITS
    b = ((shift + 1) << SUB_BITS) + ((v >> shift) - SUB_BUCKETS)
    return b if b < N_BUCKETS else N_BUCKETS - 1


def bucket_bounds(b: int) -> Tuple[int, int]:
    """Inclusive [lo, hi] value range of bucket ``b`` (inverse of
    :func:`bucket_of`)."""
    if b < SUB_BUCKETS:
        return b, b
    shift = (b >> SUB_BITS) - 1
    lo = ((b & (SUB_BUCKETS - 1)) + SUB_BUCKETS) << shift
    return lo, lo + (1 << shift) - 1


class LogHistogram:
    """Lock-free log-bucketed histogram (single-writer slots, like
    ``Counters``; readers may see slightly stale values). Records
    non-negative integers — by convention nanoseconds.

    ``locked=True`` adds a writer lock for histograms shared by
    CONCURRENT writers (e.g. the per-node commit-stage family, written
    by every actor server on the node across scheduler worker threads
    plus any coordinator step thread): ``arr[b] += n`` is a
    read-modify-write, so multi-writer updates would lose increments
    and drift ``n``/``total`` from the bucket sums. Recording is
    sampled on those paths, so the lock is off the per-command cost."""

    __slots__ = ("name", "help", "unit", "arr", "n", "total", "max_v",
                 "_lock")

    def __init__(self, name, help: str = "", unit: str = "ns",
                 locked: bool = False):
        self.name = name
        self.help = help
        self.unit = unit
        self.arr = np.zeros(N_BUCKETS, dtype=np.int64)
        self.n = 0
        self.total = 0
        self.max_v = 0
        self._lock = threading.Lock() if locked else None

    def record(self, v: int, count: int = 1) -> None:
        v = int(v)
        if v < 0:
            v = 0
        if v < SUB_BUCKETS:
            b = v
        else:
            shift = v.bit_length() - 1 - SUB_BITS
            b = ((shift + 1) << SUB_BITS) + ((v >> shift) - SUB_BUCKETS)
            if b >= N_BUCKETS:
                b = N_BUCKETS - 1
        lock = self._lock
        if lock is not None:
            with lock:
                self.arr[b] += count
                self.n += count
                self.total += v * count
                if v > self.max_v:
                    self.max_v = v
            return
        self.arr[b] += count
        self.n += count
        self.total += v * count
        if v > self.max_v:
            self.max_v = v

    def record_seconds(self, s: float, count: int = 1) -> None:
        self.record(int(s * 1e9), count)

    # -- reads -------------------------------------------------------------

    def percentile(self, p: float) -> int:
        """Value at percentile ``p`` (0..100), as the midpoint of the
        covering bucket; 0 when empty."""
        return self.percentiles((p,))[0]

    def percentiles(self, ps: Sequence[float]) -> List[int]:
        counts = self.arr.copy()  # snapshot: writer may race the scan
        total = int(counts.sum())
        if total == 0:
            return [0] * len(ps)
        cum = np.cumsum(counts)
        out = []
        for p in ps:
            # rank of the p-th percentile observation (1-based)
            rank = max(1, min(total, int(np.ceil(p / 100.0 * total))))
            b = int(np.searchsorted(cum, rank))
            lo, hi = bucket_bounds(b)
            out.append((lo + hi) // 2)
        return out

    def mean(self) -> float:
        return self.total / self.n if self.n else 0.0

    def to_dict(self) -> Dict[str, Any]:
        """Summary percentiles in milliseconds (assuming ns records)."""
        p50, p90, p99, p999 = self.percentiles((50, 90, 99, 99.9))
        return {
            "count": self.n,
            "sum_ms": round(self.total / 1e6, 3),
            "mean_ms": round(self.mean() / 1e6, 4),
            "max_ms": round(self.max_v / 1e6, 3),
            "p50_ms": round(p50 / 1e6, 4),
            "p90_ms": round(p90 / 1e6, 4),
            "p99_ms": round(p99 / 1e6, 4),
            "p99_9_ms": round(p999 / 1e6, 4),
        }

    def nonzero_buckets(self) -> List[Tuple[int, int, int]]:
        """(lo, hi, count) for every non-empty bucket (debug/export)."""
        idx = np.flatnonzero(self.arr)
        return [(*bucket_bounds(int(b)), int(self.arr[b])) for b in idx]

    def merge(self, other: "LogHistogram") -> None:
        """Fold another histogram's buckets into this one (aggregation
        across nodes/shards; both must use the same unit)."""
        self.arr += other.arr
        self.n += other.n
        self.total += other.total
        if other.max_v > self.max_v:
            self.max_v = other.max_v

    def reset(self) -> None:
        self.arr[:] = 0
        self.n = 0
        self.total = 0
        self.max_v = 0


class HistogramRegistry:
    """Process-global registry: name -> LogHistogram (mirrors
    CounterRegistry; ``new`` returns the existing histogram when the
    name is already registered)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._tab: Dict[object, LogHistogram] = {}

    def new(self, name, help: str = "", unit: str = "ns",
            locked: bool = False) -> LogHistogram:
        with self._lock:
            h = self._tab.get(name)
            if h is None:
                h = LogHistogram(name, help=help, unit=unit, locked=locked)
                self._tab[name] = h
            return h

    def fetch(self, name) -> Optional[LogHistogram]:
        with self._lock:
            return self._tab.get(name)

    def delete(self, name) -> None:
        with self._lock:
            self._tab.pop(name, None)

    def names(self) -> List[object]:
        with self._lock:
            return list(self._tab.keys())

    def overview(self) -> Dict[object, Dict[str, Any]]:
        with self._lock:
            items = list(self._tab.items())
        return {k: h.to_dict() for k, h in items if h.n}


_hists = HistogramRegistry()


def histograms() -> HistogramRegistry:
    return _hists


def histogram(name, help: str = "", unit: str = "ns",
              locked: bool = False) -> LogHistogram:
    return _hists.new(name, help=help, unit=unit, locked=locked)


# -- well-known histogram families ------------------------------------------

# coordinator wave-loop phases (per step; docs/INTERNALS.md §13).
# WAVE_STEP_PHASES are DISJOINT slices of one coordinator step — they
# sum to the step-loop wall time and are the share denominator in
# attribution tools; WAVE_SUBSET_PHASES are finer-grained views RECORDED
# WITHIN a step phase (never added to the denominator). profile_wave.py
# derives its tables from these, so a new phase lands there for free.
WAVE_STEP_PHASES = (
    ("ingress_drain", "drain ingress queues + route messages + append "
                      "client commands (includes WAL handoff)"),
    ("host_pack", "apply queued device scatters + pack the mailbox"),
    ("device_step", "fused consensus step dispatch + egress host sync"),
    ("host_egress", "realise egress: acks, role changes, apply, replies"),
    ("aer_fanout", "build + send outbound AER batches"),
)
WAVE_SUBSET_PHASES = {
    "apply": "subset of host_egress (machine apply, sampled groups)",
    "wal_handoff": "subset of ingress_drain (log.append hand-off, "
                   "sampled groups)",
    "classify_native": "subset of ingress_drain (GIL-released native "
                       "class partition of the drained burst; zero "
                       "samples when the native path is off)",
    "pack_native": "subset of host_pack (GIL-released native mailbox "
                   "scatter; zero samples when the native path is off)",
}
WAVE_PHASES = WAVE_STEP_PHASES + tuple(WAVE_SUBSET_PHASES.items())

# commit-latency decomposition stages (sampled per command; both backends)
COMMIT_STAGES = (
    ("submit_append", "client submit -> leader log append"),
    ("append_durable", "log append -> WAL durable watermark covers it"),
    ("durable_commit", "durable -> quorum commit observed"),
    ("commit_apply", "commit observed -> machine apply done"),
    ("apply_reply", "machine apply -> client reply issued"),
)


def wave_hists(node_name: str) -> Dict[str, LogHistogram]:
    return {
        ph: histogram(("wave", node_name, ph), help=h)
        for ph, h in WAVE_PHASES
    }


def staleness_hist(node_name: str) -> LogHistogram:
    """Observed staleness bound claimed at each bounded local read
    (api.local_query max_staleness_s path, docs/INTERNALS.md §20) —
    recorded in ns of leader wall time, whether the read was served or
    rejected, so the distribution shows how fresh followers really run."""
    return histogram(
        ("follower_read_staleness", node_name),
        help="leader-stamped staleness bound evaluated for bounded "
             "local reads (max_staleness_s, docs/INTERNALS.md §20)",
        locked=True,
    )


def commit_hists(node_name: str) -> Dict[str, LogHistogram]:
    # locked: one family per NODE, but every actor server on the node
    # (scheduler worker threads) and any coordinator step thread write
    # it concurrently — recording is sampled, so the lock is cheap
    return {
        st: histogram(("commit", node_name, st), help=h, locked=True)
        for st, h in COMMIT_STAGES
    }


# ---------------------------------------------------------------------------
# flight recorder


class FlightRecorder:
    """Bounded ring of structured events for post-mortem debugging.

    Events: ``(t_monotonic, seq, kind, node, group, term, detail)``.
    Appends are lock-free and safe from any thread; the ring holds the
    most recent ``capacity`` events. ``dump()`` renders them oldest
    first — the shape a liveness flake is debugged from."""

    def __init__(self, capacity: int = 4096):
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self.capacity = capacity
        self._slots: List[Optional[Tuple]] = [None] * capacity
        self._ctr = itertools.count()

    def record(self, kind: str, node: Optional[str] = None,
               group: Optional[str] = None, term: Optional[int] = None,
               detail: Any = None) -> None:
        n = next(self._ctr)  # atomic in CPython
        self._slots[n % self.capacity] = (
            time.monotonic(), n, kind, node, group, term, detail
        )

    def events(self, last: Optional[int] = None) -> List[Dict[str, Any]]:
        """Events oldest -> newest (optionally only the last ``n``)."""
        got = [s for s in list(self._slots) if s is not None]
        got.sort(key=lambda s: s[1])
        if last is not None:
            got = got[-last:]
        return [
            {"ts": s[0], "seq": s[1], "kind": s[2], "node": s[3],
             "group": s[4], "term": s[5], "detail": s[6]}
            for s in got
        ]

    def clear(self) -> None:
        self._slots = [None] * self.capacity

    def dump(self, file=None, last: int = 200, header: str = "") -> None:
        """Human-readable dump of the most recent events (stderr by
        default) — called automatically when a kv_harness/nemesis run
        fails so liveness flakes arrive with their trace attached."""
        f = file or sys.stderr
        evts = self.events(last=last)
        print(f"-- flight recorder dump ({len(evts)} events){header} --",
              file=f)
        if not evts:
            print("   (no events recorded)", file=f)
            return
        t0 = evts[0]["ts"]
        for e in evts:
            grp = f" group={e['group']}" if e["group"] is not None else ""
            trm = f" term={e['term']}" if e["term"] is not None else ""
            det = f" {e['detail']}" if e["detail"] is not None else ""
            print(
                f"  +{e['ts'] - t0:9.3f}s #{e['seq']:<6d} "
                f"{e['kind']:<18s} node={e['node']}{grp}{trm}{det}",
                file=f,
            )


_recorder = FlightRecorder()


def flight_recorder() -> FlightRecorder:
    return _recorder


def record_event(kind: str, node: Optional[str] = None,
                 group: Optional[str] = None, term: Optional[int] = None,
                 detail: Any = None) -> None:
    _recorder.record(kind, node=node, group=group, term=term, detail=detail)


# ---------------------------------------------------------------------------
# trace buffer (Chrome/Perfetto trace-event export)


class TraceBuffer:
    """Bounded ring of completed phase spans, exported as Chrome trace
    events (``chrome://tracing`` / Perfetto JSON) so wave-phase overlap
    is VISIBLE on a timeline — the verification surface the coordinator
    step-pipelining work (ROADMAP item 2) needs: histograms say how
    long ``device_step`` takes, the trace shows whether it overlaps
    ``host_egress`` of the previous step.

    Span recording follows the flight-recorder discipline: lock-free
    appends (atomic slot store + ``itertools.count``), timestamps from
    ``time.perf_counter_ns()`` (the clock the wave loop already reads),
    safe from any thread. Disabled by default — the step loop pays one
    attribute check per step until ``enable()`` (profile_wave --trace,
    tests, or an operator turning it on live)."""

    def __init__(self, capacity: int = 1 << 16):
        if capacity <= 0:
            raise ValueError("capacity must be positive")
        self.capacity = capacity
        self.enabled = False
        self._slots: List[Optional[Tuple]] = [None] * capacity
        self._ctr = itertools.count()

    def enable(self) -> None:
        self.enabled = True

    def disable(self) -> None:
        self.enabled = False

    def clear(self) -> None:
        self._slots = [None] * self.capacity
        self._ctr = itertools.count()

    def span(self, name: str, pid: str, ts_ns: int, dur_ns: int,
             tid: Optional[str] = None, cat: str = "wave") -> None:
        """Record one completed span (begin at ``ts_ns``, ``dur_ns``
        long; perf_counter_ns clock). ``pid`` groups lanes per node,
        ``tid`` is the lane (defaults to the span name)."""
        n = next(self._ctr)  # atomic in CPython
        self._slots[n % self.capacity] = (
            ts_ns, dur_ns, name, pid, tid or name, cat, n
        )

    def spans(self) -> List[Tuple]:
        got = [s for s in list(self._slots) if s is not None]
        got.sort(key=lambda s: (s[0], s[6]))
        return got

    def to_chrome(self) -> Dict[str, Any]:
        """Render the ring as a Chrome trace-event document: matched
        B/E pairs per (pid, tid) lane plus process/thread metadata.
        Timestamps are microsecond floats relative to the earliest
        span (the format's expectation)."""
        spans = self.spans()
        events: List[Dict[str, Any]] = []
        pids: Dict[str, int] = {}
        tids: Dict[Tuple[str, str], int] = {}
        t0 = spans[0][0] if spans else 0
        for ts_ns, dur_ns, name, pid_s, tid_s, cat, _n in spans:
            pid = pids.setdefault(pid_s, len(pids) + 1)
            tkey = (pid_s, tid_s)
            if tkey not in tids:
                tids[tkey] = len(tids) + 1
            tid = tids[tkey]
            ts_us = (ts_ns - t0) / 1e3
            events.append({"name": name, "cat": cat, "ph": "B",
                           "ts": ts_us, "pid": pid, "tid": tid})
            events.append({"name": name, "cat": cat, "ph": "E",
                           "ts": ts_us + max(dur_ns, 0) / 1e3,
                           "pid": pid, "tid": tid})
        meta = [
            {"name": "process_name", "ph": "M", "pid": pid, "tid": 0,
             "args": {"name": pid_s}}
            for pid_s, pid in pids.items()
        ] + [
            {"name": "thread_name", "ph": "M", "pid": pids[pid_s],
             "tid": tid, "args": {"name": tid_s}}
            for (pid_s, tid_s), tid in tids.items()
        ]
        return {"traceEvents": meta + events, "displayTimeUnit": "ms"}

    def dump(self, path: str) -> int:
        """Write the Chrome trace JSON to ``path``; returns the number
        of span events written (excluding metadata)."""
        import json

        doc = self.to_chrome()
        with open(path, "w") as f:
            json.dump(doc, f)
        return sum(1 for e in doc["traceEvents"] if e["ph"] != "M")


_trace = TraceBuffer()


def trace_buffer() -> TraceBuffer:
    return _trace


def validate_chrome_trace(doc: Any) -> List[str]:
    """Structural validation of a Chrome trace document (the obs_smoke
    gate and the tests both run dumped files through this): span events
    must carry numeric ts/pid/tid, every lane's B/E events must nest
    and match, and each lane's begin timestamps must be monotone.
    Returns a list of problems (empty == well-formed)."""
    errors: List[str] = []
    if not isinstance(doc, dict) or not isinstance(
        doc.get("traceEvents"), list
    ):
        return ["traceEvents missing or not a list"]
    lanes: Dict[Tuple, List] = {}
    for i, e in enumerate(doc["traceEvents"]):
        ph = e.get("ph")
        if ph == "M":
            continue
        if ph not in ("B", "E", "X", "i", "I"):
            errors.append(f"event {i}: unknown ph {ph!r}")
            continue
        ts = e.get("ts")
        if not isinstance(ts, (int, float)) or ts != ts or ts < 0:
            errors.append(f"event {i}: bad ts {ts!r}")
            continue
        if not isinstance(e.get("pid"), int) or not isinstance(
            e.get("tid"), int
        ):
            errors.append(f"event {i}: non-int pid/tid")
            continue
        lanes.setdefault((e["pid"], e["tid"]), []).append(
            (ts, ph, e.get("name"), i)
        )
    for lane, evts in lanes.items():
        stack: List[Tuple] = []
        last_b = -1.0
        for ts, ph, name, i in evts:  # events are emitted in ts order
            if ph == "B":
                if ts < last_b:
                    errors.append(
                        f"lane {lane}: non-monotone begin at event {i}"
                    )
                last_b = ts
                stack.append((name, ts))
            elif ph == "E":
                if not stack:
                    errors.append(f"lane {lane}: E without B at event {i}")
                    continue
                b_name, b_ts = stack.pop()
                if name is not None and b_name != name:
                    errors.append(
                        f"lane {lane}: mismatched span {b_name!r}/"
                        f"{name!r} at event {i}"
                    )
                if ts < b_ts:
                    errors.append(
                        f"lane {lane}: span {name!r} ends before it "
                        f"begins at event {i}"
                    )
        if stack:
            errors.append(
                f"lane {lane}: {len(stack)} unmatched B events"
            )
    return errors


# ---------------------------------------------------------------------------
# exposition


def _metric_name(name) -> str:
    """Flatten a registry key into a Prometheus metric-name suffix."""
    if isinstance(name, tuple):
        flat = "_".join(str(p) for p in name)
    else:
        flat = str(name)
    return "".join(c if c.isalnum() or c == "_" else "_" for c in flat)


def _label_of(name) -> str:
    s = str(name).replace("\\", "\\\\").replace('"', '\\"').replace("\n", " ")
    return f'name="{s}"'


def prometheus_text() -> str:
    """Prometheus text exposition of every registered counter vector and
    histogram. Counters keep their field kind/help (the describe() path
    ``overview()`` drops); histograms export as summaries in SECONDS
    plus ``_count``/``_sum``."""
    from ra_tpu import counters as _counters

    out: List[str] = []
    # counters: one metric family per field name; vectors become labels.
    # Collect (field -> kind, help, [(owner, value)]) across the registry.
    fields: Dict[str, Tuple[str, str, List[Tuple[object, int]]]] = {}
    reg = _counters.registry()
    for owner in reg.names():
        c = reg.fetch(owner)
        if c is None:
            continue
        vals = c.to_dict()
        for fname, kind, help_txt in c.fields:
            ent = fields.get(fname)
            if ent is None:
                ent = fields[fname] = (kind, help_txt, [])
            ent[2].append((owner, vals[fname]))
    for fname in sorted(fields):
        kind, help_txt, rows = fields[fname]
        metric = f"ra_{_metric_name(fname)}"
        out.append(f"# HELP {metric} {help_txt}")
        out.append(f"# TYPE {metric} {'counter' if kind == 'counter' else 'gauge'}")
        for owner, v in rows:
            out.append(f"{metric}{{{_label_of(owner)}}} {v}")
    # histograms: summaries with fixed quantiles, values in seconds
    for name in sorted(_hists.names(), key=str):
        h = _hists.fetch(name)
        if h is None:
            continue
        metric = f"ra_{_metric_name(name)}_seconds"
        out.append(f"# HELP {metric} {h.help or 'latency histogram'}")
        out.append(f"# TYPE {metric} summary")
        ps = h.percentiles((50, 90, 99, 99.9))
        for q, v in zip(("0.5", "0.9", "0.99", "0.999"), ps):
            out.append(f'{metric}{{quantile="{q}"}} {v / 1e9:.9f}')
        out.append(f"{metric}_sum {h.total / 1e9:.9f}")
        out.append(f"{metric}_count {h.n}")
    return "\n".join(out) + "\n"
