"""Batch coordinator: many raft groups stepped together on the device.

The framework's north-star execution backend (``server_impl =
"tpu_batch"``): instead of one actor per group, one coordinator owns the
consensus decision state of *all* its groups as device arrays
(``ra_tpu.ops.consensus.GroupState``) and advances them in fused steps —
one ``consensus_step`` call classifies up to one inbound message per
group and runs every group's quorum scan at once.

Division of labor (keeps host<->device traffic to one egress struct per
step):

- **device (authoritative)**: current_term, voted_for, role, votes,
  match_index, commit_index, log-tail bookkeeping + recent-term ring;
- **host (authoritative)**: log *contents* (WAL/memtable/segments),
  machine apply, client replies, outbound AER construction with its own
  ``next_index`` bookkeeping (host routes every inbound reply anyway, so
  both sides update their own variables from the same messages — no
  gathers needed);
- **rare paths** (election initiation, deep-backfill term lookups) run
  host-side against the post-step egress mirror, re-entering the device
  via scatters (``set_roles``/``record_appended``) and mailbox term
  overrides.

The coordinator registers in the node registry and speaks the same
transport/protocol as per-group ServerProcs, so batch-backed and
actor-backed members interoperate in one cluster. Replies leaving a step
are batched per destination node — thousands of groups' traffic rides
single transport hops.

Snapshot install/send for batch-backed groups is fully implemented:
``_receive_snapshot_chunk`` runs the 4-phase chunked accept (init/pre/
next/last) host-side and scatters the new floor to the device;
``_start_snapshot_sender`` spools + streams outbound transfers through
the shared ``SnapshotSender`` (see ``ra_tpu/runtime/proc.py``); batch-
and actor-backed members interoperate in either direction.
"""

from __future__ import annotations

import logging
import pickle
import random
import threading
import time
from bisect import bisect_left
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp

from ra_tpu import effects as fx
from ra_tpu import faults
from ra_tpu import leaderboard
from ra_tpu import native as _native
from ra_tpu.log.api import LogApi
from ra_tpu.log.memory import MemoryLog
from ra_tpu.machine import Machine, normalize_apply_result
from ra_tpu.ops import consensus as C
from ra_tpu.protocol import (
    AppendEntriesReply,
    AppendEntriesRpc,
    CHUNK_INIT,
    CHUNK_LAST,
    CHUNK_NEXT,
    CHUNK_PRE,
    Command,
    ElectionTimeout,
    TimeoutNow,
    Entry,
    FromPeer,
    HeartbeatReply,
    HeartbeatRpc,
    InstallSnapshotAck,
    InstallSnapshotResult,
    InstallSnapshotRpc,
    LOSSY_PROTOCOL_TYPES,
    NOOP,
    RC_BATCH,
    RC_CMD,
    RC_CMD_LOW,
    RC_CMDS,
    RC_CMDS_LOW,
    RC_MSG,
    REJECT_NOSPACE,
    REJECT_OVERLOADED,
    PreVoteResult,
    PreVoteRpc,
    RA_CLUSTER_CHANGE,
    RA_JOIN,
    RA_LEAVE,
    RequestVoteResult,
    RequestVoteRpc,
    ServerId,
    USR,
)
from ra_tpu.runtime.transport import InProcTransport, NodeRegistry, registry as node_registry

logger = logging.getLogger("ra_tpu")

MSG_OF_TYPE = {
    AppendEntriesRpc: C.MSG_AER,
    AppendEntriesReply: C.MSG_AER_REPLY,
    RequestVoteRpc: C.MSG_VOTE_REQ,
    RequestVoteResult: C.MSG_VOTE_REPLY,
    PreVoteRpc: C.MSG_PREVOTE_REQ,
    PreVoteResult: C.MSG_PREVOTE_REPLY,
}

_NATIVE_PATHS = frozenset(("pack", "classify", "egress"))


def parse_native(spec) -> frozenset:
    """Parse a ``--native`` spec into the set of enabled native
    hot-loop paths: ``"auto"``/``"on"``/``True`` enable all three,
    ``"off"``/``"none"``/``False`` none, anything else a comma list
    over {pack, classify, egress} (docs/INTERNALS.md §18)."""
    if spec is True or spec in ("auto", "on", "all"):
        return _NATIVE_PATHS
    if not spec or spec in ("off", "none"):
        return frozenset()
    parts = frozenset(p.strip() for p in str(spec).split(",") if p.strip())
    unknown = parts - _NATIVE_PATHS
    if unknown:
        raise ValueError(f"unknown native paths {sorted(unknown)}")
    return parts


class GroupHost:
    """Host-side companion of one device-resident group."""

    __slots__ = (
        "gid", "name", "cluster_name", "members", "self_slot", "log",
        "machine", "machine_state", "last_applied", "role", "term",
        "leader_slot", "next_index", "commit_sent", "pending_replies",
        "inbox", "host_term_hint", "election_ref", "effective_machine_version",
        "pending_ack", "snap_accept", "snap_senders", "pre_vote_token",
        "voter_status", "cluster_change_permitted", "cluster_index",
        "pending_queries", "machine_timers", "has_tick", "snap_floor",
        "noop_index", "noop_committed", "query_seq", "cluster_history",
        "last_ack", "aux_state", "aux_inited", "last_contact", "low_q",
        "specials", "last_ok_sent", "fresh_tail", "match_hint", "lat",
        "_clock", "fresh_anchor", "fresh_ts", "lease_contact",
    )

    def __init__(self, gid, name, cluster_name, members, self_slot, log, machine,
                 clock=None):
        from ra_tpu.runtime.clock import WALL

        self._clock = clock or WALL
        self.gid = gid
        self.name = name
        self.cluster_name = cluster_name
        self.members: List[ServerId] = list(members)
        self.self_slot = self_slot
        self.log: LogApi = log
        self.machine: Machine = machine
        self.machine_state = machine.init({"name": cluster_name})
        self.effective_machine_version = 0
        self.last_applied = 0
        self.role = C.R_FOLLOWER
        self.term = 0
        self.leader_slot = -1
        self.next_index = [1] * len(self.members)
        self.commit_sent = [0] * len(self.members)
        self.pending_replies: Dict[int, Any] = {}
        self.inbox: deque = deque()
        self.host_term_hint: Optional[Tuple[int, int]] = None
        self.election_ref = None
        # deferred AER ack awaiting WAL durability: (leader_sid, up_to_idx)
        self.pending_ack: Optional[Tuple[ServerId, int]] = None
        # inbound snapshot transfer state / outbound senders per peer
        self.snap_accept: Optional[Dict[str, Any]] = None
        self.snap_senders: Dict[ServerId, Any] = {}
        # host mirror of the device pre-vote round token (incremented in
        # lockstep with every set_roles(R_PRE_VOTE) scatter)
        self.pre_vote_token = 0
        # membership: voter status per slot ("voter" | ("nonvoter", tgt));
        # tombstoned slots hold None in self.members. One cluster change
        # in flight at a time (Raft one-at-a-time rule).
        self.voter_status: Dict[int, Any] = {
            i: "voter" for i in range(len(self.members))
        }
        self.cluster_change_permitted = True
        self.cluster_index = 0  # log index of the latest cluster change
        # consistent queries awaiting a leadership-confirmation quorum:
        # [{"qi": idx, "fn": fn, "fut": fut, "acks": set()}]
        self.pending_queries: List[Dict[str, Any]] = []
        self.machine_timers: Dict[Any, Any] = {}
        # a versioned container may delegate tick to its modules: check
        # the effective module as well as the container itself
        self.has_tick = (
            type(machine).tick is not Machine.tick
            or type(machine.which_module(machine.version())).tick
            is not Machine.tick
        )
        self.snap_floor = 0  # device-known snapshot floor (host mirror)
        # current-term-commit gate: a new leader may neither change
        # membership nor serve linearizable reads until its own noop has
        # committed (Raft read-index rule; reference: post_election
        # noop + cluster_change_permitted, src/ra_server.erl:4028-4064)
        self.noop_index = 0
        self.noop_committed = True  # groups start pre-election
        self.query_seq = 0
        # rollback snapshots for write-time cluster adoption: an
        # uncommitted change adopted from a dead leader must be undone
        # when a new leader truncates that suffix.
        # [(entry_index, members_copy, voter_status_copy), ...]
        self.cluster_history: List[Tuple[int, List, Dict[int, Any]]] = []
        # per-slot monotonic time of the last AER ack (leader-side);
        # drives the periodic resync of silent peers
        self.last_ack: Dict[int, float] = {}
        # aux machine state (initialized lazily on first aux message)
        self.aux_state: Any = None
        self.aux_inited = False
        # monotonic time of the last leader contact (AER / heartbeat /
        # snapshot chunk). The leader's silent-peer resync probe runs
        # every 2 ticks, so on this backend "no contact for several
        # ticks" is a reliable leaderless signal — the detector uses it
        # to retry elections after partition heals (a stalled pre-vote
        # or a deposed-leader cluster would otherwise wedge forever)
        self.last_contact = self._clock.monotonic()
        # buffered low-priority commands, drained in bounded slices
        # after normal traffic (reference: ra_ets_queue lane,
        # src/ra_server_proc.erl:507-530)
        self.low_q: deque = deque()
        # ascending log indexes holding non-USR commands (noops, cluster
        # changes). Tracked at append/write time so the apply loop can
        # take the batched fast path without scanning every entry; kept
        # exhaustive by the truncation/snapshot paths.
        self.specials: List[int] = []
        # last success ack shipped to a leader: (sid, term, last_index,
        # monotonic time). An identical re-ack within one tick interval
        # is suppressed — the pipeline's commit-sync AER round otherwise
        # triggers a reply that tells the leader nothing new. The time
        # bound keeps the leader's silent-peer resync probe honest: a
        # probe after 2 quiet ticks always gets a fresh ack.
        self.last_ok_sent: Optional[Tuple[ServerId, int, int, float]] = None
        # entries appended by THIS step's _handle_commands, passed
        # through to _send_aers so the steady-state AER build skips the
        # log re-read: (first_idx, prev_term, term, [Entry, ...]).
        # Valid only within one step; _send_aers always clears it.
        self.fresh_tail: Optional[Tuple[int, int, int, list]] = None
        # leader-side CONFIRMED replication point per slot (from AER
        # success replies) — the host mirror the pipeline window is
        # enforced against (next_index advances optimistically at send
        # time; match_hint only on acks, mirroring the reference's
        # match_index in its Next - Match <= ?MAX_PIPELINE_COUNT gate,
        # src/ra_server.erl:2308-2329)
        self.match_hint: List[int] = [0] * len(self.members)
        # in-flight commit-latency sample (obs.COMMIT_STAGES): at most
        # one per group, [idx, t_submit, t_append, t_durable, t_commit]
        # in monotonic ns. Only sampled groups (gid & lat_mask == 0)
        # for commands carrying a submit ts ever allocate one.
        self.lat: Optional[list] = None
        # staleness-bounded follower reads (docs/INTERNALS.md §20):
        # fresh_ts is the newest leader wall-clock stamp whose commit
        # point this replica has fully applied; fresh_anchor holds a
        # (leader_commit, commit_ts) pair still waiting for apply to
        # catch up. lease_contact is the leader-contact stamp backing
        # the stickiness promise (AER/heartbeat/snapshot only — NOT
        # the election-suspicion last_contact, which also restarts on
        # role changes and vote grants).
        self.fresh_anchor: Tuple[int, float] = (0, 0.0)
        self.fresh_ts = 0.0
        self.lease_contact = 0.0

    def slot_of(self, sid: ServerId) -> int:
        try:
            return self.members.index(sid)
        except ValueError:
            return -1

    def sid_of(self, slot: int) -> Optional[ServerId]:
        if 0 <= slot < len(self.members):
            return self.members[slot]
        return None


class BatchCoordinator:
    """Hosts up to ``capacity`` groups on one node, device-stepped."""

    def __init__(
        self,
        node_name: str,
        capacity: int = 1024,
        num_peers: int = 3,
        suffix_k: int = 32,
        nodes: Optional[NodeRegistry] = None,
        aer_batch_size: int = 128,
        election_timeout_s: float = 0.15,
        detector_poll_s: float = 0.1,
        meta=None,
        idle_sleep_s: float = 0.0005,
        tick_interval_s: float = 1.0,
        send_msg_cb=None,
        mesh=None,
        active_set: str = "auto",
        max_pipeline_count: int = 4096,
        max_command_backlog: int = 4096,
        command_deadline_s: float = 5.0,
        pipeline: bool = True,
        rings: bool = True,
        ingress_ring_slots: int = 8192,
        egress_async: bool = True,
        native: str = "auto",
        clock=None,
        lease: bool = False,
        lease_safety_factor: float = 0.8,
        lease_drift_epsilon_s: float = 0.002,
    ):
        from ra_tpu.runtime.clock import WALL

        # behavioral clock seam (docs/INTERNALS.md §19): election/resync
        # windows, contact stamps and the tick cadence read this clock;
        # the monotonic_ns() latency-histogram stamps below intentionally
        # stay on the wall clock (they measure real host time and the
        # simulation plane never drives this backend)
        self.clock = clock or WALL
        self.name = node_name
        self.capacity = capacity
        self.P = num_peers
        self.aer_batch_size = aer_batch_size
        self.election_timeout_s = election_timeout_s
        self.meta = meta
        self.idle_sleep_s = idle_sleep_s
        self.tick_interval_s = tick_interval_s
        self.send_msg_cb = send_msg_cb
        # flow control: per-peer AER pipeline window (reference:
        # ?MAX_PIPELINE_COUNT, src/ra_server.hrl:8), per-group client
        # admission window against apply progress, and the command-lane
        # watchdog deadline (accepted command with no commit progress
        # for this long -> detected wedge, recovery, bounded failure)
        self.max_pipeline_count = max_pipeline_count
        self.max_command_backlog = max_command_backlog
        self.command_deadline_s = command_deadline_s
        from ra_tpu import counters as _counters
        from ra_tpu import health as _health
        from ra_tpu import obs as _obs
        from ra_tpu.li import LeakyIntegrator

        self.counters = _counters.new(
            ("coordinator", node_name), _counters.COORDINATOR_FIELDS
        )
        # wave-phase + commit-stage histograms (docs/INTERNALS.md §13)
        # and the flight recorder; per-node histogram names so batch-
        # and actor-backed members on one node share a commit family
        self._wave_h = _obs.wave_hists(node_name)
        self._commit_h = _obs.commit_hists(node_name)
        self._obs_rec = _obs.flight_recorder()
        # wave-phase trace spans land here when tracing is enabled
        # (profile_wave --trace / api.dump_trace); one attribute check
        # per step while disabled
        self._trace = _obs.trace_buffer()
        # per-group health scanner (docs/INTERNALS.md §14): fed once
        # per tick from the detector thread with ONE device fetch over
        # the existing mirrors — never from the step loop
        self._health = _health.register(
            node_name, backend="tpu_batch", capacity=max(64, capacity)
        )
        # storage-pressure plane (docs/INTERNALS.md §21): the harness /
        # embedding application drives enter/exit from its WAL-failure
        # classification and watermark accounting; the coordinator
        # consults it at admission and when granting snapshot credits
        from ra_tpu.pressure import StoragePressure

        self.pressure = StoragePressure(node_name)
        self.snapshot_credit_window = 4
        self._hslots: List[int] = []  # gid -> scanner slot
        # commit-latency sampling mask: groups with gid & mask == 0 are
        # eligible (bounds hot-path cost to ~1/64 of groups); _lat_gids
        # tracks the gids with a sample in flight so per-step sweeps
        # (the durable-watermark check) cost nothing when none is
        self._lat_mask = 63
        self._lat_gids: set = set()
        # aggregate commit-rate gauge over all groups (the batch-backend
        # analog of the per-proc ra_li integrator), sampled per tick
        self._commit_li = LeakyIntegrator()
        self._commit_li_prev: Optional[Tuple[float, int]] = None
        # activity-scaled stepping: "auto" runs the fused step over a
        # compact gather of just the groups with pending device work
        # whenever they number at most capacity/4 (power-of-two padded
        # sub-batches), falling back to the full-width step at
        # saturation; "always"/"never" pin a path (tests/bench). Step
        # cost then scales with ACTIVITY, not capacity — a lone commit
        # round trip at 10k-group capacity no longer pays ~10 full-width
        # steps (the reference's per-group process wakes only on
        # messages: src/ra_server_proc.erl:457-530).
        if active_set not in ("auto", "always", "never"):
            raise ValueError(f"unknown active_set mode {active_set!r}")
        self.active_set = active_set

        self.state = C.make_group_state(capacity, num_peers, suffix_k)
        # groups not yet registered must never act: mark inactive
        self.state = self.state._replace(
            active=jnp.zeros((capacity, num_peers), dtype=jnp.bool_),
            voting=jnp.zeros((capacity, num_peers), dtype=jnp.bool_),
        )
        # multi-chip: shard the GROUP axis of all consensus state over
        # the mesh (replica axis P rides along unsharded). Every group's
        # decision math is independent, so the fused step partitions
        # with zero cross-device communication; host scatters address
        # groups by id and GSPMD routes them. The state is re-pinned to
        # the sharding before each fused step (host-side single-row
        # updates may produce replicated layouts).
        self._shard_state = self._shard_mbox = None
        if mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec

            n_dev = mesh.devices.size
            if capacity % n_dev:
                raise ValueError(
                    f"capacity {capacity} not divisible by mesh size {n_dev}"
                )
            # the group axis shards over EVERY mesh axis — a 2-D mesh
            # (e.g. ici x dcn) still engages all devices instead of
            # silently replicating over the unnamed axes
            axes = tuple(mesh.axis_names)
            self._shard_state = NamedSharding(mesh, PartitionSpec(axes))
            self._shard_mbox = NamedSharding(mesh, PartitionSpec(None, axes))
            self.state = jax.device_put(self.state, self._shard_state)
        self.groups: List[Optional[GroupHost]] = [None] * capacity
        self.by_name: Dict[str, GroupHost] = {}
        self.n_groups = 0

        # async command plane (docs/INTERNALS.md §16): per-producer-
        # thread lock-free SPSC ingress rings, drained in one batched
        # multi-lane pass by the step thread. No sender ever contends
        # with the step loop; the step thread blocks on _wake (an
        # Event set by every publish / WAL notify / egress realisation)
        # instead of 50 ms timed polls. rings=False swaps in the
        # lock+deque control implementation (the --rings=off A/B).
        from ra_tpu.rings import IngressRings, LockedLanes, WaitGate

        self._wake = threading.Event()
        ring_cls = IngressRings if rings else LockedLanes
        self.rings = rings
        self._rings = ring_cls(lane_slots=ingress_ring_slots,
                               wake=self._wake)
        # ring-full backpressure gate: opened on every drain that freed
        # space; ring-full-rejected clients wait on it instead of
        # sleeping (the ingress analog of the per-group admission gate)
        self._ring_gate = WaitGate()
        # admission-window gate: opened whenever apply progress releases
        # window room; admission-rejected clients park a waiter on it
        # (api.process_command) instead of a fixed 10 ms sleep poll
        self._adm_gate = WaitGate()
        # idents of the threads that DRAIN the rings (step + egress loop
        # threads, plus whichever thread is inside a cooperative step_*
        # call): a full-ring publish from one of these must divert to
        # _internal_q — gate-waiting would deadlock on itself
        self._drainer_idents: set = set()
        # reusable drain scratch (step-thread only)
        self._drain_buf: List = []
        # must-deliver self-publishes from the coordinator's own step/
        # egress threads (machine Append/Aux effects): a blocking ring
        # publish from the drainer thread would deadlock, so they ride
        # this state-lock-guarded queue into the next drain instead
        self._internal_q: deque = deque()
        # must-deliver overflow from FOREIGN threads (peer coordinator
        # step/egress/WAL threads, detector timers) whose publish hit a
        # full lane: never dropped, and never gate-waited either — a
        # peer's drainer thread parked on OUR ring gate while we park
        # on ITS gate is a distributed deadlock. Tiny leaf lock (never
        # nested inside any other), folded first by _drain_classify so
        # overflow items keep their arrival seniority.
        self._overflow_q: deque = deque()
        self._overflow_codes: deque = deque()  # RC_* sidecar, in step
        self._overflow_lock = threading.Lock()
        # native hot-loop runtime switches (docs/INTERNALS.md §18):
        # requested paths resolved against what actually loaded. Every
        # native path keeps the byte-identical Python fallback and
        # routes around itself while ANY failpoint is armed, so the
        # nemesis plane always exercises the Python fault seams.
        paths = parse_native(native)
        eps = _native.entry_points() if paths else {}
        self.native = native
        self._nat_pack = "pack" in paths and eps.get("pack", False)
        self._nat_classify = "classify" in paths and eps.get("classify", False)
        self._nat_egress = "egress" in paths and eps.get("egress", False)
        self._drain_codes = bytearray()  # classify sidecar scratch
        self._low_dirty: set = set()  # gids with buffered low-priority cmds
        # staged device scatters, coalesced ACROSS passes (the host half
        # of the double-buffered staging): appended runs per gid as
        # [[lo, hi, term], ...] chronological, durable watermarks as
        # gid -> max idx. Ingest-only passes fold straight into these;
        # the next dispatching pass consumes them with zero re-merging.
        self._staged_app: Dict[int, List[List[int]]] = {}
        self._staged_written: Dict[int, int] = {}
        # pre-zeroed full-width mailbox buffer staged in the pipeline
        # overlap window (dispatch packs into it with no take/zero cost)
        self._spare_mbox: Optional[np.ndarray] = None
        # prezero only while full-width steps are the live shape (the
        # active-set sub path zeroes tiny buffers — not worth staging)
        self._prezero_useful = False
        # dedicated egress sender thread (started pipelined loop only):
        # AER/ack fan-out hands (node, msgs) batches to a bounded ring
        # consumed off the step loop; overflow falls back to inline send
        self._egress_async = egress_async
        self._egress_on = False
        self._egress_wake = threading.Event()
        self._egress_rings = ring_cls(lane_slots=4096,
                                      wake=self._egress_wake)
        self._sender_thread: Optional[threading.Thread] = None
        # clock-bound leader leases, vectorized over the group axis
        # (docs/INTERNALS.md §20): per-slot oldest-outstanding-send
        # stamps and credited ack bases, folded into a (G,) expiry
        # column by _lease_refresh over just the dirty gids. Off by
        # default — leader stickiness changes election behavior.
        from ra_tpu.lease import LeaseConfig

        self.lease_cfg = LeaseConfig(
            enabled=lease, election_timeout_s=election_timeout_s,
            safety_factor=lease_safety_factor,
            drift_epsilon_s=lease_drift_epsilon_s,
        )
        self._lease_sent = np.zeros((capacity, num_peers), np.float64)
        self._lease_basis = np.zeros((capacity, num_peers), np.float64)
        self._lease_expiry = np.zeros(capacity, np.float64)
        self._lease_voters = np.zeros((capacity, num_peers), bool)
        self._lease_quorum = np.zeros(capacity, np.int64)
        self._lease_self = np.zeros(capacity, np.int64)
        self._lease_renew_t = np.zeros(capacity, np.float64)
        self._lease_dirty: set = set()
        self._stale_h = None  # lazy follower_read_staleness histogram
        # role transitions queued by rare paths, applied as ONE scatter
        # at the start of the next step (an election storm over many
        # groups must not pay one jitted scatter per group)
        self._pending_roles: List[Tuple[int, int]] = []
        self._hot: set = set()  # gids with queued inbox msgs / term hints
        self._applied_np = np.zeros(capacity, np.int64)  # last_applied mirror
        # mailbox pack buffers, double-buffered (docs/INTERNALS.md §15):
        # a build hands out a zero-copy jnp view of one buffer; the
        # buffer returns to the pool only after that step's egress sync
        # (np.asarray) proves the device consumed the view. The
        # sequential loop cycles one buffer; the pipelined loop keeps
        # one in flight while the next step packs the other — the pool
        # is bounded by the single-outstanding-ticket cap.
        self._mbox_pool: List[np.ndarray] = []
        # guards self.state (donated buffers!) between the step thread and
        # add_group callers
        self._state_lock = threading.Lock()

        self.registry = nodes or node_registry()
        self.transport = InProcTransport(node_name, self.registry)
        self.running = True
        self.registry.register(node_name, self)
        self.steps = 0
        self.sub_steps = 0  # steps taken on the active-set (sub) path
        self.msgs_processed = 0

        # pipelined wave loop (docs/INTERNALS.md §15): the threaded run
        # loop splits each step into host staging (ingress drain + pack
        # + device dispatch, step thread) and realisation (egress sync
        # + process + AER fan-out, egress thread), overlapping step
        # N+1's staging with step N's device compute / egress sync.
        # ``step_once`` (tests, cooperative bench driver) is always the
        # sequential two-halves-inline form; callers must not mix it
        # with a STARTED pipelined loop (ticket order would invert).
        self.pipeline = pipeline
        self._pipe_cv = threading.Condition()
        self._pipe_q: deque = deque()
        self._pipe_inflight = 0  # tickets dispatched but not finished
        self._egress_thread: Optional[threading.Thread] = None
        # work drained by ingest-only passes (a ticket still in
        # flight): rares park here until the next dispatching pass
        # picks them up (appended/written runs go straight to the
        # staged scatter dicts, their canonical form; AER fan-out
        # never parks — ingest passes ship it immediately)
        self._pending_rare: List[Tuple] = []
        # outstanding ticket of the cooperative pipelined driver form
        self._coop_ticket: Optional[BatchCoordinator._StepTicket] = None
        self._step_thread = threading.Thread(
            target=self._run, name=f"ra-batch-{node_name}", daemon=True
        )
        self._node_status: Dict[str, bool] = {}
        self._detector_poll_s = detector_poll_s
        self._detector = threading.Thread(
            target=self._detect_loop, name=f"ra-batch-det-{node_name}", daemon=True
        )
        self._started = False

    # -- node-registry interface (same duck type as RaNode) ---------------

    @property
    def procs(self) -> Dict[str, Any]:
        return self.by_name

    # ring item tags: generic message | single command | bulk command
    # fan-out | per-node batch of (name, from_sid, msg) triples
    _R_MSG, _R_CMD, _R_CMDS, _R_BATCH = 0, 1, 2, 3

    def deliver(self, to: ServerId, msg: Any, from_sid: Optional[ServerId]) -> bool:
        """Lock-free ingress: publish onto this thread's SPSC lane. A
        full lane backpressures explicitly (docs/INTERNALS.md §16):
        client commands owing a reply reject through the admission
        path with a gate waiter, ack-free commands drop counted
        (at-most-once contract), lossy peer protocol traffic drops
        counted (transport contract), and must-deliver control
        messages (log events, internal commands, queries) ride the
        overflow queue — never a silent drop, and never a block (the
        caller may be a peer coordinator's drainer thread; parking it
        on our gate while we park on its gate would deadlock)."""
        name = to[0]
        if name not in self.by_name:
            return False
        if type(msg) is Command:
            # the RC_* class code rides a sidecar slot next to the item
            # (the flat tagged-item layout): the priority split is paid
            # once at the producer so the native drain-classify never
            # touches the object
            code = RC_CMD_LOW if msg.priority == "low" else RC_CMD
            if msg.internal and self._overflow_q:
                # older must-deliver work is parked on the overflow
                # queue: a lane publish would overtake it (the queue
                # folds after the lane drain) — keep arrival order
                return self._publish_overflow((self._R_CMD, name, msg), code)
            if self._rings.publish((self._R_CMD, name, msg), code):
                return True
            return self._ring_full_cmd(name, msg, code)
        if type(msg) not in LOSSY_PROTOCOL_TYPES and self._overflow_q:
            return self._publish_overflow(
                (self._R_MSG, name, from_sid, msg), RC_MSG)
        if self._rings.publish((self._R_MSG, name, from_sid, msg), RC_MSG):
            return True
        self.counters.incr("ingress_ring_full")
        if type(msg) in LOSSY_PROTOCOL_TYPES:
            return False  # lossy peer traffic: counted drop
        return self._publish_overflow((self._R_MSG, name, from_sid, msg), RC_MSG)

    def _ring_full_cmd(self, name: str, msg: Command,
                       code: int = RC_CMD) -> bool:
        self.counters.incr("ingress_ring_full")
        if msg.internal:
            # machine-internal must-deliver (timer fires, Append
            # effects): overflow queue, never shed
            return self._publish_overflow((self._R_CMD, name, msg), code)
        if msg.from_ref is not None:
            # explicit backpressure: the command was NEVER enqueued, so
            # a retry is exactly-once safe; the gate waiter wakes the
            # client on the next drain instead of a sleep loop
            self.counters.incr("commands_rejected")
            self._reply(
                msg.from_ref,
                REJECT_OVERLOADED + (self._ring_gate.waiter(),),
            )
            return True
        self.counters.incr("commands_dropped_overload")
        return False

    def _publish_blocking(self, item, code: int = RC_MSG) -> bool:
        """Bounded-wait publish for must-deliver BULK CLIENT traffic
        (deliver_commands / deliver_many — the producers there are
        client/driver threads, where waiting IS the backpressure): wait
        on the ring gate (opened by every space-freeing drain) and
        retry. A drainer thread (step/egress loop, or a cooperative
        step_* call) must never gate-wait on itself — its must-deliver
        traffic rides ``_internal_q`` into its own next drain instead.
        Never used for traffic that may originate on ANOTHER
        coordinator's drainer thread (see _publish_overflow)."""
        if threading.get_ident() in self._drainer_idents:
            # caller holds the state lock (every drainer publish comes
            # from inside a locked stage/realise half)
            self._internal_q.append(item)
            return True
        for _ in range(4):
            if not self.running:
                return False
            if self._rings.publish(item, code):
                return True
            self._ring_gate.waiter().wait(0.05)
        # still full after the bounded wait: in cooperative (non-
        # started) mode the only drainer may be THIS thread between
        # step_* calls — spinning here would livelock until an external
        # stop(). Fall back to the overflow queue: delivered on the
        # next drain, never spun on, never shed.
        return self._publish_overflow(item, code)

    def _publish_overflow(self, item, code: int = RC_MSG) -> bool:
        """Non-blocking must-deliver fallback for a full lane: park the
        item on the overflow queue the next _drain_classify folds FIRST
        (arrival seniority kept). Used for traffic whose producer may
        be a peer coordinator's drainer thread or a timer — blocking
        those risks distributed deadlock, dropping violates the
        must-deliver contract. Unbounded, but only ever fed by the
        low-rate control/ack trickle that outlived a full lane."""
        if threading.get_ident() in self._drainer_idents:
            self._internal_q.append(item)
            return True
        with self._overflow_lock:
            self._overflow_q.append(item)
            self._overflow_codes.append(code)
        self.counters.incr("ingress_overflow_msgs")
        if not self._wake.is_set():
            self._wake.set()
        return True

    def _deliver_internal(self, name: str, msg) -> None:
        """Self-delivery from the step/egress threads (machine effects
        re-entering the command queue). Caller holds the state lock;
        the queue is drained by the next _drain_and_dispatch."""
        if type(msg) is Command:
            self._internal_q.append((self._R_CMD, name, msg))
        else:
            self._internal_q.append((self._R_MSG, name, None, msg))

    def deliver_commands(self, names, cmd: Command) -> None:
        """Bulk ingress for ONE command fanned to many groups (the
        pipelined-bench shape: one wave = the same no-op command to
        every group leader). One ring slot for the whole wave; the
        per-group regrouping runs at drain time on the step thread,
        off every client lock. ``names`` must not be mutated after the
        call. Blocks (gate-paced) when the lane is full — the bulk
        producer is the natural place to absorb backpressure."""
        code = RC_CMDS_LOW if cmd.priority == "low" else RC_CMDS
        self._publish_bulk((self._R_CMDS, names, cmd), code)

    def wal_notify(self, uid: str, evt) -> None:
        """Log-event entry point for WAL / segment-writer notify
        callbacks. ``written`` events take the decoupled durable-ack
        path — handled on the CALLING (WAL writer) thread so a durable
        batch advances watermarks, releases deferred AER acks, and
        queues the device written-scatter without waiting for a step-
        loop pass. Everything else rides normal ingress ordering."""
        if type(evt) is tuple and evt and evt[0] == "written":
            self.wal_notify_many([(uid, evt)])
        else:
            self.deliver((uid, self.name), ("log_event", evt), None)

    def wal_notify_many(self, items) -> None:
        """Bulk durable-watermark delivery from one WAL flush (wire as
        ``wal.notify_many``): one state-lock round for the whole
        batch's written events. The durable-ack decoupling invariant
        (docs/INTERNALS.md §15): everything this touches — the log's
        written watermark, ``pending_ack``, ``last_ok_sent``, the
        pending-scatter queue — is guarded by the state lock, and the
        ack it emits is exactly the ack the step-loop path would have
        emitted one wave later."""
        route_out: Dict[str, List] = {}
        staged = False
        with self._state_lock:
            by_get = self.by_name.get
            sw = self._staged_written
            for uid, evt in items:
                g = by_get(uid)
                if g is None:
                    continue
                if not (type(evt) is tuple and evt and evt[0] == "written"):
                    self.deliver((uid, self.name), ("log_event", evt), None)
                    continue
                g.log.handle_event(evt)
                wi, wt = g.log.last_written()
                # the device learns the durable watermark at the next
                # dispatch (the staged written scatter drives the
                # quorum scan)
                if sw.get(g.gid, 0) < wi:
                    sw[g.gid] = wi
                staged = True
                if g.pending_ack is not None and wi >= g.pending_ack[1]:
                    leader_sid, cover = g.pending_ack
                    g.pending_ack = None
                    ack = min(wi, cover)
                    at = g.log.fetch_term(ack)
                    out = route_out.get(leader_sid[1])
                    if out is None:
                        route_out[leader_sid[1]] = out = []
                    out.append(
                        (leader_sid,
                         AppendEntriesReply(g.term, True, ack + 1, ack,
                                            at if at is not None else wt),
                         (g.name, self.name))
                    )
        for node_name, msgs in route_out.items():
            self._send_batch(node_name, msgs)
        # wake the step thread only when the staged watermark is
        # actionable NOW: with a ticket in flight the idle predicate
        # ignores staged work (an ingest-only pass cannot scatter it),
        # so an unconditional set here woke the loop for nothing — the
        # spurious wakeups BENCH_THREADED recorded. When the in-flight
        # ticket realises, the egress thread's own _have_work check
        # sees the staged state and wakes the loop (its inflight
        # decrement precedes that check, so no release is ever missed).
        if staged and self._have_work() and not self._wake.is_set():
            self._wake.set()

    def deliver_many(self, msgs) -> None:
        """Batch ingress: ONE ring slot for many ``(to_sid, msg,
        from_sid)`` triples (unknown group names are dropped at drain,
        as in ``deliver``). Blocks gate-paced when the lane is full."""
        triples = [(to[0], frm, m) for to, m, frm in msgs]
        self._publish_bulk((self._R_BATCH, triples), RC_BATCH)

    def _publish_bulk(self, item, code: int = RC_MSG) -> None:
        """Bulk client publish: keep arrival order (never overtake
        parked overflow work — the overflow queue folds after the lane
        drain) WITHOUT giving up pacing. While overflow is pending,
        gate-wait a bounded window for the drain to clear it; only if
        it persists does the wave park on the overflow queue too —
        producers stay paced at the gate cadence instead of appending
        unbounded waves at line rate (the failure mode an unconditional
        divert would reintroduce under exactly the overload the bounded
        rings exist for)."""
        if self._overflow_q:
            ident = threading.get_ident()
            for _ in range(4):
                if ident in self._drainer_idents or not self.running:
                    break
                self._ring_gate.waiter().wait(0.05)
                if not self._overflow_q:
                    break
            if self._overflow_q:
                self._publish_overflow(item, code)
                return
        if not self._rings.publish(item, code):
            self.counters.incr("ingress_ring_full")
            self._publish_blocking(item, code)

    def ingest_batch(self, triples) -> int:
        """Peer-coordinator bulk ingress (the _send_batch fast path):
        pre-normalized ``(name, from_sid, msg)`` triples, one ring slot
        per per-node batch. On a full lane the batch SPLITS by the
        backpressure table: lossy protocol traffic is shed (returns the
        shed count for the sender's drop accounting), everything else —
        snapshot chunks/acks, TimeoutNow, client commands, log events —
        rides the overflow queue (must-deliver: a batch-level drop
        would stall a snapshot transfer for its whole ack timeout and
        silently swallow leadership transfers). Returns the number of
        messages dropped (0 = everything delivered)."""
        if not self._overflow_q:
            # (while older must-deliver work is parked on the overflow
            # queue, a lane publish would overtake it — divert below)
            if self._rings.publish((self._R_BATCH, triples), RC_BATCH):
                return 0
            self.counters.incr("ingress_ring_full")
        must = [t for t in triples if type(t[2]) not in LOSSY_PROTOCOL_TYPES]
        if must:
            self._publish_overflow((self._R_BATCH, must), RC_BATCH)
        if len(must) == len(triples):
            return 0
        # lossy remainder is order-insensitive (sender-retried): it may
        # still ride the lane; shed only what the lane cannot take
        lossy = [t for t in triples if type(t[2]) in LOSSY_PROTOCOL_TYPES]
        if self._rings.publish((self._R_BATCH, lossy), RC_BATCH):
            return 0
        return len(lossy)

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if not self._started:
            self._started = True
            self._step_thread.start()
            self._detector.start()

    def stop(self) -> None:
        self.running = False
        self._egress_on = False  # late sends go inline, not to a dead ring
        if self._started:
            self._wake.set()
            self._egress_wake.set()
            with self._pipe_cv:
                self._pipe_cv.notify_all()
            self._step_thread.join(timeout=5)
            if self._egress_thread is not None:
                self._egress_thread.join(timeout=5)
            if self._sender_thread is not None:
                self._sender_thread.join(timeout=5)
                # a publisher that read _egress_on before stop() flipped
                # it can land a batch AFTER the sender's final drain:
                # ship the residue inline so queued acks still leave
                out: List = []
                if self._egress_rings.drain(out):
                    for node_name, msgs in out:
                        try:
                            self._send_batch_inline(node_name, msgs)
                        except Exception:  # noqa: BLE001 — best effort
                            pass
            # join the detector too: a straggling health scan sitting in
            # a device fetch at interpreter exit can crash the XLA
            # runtime's C++ teardown
            self._detector.join(timeout=5)
        from ra_tpu import counters as _counters
        from ra_tpu import health as _health

        _counters.delete(("coordinator", self.name))
        _health.unregister(self.name)
        self.pressure.delete()
        for g in self.groups:
            if g is not None:
                for t in g.machine_timers.values():
                    t.cancel()
                g.machine_timers.clear()
        self.registry.unregister(self.name)

    def add_group(
        self,
        name: str,
        cluster_name: str,
        members: List[ServerId],
        machine: Machine,
        log: Optional[LogApi] = None,
    ) -> ServerId:
        return self.add_groups([(name, cluster_name, members, machine, log)])[0]

    def add_groups(self, specs) -> List[ServerId]:
        """Bulk group registration: ONE set of device scatters for the
        whole batch. ``specs`` rows are ``(name, cluster_name, members,
        machine[, log])``. Registering 10k groups one scatter-set at a
        time was minutes of un-jitted dispatch; this is 5 scatters
        total."""
        specs = list(specs)
        # validate EVERYTHING before mutating: a mid-batch error must
        # not leave half-registered groups with inactive device rows
        if self.n_groups + len(specs) > self.capacity:
            raise RuntimeError("coordinator at capacity")
        for spec in specs:
            name, _cl, members = spec[0], spec[1], spec[2]
            if len(members) > self.P:
                raise ValueError(
                    f"group has {len(members)} members; capacity is {self.P}"
                )
            if (name, self.name) not in members:
                raise ValueError(
                    "members must include this coordinator's server id"
                )
        sids: List[ServerId] = []
        hosts: List[Tuple[str, GroupHost]] = []
        rows: List[Tuple[int, np.ndarray, int, int, int]] = []
        for k, spec in enumerate(specs):
            name, cluster_name, members, machine = spec[:4]
            log = spec[4] if len(spec) > 4 else None
            sid = (name, self.name)
            gid = self.n_groups + k
            g = GroupHost(
                gid, name, cluster_name, members, members.index(sid),
                log or MemoryLog(auto_written=True), machine,
                clock=self.clock,
            )
            # restart safety: reload the durable term/vote so this
            # member cannot re-vote in a term it already voted in
            term0, voted_slot = 0, -1
            if self.meta is not None:
                uid = f"{cluster_name}_{name}"
                term0 = int(self.meta.fetch(uid, "current_term", 0))
                voted_sid = self.meta.fetch(uid, "voted_for", None)
                if voted_sid is not None:
                    voted_slot = g.slot_of(tuple(voted_sid))
                    if voted_slot < 0:
                        # we voted this term for a sid not in the
                        # current member table (e.g. removed since):
                        # seed an out-of-range slot so free_to_vote
                        # stays False for the rest of the term — never
                        # degrade to "never voted" (-1), which would
                        # allow a second grant
                        voted_slot = self.P
                g.term = term0
            active = np.zeros(self.P, dtype=bool)
            active[: len(members)] = True
            li, lt = g.log.last_index_term()
            snap0 = g.log.snapshot_index_term()
            sidx, sterm = snap0 if snap0 else (0, 0)
            if snap0 is not None:
                # cold restart onto a snapshot-bearing log: entries at or
                # below the floor are gone, so the machine state MUST be
                # restored from the capture (replay-from-1 would raise on
                # the missing prefix); apply resumes above the floor
                got = g.log.read_snapshot()
                if got is not None:
                    meta0, state_obj = got
                    g.machine_state = state_obj
                    g.effective_machine_version = meta0.machine_version
                    g.last_applied = meta0.index
                    g.snap_floor = meta0.index
                    self._applied_np[gid] = meta0.index
            fi = sidx + 1
            if li >= fi:
                # a pre-populated log (cold restart with a persistent
                # log): seed the specials index so the batched apply
                # fast path stays sound
                g.specials = [
                    e.index for e in g.log.fetch_range(fi, li)
                    if type(e.cmd) is not Command or e.cmd.kind != USR
                ]
            rows.append((gid, active, g.self_slot, term0, voted_slot,
                         li, lt, sidx, sterm))
            hosts.append((name, g))
            sids.append(sid)
            if self.lease_cfg.enabled:
                self._lease_sync(g)
        if rows:
            gids = jnp.asarray(np.array([r[0] for r in rows], np.int32))
            act = jnp.asarray(np.stack([r[1] for r in rows]))
            slots = jnp.asarray(np.array([r[2] for r in rows], np.int32))
            terms = jnp.asarray(np.array([r[3] for r in rows], np.int32))
            voted = jnp.asarray(np.array([r[4] for r in rows], np.int32))
            lis_np = np.array([r[5] for r in rows], np.int32)
            lts_np = np.array([r[6] for r in rows], np.int32)
            sidx_np = np.array([r[7] for r in rows], np.int32)
            sterm_np = np.array([r[8] for r in rows], np.int32)
            lis = jnp.asarray(lis_np)
            lts = jnp.asarray(lts_np)
            sidxs = jnp.asarray(sidx_np)
            sterms = jnp.asarray(sterm_np)
            # recovered tails: the device learns last/written/snapshot
            # rows, with the whole (snap, li] interval marked
            # term-unknown — prev-term lookups fall back to the host log
            # (needs_host) until traffic reconciles the ring. Everything
            # already on disk is durable, so written == last.
            unk_lo = jnp.asarray(
                np.where(lis_np > sidx_np, sidx_np + 1, 1).astype(np.int32)
            )
            unk_hi = jnp.asarray(
                np.where(lis_np > sidx_np, lis_np, 0).astype(np.int32)
            )
            with self._state_lock:
                self.state = self.state._replace(
                    active=self.state.active.at[gids].set(act),
                    voting=self.state.voting.at[gids].set(act),
                    self_slot=self.state.self_slot.at[gids].set(slots),
                    current_term=self.state.current_term.at[gids].set(terms),
                    voted_for=self.state.voted_for.at[gids].set(voted),
                    last_index=self.state.last_index.at[gids].set(lis),
                    last_term=self.state.last_term.at[gids].set(lts),
                    written_index=self.state.written_index.at[gids].set(lis),
                    commit_index=self.state.commit_index.at[gids].set(sidxs),
                    last_applied=self.state.last_applied.at[gids].set(sidxs),
                    snapshot_index=self.state.snapshot_index.at[gids].set(sidxs),
                    snapshot_term=self.state.snapshot_term.at[gids].set(sterms),
                    unknown_lo=self.state.unknown_lo.at[gids].set(unk_lo),
                    unknown_hi=self.state.unknown_hi.at[gids].set(unk_hi),
                )
        # publish only after the device rows are live: deliver() must
        # never accept traffic for a group with inactive rows
        for name, g in hosts:
            self.groups[g.gid] = g
            self.by_name[name] = g
            self._hslots.append(self._health.ensure(name, g.cluster_name))
        self.n_groups += len(hosts)
        return sids

    # -- the step loop -----------------------------------------------------

    def _have_work(self) -> bool:
        """Is there anything for a step pass to do right now? Fresh
        ingress (ring items, internal self-deliveries, buffered lows)
        always counts. Deferred device work — hot gids, staged
        scatters, queued roles, parked rares — counts only with no
        ticket in flight: an ingest-only pass cannot act on it, so
        waiting on it mid-flight would busy-spin until realisation
        wakes us (its inflight decrement precedes the wake set, so the
        post-wake re-check sees the dispatchable state)."""
        if (
            self._rings.pending() or self._internal_q
            or self._overflow_q or self._low_dirty
        ):
            return True
        if self._pipe_inflight > 0:
            return False
        return bool(
            self._hot or self._staged_app or self._staged_written
            or self._pending_roles or self._pending_rare
        )

    def _idle_wait(self) -> None:
        """Event-driven idle block (docs/INTERNALS.md §16): clear the
        wake event, re-check for work (a publish between the last drain
        and the clear must not be lost — publish stores the item BEFORE
        setting the event, so either the re-check sees the item or the
        wait sees the set), then block until a ring publish, WAL
        notify, egress realisation, timer delivery, or stop wakes us.
        No timed polls: an idle coordinator consumes zero CPU, and the
        ``step_spurious_wakeups`` counter proves every wake found
        work."""
        wake = self._wake
        wake.clear()
        if self._have_work() or not self.running:
            return
        wake.wait()
        self.counters.incr("step_wakeups")
        if self.running and not self._have_work():
            self.counters.incr("step_spurious_wakeups")

    def _run(self) -> None:
        self._drainer_idents.add(threading.get_ident())
        if self.pipeline:
            self._run_pipelined()
            return
        while self.running:
            worked = self.step_once()
            if not worked:
                self._idle_wait()

    def _run_pipelined(self) -> None:
        """Two-stage pipelined wave loop (docs/INTERNALS.md §15). This
        thread owns host STAGING: ingress drain, command append + WAL
        handoff, queued scatters, mailbox pack, async device dispatch.
        The egress thread owns step REALISATION: egress host sync,
        egress processing (applies, acks, role changes), rare messages,
        AER fan-out. Every touch of host group state happens under
        ``_state_lock`` on either thread; the overlap window is the
        device compute + egress sync wait, which runs with no lock
        held — step N+1 stages and dispatches inside it. At most ONE
        ticket is in flight past the one being realised (the double
        buffer bound); tickets are realised strictly in dispatch order
        (egress fields are absolute per-step snapshots — out-of-order
        realisation would regress role/term mirrors)."""
        self._egress_thread = threading.Thread(
            target=self._egress_loop, name=f"ra-batch-eg-{self.name}",
            daemon=True,
        )
        self._egress_thread.start()
        if self._egress_async:
            self._sender_thread = threading.Thread(
                target=self._sender_loop, name=f"ra-batch-snd-{self.name}",
                daemon=True,
            )
            self._sender_thread.start()
            self._egress_on = True
        cv = self._pipe_cv
        while self.running:
            t0 = time.perf_counter_ns()
            # dispatch only with NO ticket in flight (the double-buffer
            # bound): while one is being realised, passes are INGEST-
            # ONLY — ingress keeps draining and commands keep reaching
            # the logs/WAL (coalescing the next step) without splitting
            # the wave into many small device steps. _pipe_inflight is
            # only incremented by this thread, so a lock-free read of 0
            # is exact (a stale >0 just delays dispatch by one pass).
            inflight = self._pipe_inflight > 0
            # classify OUTSIDE the state lock (docs/INTERNALS.md §16):
            # the WAL writer's wal_notify_many must never wait behind
            # the O(items) classification of a deep burst
            pre = self._drain_classify()
            with self._state_lock:
                ticket = self._drain_and_dispatch(
                    dispatch=not inflight, pre=pre
                )
            if inflight:
                # host staging done while the previous step's device
                # compute / egress realisation / WAL handoff were in
                # flight — the overlap the pipeline exists for
                dt = time.perf_counter_ns() - t0
                if dt > 20_000:  # ignore empty probe passes
                    self.counters.incr("pipeline_overlap_ns", dt)
                # double-buffered staging: pre-zero the NEXT dispatch's
                # full-width mailbox inside the overlap window, so the
                # dispatching pass packs into a ready spare with zero
                # take/zero cost on its critical path
                if self._prezero_useful and self._spare_mbox is None:
                    with self._state_lock:
                        buf = None
                        pool = self._mbox_pool
                        for k, b in enumerate(pool):
                            if b.shape[1] == self.capacity:
                                buf = b
                                del pool[k]
                                break
                    if buf is None:
                        buf = np.zeros(
                            (self._NROWS, self.capacity), np.int32
                        )
                    else:
                        buf.fill(0)
                    self._spare_mbox = buf
                    self.counters.incr("staging_prezeroed")
            if ticket is not None:
                self.counters.incr("pipeline_steps")
                with cv:
                    self._pipe_inflight += 1
                    self._pipe_q.append(ticket)
                    cv.notify_all()
                continue
            self._idle_wait()
        with cv:
            cv.notify_all()
        self._egress_wake.set()

    def _egress_loop(self) -> None:
        self._drainer_idents.add(threading.get_ident())
        cv = self._pipe_cv
        while True:
            with cv:
                while not self._pipe_q and self.running:
                    cv.wait()
                if not self._pipe_q:
                    return  # stopped and drained
                ticket = self._pipe_q.popleft()
            eg_np = None
            if ticket.eg_packed is not None:
                # device sync OUTSIDE every lock: the step thread stages
                # and dispatches the next step during this wait
                eg_np = np.asarray(ticket.eg_packed)
            with self._state_lock:
                self._finish_ticket(ticket, eg_np)
            with cv:
                self._pipe_inflight -= 1
                cv.notify_all()
            # realisation may have produced device work (hot retries,
            # staged scatters) or unblocked deferred work the idle
            # predicate ignores while a ticket is in flight: wake the
            # step thread ONLY when such work exists — an unconditional
            # wake after a work-free realisation is exactly the
            # spurious wakeup the idle invariant forbids (caught by
            # test_command_plane's zero-spurious assertion). The
            # inflight decrement above precedes the check, so the
            # deferred state is dispatchable by the time we look; any
            # work arriving after a negative check sets the wake
            # itself (publish/stage/notify all do).
            if self._have_work() and not self._wake.is_set():
                self._wake.set()

    def _sender_loop(self) -> None:
        """Dedicated egress fan-out thread: per-destination message
        batches handed off through a bounded ring by the step/egress/
        WAL threads are shipped here, off every latency-critical loop.
        Drains outstanding batches on stop so queued acks still leave."""
        wake = self._egress_wake
        rings = self._egress_rings
        out: List = []
        while True:
            n = rings.drain(out)
            if not n:
                wake.clear()
                if rings.pending():
                    continue
                if not self.running:
                    # straggler window: a publisher that read _egress_on
                    # just before stop() flipped it may land a batch
                    # after this empty check — give it one short beat,
                    # re-drain, and let stop()'s post-join residual
                    # drain catch anything even later
                    time.sleep(0.01)
                    if rings.pending():
                        continue
                    return
                wake.wait()
                continue
            msgs_n = 0
            for node_name, msgs in out:
                try:
                    self._send_batch_inline(node_name, msgs)
                except Exception:  # noqa: BLE001
                    logger.exception(
                        "coordinator %s: egress sender batch to %s failed",
                        self.name, node_name,
                    )
                msgs_n += len(msgs)
            self.counters.incr("egress_thread_batches", n)
            self.counters.incr("egress_thread_msgs", msgs_n)
            out.clear()

    def _coop_drainer(self):
        """Register the calling thread as a drainer for the span of one
        cooperative step_* call (its self-publishes divert to
        ``_internal_q`` instead of gate-waiting on a ring it is itself
        responsible for draining). Returns a token for ``_coop_done``."""
        ident = threading.get_ident()
        if ident in self._drainer_idents:
            return 0
        self._drainer_idents.add(ident)
        return ident

    def _coop_done(self, token: int) -> None:
        if token:
            self._drainer_idents.discard(token)

    def step_once(self) -> bool:
        """One SEQUENTIAL coordinator iteration: drain ingress, scatter
        host log updates, run the fused device step, realise egress.
        Returns False when there was nothing to do. Deterministic-test
        and cooperative-driver entry point — never call it on a started
        pipelined coordinator (realisation order would invert)."""
        token = self._coop_drainer()
        try:
            return self._step_once_inner()
        finally:
            self._coop_done(token)

    def _step_once_inner(self) -> bool:
        pre = self._drain_classify()  # heavy half, off the state lock
        with self._state_lock:
            prev = self._coop_ticket
            if prev is not None:
                # flush a leftover pipelined-driver ticket first so
                # realisation order is preserved across driver modes
                self._coop_ticket = None
                eg_np = (
                    np.asarray(prev.eg_packed)
                    if prev.eg_packed is not None else None
                )
                self._finish_ticket(prev, eg_np)
                # the pre-drained items are NOT lost: hand them to the
                # dispatch pass the driver's next call runs
                self._drain_and_dispatch(dispatch=False, pre=pre)
                return True
            ticket = self._drain_and_dispatch(pre=pre)
            if ticket is None:
                return False
            eg_np = (
                np.asarray(ticket.eg_packed)
                if ticket.eg_packed is not None else None
            )
            self._finish_ticket(ticket, eg_np)
            return True

    def step_stage(self) -> bool:
        """Cooperative-pipeline half A: drain ingress, append commands,
        ship drain-produced AERs, and DISPATCH the fused device step
        (async), parking the ticket for ``step_finish``. A multi-
        coordinator driver stages every coordinator first, then
        finishes every coordinator — each device step then computes
        while the driver stages the others (the single-thread form of
        the wave pipeline, docs/INTERNALS.md §15)."""
        token = self._coop_drainer()
        try:
            return self._step_stage_inner()
        finally:
            self._coop_done(token)

    def _step_stage_inner(self) -> bool:
        pre = self._drain_classify()  # heavy half, off the state lock
        with self._state_lock:
            prev = self._coop_ticket
            if prev is not None:
                # driver skipped a finish: realise in order first
                self._coop_ticket = None
                eg_np = (
                    np.asarray(prev.eg_packed)
                    if prev.eg_packed is not None else None
                )
                self._finish_ticket(prev, eg_np)
            ticket = self._drain_and_dispatch(pre=pre)
            self._coop_ticket = ticket
            return ticket is not None

    def step_finish(self) -> bool:
        """Cooperative-pipeline half B: realise the ticket parked by
        ``step_stage`` (egress sync + processing + commit-driven AERs).
        Counts the staged-while-in-flight overlap."""
        token = self._coop_drainer()
        try:
            return self._step_finish_inner()
        finally:
            self._coop_done(token)

    def _step_finish_inner(self) -> bool:
        with self._state_lock:
            ticket = self._coop_ticket
            if ticket is None:
                return False
            self._coop_ticket = None
            t0 = time.perf_counter_ns()
            eg_np = (
                np.asarray(ticket.eg_packed)
                if ticket.eg_packed is not None else None
            )
            self._finish_ticket(ticket, eg_np)
            if ticket.stepped:
                self.counters.incr("pipeline_steps")
                # host work done between device dispatch and egress
                # sync (AER fan-out + the other coordinators' staging):
                # the window the device step computed inside
                hidden = t0 - ticket.t_pack
                if hidden > 0:
                    self.counters.incr("pipeline_overlap_ns", hidden)
            return True

    def step_pipelined(self) -> bool:
        """One cooperative PIPELINED iteration (single-driver-thread
        form of the wave pipeline, docs/INTERNALS.md §15): realise the
        PREVIOUSLY dispatched step (its device compute had the whole
        driver round to finish), then stage + dispatch the next one —
        whose drain already sees the realised egress's products, and
        whose device compute overlaps this thread realising the OTHER
        coordinators in the round-robin. Drain-produced AERs leave at
        dispatch time (inside ``_drain_and_dispatch``), so replication
        fan-out never waits a pipeline slot. Same ticket machinery as
        the threaded loop; keep calling until False before reading
        final state, and do not mix with a started loop."""
        token = self._coop_drainer()
        try:
            return self._step_pipelined_inner()
        finally:
            self._coop_done(token)

    def _step_pipelined_inner(self) -> bool:
        pre = self._drain_classify()  # heavy half, off the state lock
        with self._state_lock:
            prev = self._coop_ticket
            self._coop_ticket = None
            if prev is not None:
                eg_np = (
                    np.asarray(prev.eg_packed)
                    if prev.eg_packed is not None else None
                )
                self._finish_ticket(prev, eg_np)
            t0 = time.perf_counter_ns()
            ticket = self._drain_and_dispatch(pre=pre)
            self._coop_ticket = ticket
            if ticket is not None and prev is not None:
                # staged+dispatched in the same round a previous step
                # was realised: the new device step runs while the
                # driver services the other coordinators
                self.counters.incr(
                    "pipeline_overlap_ns", time.perf_counter_ns() - t0
                )
                self.counters.incr("pipeline_steps")
            return ticket is not None or prev is not None

    class _StepTicket:
        """One dispatched-but-unrealised step: the device egress handle
        plus everything realisation needs (who was consumed, the
        position->gid map, rares, and the staging timestamps)."""

        __slots__ = ("eg_packed", "consumed", "act", "aer_dirty", "rare",
                     "mbox_buf", "t_in", "t_drain", "t_pack", "stepped")

        def __init__(self, **kw):
            for k in self.__slots__:
                setattr(self, k, kw.get(k))

    def _drain_classify(self):
        """Lock-FREE half of the drain (docs/INTERNALS.md §16): pop
        every ingress lane into the reusable scratch and classify in
        one pass — commands regroup per target, generic messages
        collect into a route list, low-priority commands set aside.
        Runs on the step/driver thread WITHOUT the state lock: the
        classification of a deep-pipelined burst is O(items) pure
        Python, and holding the state lock through it starved the WAL
        writer's ``wal_notify_many`` (measured: 4x total fsync time,
        p99 8 ms -> 150 ms at 10240x96 — the writer blocked behind the
        lock, its queue grew, and every later batch paid the backlog).
        Only ``by_name`` reads happen here (GIL-safe dict reads; a
        concurrently added group at worst misses one pass, the same
        contract ``deliver`` already has). Returns the pre-drain
        ``(t_in, n_items, cmd_q, routes, lows)`` consumed by
        ``_drain_and_dispatch`` under the lock."""
        _t_in = time.perf_counter_ns()
        buf = self._drain_buf
        # native classify (docs/INTERNALS.md §18): drain the RC_* code
        # sidecar alongside the items and let rt_classify partition the
        # burst with the GIL released; Python keeps the routing half.
        # Routes around itself while ANY failpoint is armed so nemesis
        # runs always exercise the Python classification seam.
        nat = self._nat_classify and not faults.anything_armed()
        codes = self._drain_codes
        n_items = self._rings.drain(buf, codes if nat else None)
        if self._overflow_q:
            # overflow items are NEWER than the ring contents drained
            # above (a publish only overflows while the lane is full of
            # its own earlier items), so they fold AFTER the lane
            # drain; cross-pass order is kept by the producer-side
            # divert (a must-deliver publish goes straight to overflow
            # while older overflow is still parked — see deliver/
            # ingest_batch)
            with self._overflow_lock:
                n_items += len(self._overflow_q)
                buf.extend(self._overflow_q)
                if nat:
                    codes.extend(self._overflow_codes)
                self._overflow_q.clear()
                self._overflow_codes.clear()
        cmd_q: Optional[Dict[str, List[Command]]] = None
        routes: Optional[List] = None
        lows: Optional[List] = None
        if buf:
            cmd_q = {}
            routes = []
            lows = []
            if nat and len(codes) == len(buf):
                t0 = time.perf_counter_ns()
                part = _native.classify(codes, len(buf))
                if part is not None:
                    self._route_classified(buf, part, cmd_q, routes, lows)
                    self._wave_h["classify_native"].record(
                        time.perf_counter_ns() - t0)
                    self.counters.incr("native_classify_batches")
                    self.counters.incr("native_classify_items", len(buf))
                    buf.clear()
                    codes.clear()
                    self.counters.incr("ingress_ring_msgs", n_items)
                    self.counters.incr("ingress_ring_drains")
                    self._ring_gate.open()
                    return (_t_in, n_items, cmd_q, routes, lows)
                self.counters.incr("native_fallbacks")
            radd = routes.append
            by = self.by_name
            cq_get = cmd_q.get
            R_MSG, R_CMD, R_CMDS = self._R_MSG, self._R_CMD, self._R_CMDS
            for item in buf:
                tag = item[0]
                if tag == R_CMD:
                    _, name, cmd = item
                    if name not in by:
                        continue
                    if cmd.priority == "low":
                        lows.append((name, cmd))
                        continue
                    q = cq_get(name)
                    if q is None:
                        cmd_q[name] = [cmd]
                    else:
                        q.append(cmd)
                elif tag == R_MSG:
                    _, name, from_sid, msg = item
                    if name in by:
                        radd((name, from_sid, msg))
                elif tag == R_CMDS:
                    _, names, cmd = item
                    if cmd.priority == "low":
                        for name in names:
                            if name in by:
                                lows.append((name, cmd))
                        continue
                    for name in names:
                        q = cq_get(name)
                        if q is None:
                            if name not in by:
                                continue
                            cmd_q[name] = [cmd]
                        else:
                            q.append(cmd)
                else:  # R_BATCH: pre-normalized (name, from_sid, msg)
                    for trip in item[1]:
                        name = trip[0]
                        msg = trip[2]
                        if type(msg) is Command:
                            if msg.priority == "low":
                                if name in by:
                                    lows.append((name, msg))
                                continue
                            q = cq_get(name)
                            if q is None:
                                if name not in by:
                                    continue
                                cmd_q[name] = [msg]
                            else:
                                q.append(msg)
                        elif name in by:
                            radd(trip)
            buf.clear()
            if codes:
                codes.clear()
        if n_items:
            self.counters.incr("ingress_ring_msgs", n_items)
            self.counters.incr("ingress_ring_drains")
            # space was freed on every lane: wake ring-full waiters
            self._ring_gate.open()
        return (_t_in, n_items, cmd_q, routes, lows)

    def _route_classified(self, buf, part, cmd_q, routes, lows) -> None:
        """Python routing half of the native drain-classify: walk the
        per-class index partitions ``rt_classify`` returned (arrival
        order kept within each class) and run each class's straight-
        line routing loop — no per-item tag dispatch, no priority
        checks (the producer stamped those into the RC_* code).

        Ordering contract (docs/INTERNALS.md §18): order is preserved
        WITHIN each class; classes may reorder against each other.
        That is safe because any producer's causally-ordered commands
        ride a single class (clients publish R_CMD, bulk drivers
        R_CMDS, peer forwards R_BATCH) and protocol traffic is
        reorder-tolerant by the transport contract."""
        idx, counts = part
        ilist = idx.tolist()
        c_msg, c_cmd, c_cmd_low, c_cmds, c_cmds_low, c_batch = counts.tolist()
        by = self.by_name
        cq_get = cmd_q.get
        radd = routes.append
        ladd = lows.append
        o = 0
        for k in ilist[o:o + c_msg]:
            item = buf[k]
            name = item[1]
            if name in by:
                radd((name, item[2], item[3]))
        o += c_msg
        for k in ilist[o:o + c_cmd]:
            _, name, cmd = buf[k]
            if name not in by:
                continue
            q = cq_get(name)
            if q is None:
                cmd_q[name] = [cmd]
            else:
                q.append(cmd)
        o += c_cmd
        for k in ilist[o:o + c_cmd_low]:
            _, name, cmd = buf[k]
            if name in by:
                ladd((name, cmd))
        o += c_cmd_low
        for k in ilist[o:o + c_cmds]:
            _, names, cmd = buf[k]
            for name in names:
                q = cq_get(name)
                if q is None:
                    if name not in by:
                        continue
                    cmd_q[name] = [cmd]
                else:
                    q.append(cmd)
        o += c_cmds
        for k in ilist[o:o + c_cmds_low]:
            _, names, cmd = buf[k]
            for name in names:
                if name in by:
                    ladd((name, cmd))
        o += c_cmds_low
        for k in ilist[o:o + c_batch]:
            for trip in buf[k][1]:
                name = trip[0]
                msg = trip[2]
                if type(msg) is Command:
                    if msg.priority == "low":
                        if name in by:
                            ladd((name, msg))
                        continue
                    q = cq_get(name)
                    if q is None:
                        if name not in by:
                            continue
                        cmd_q[name] = [msg]
                    else:
                        q.append(msg)
                elif name in by:
                    radd(trip)

    def _drain_and_dispatch(
        self, dispatch: bool = True, pre=None
    ) -> Optional["BatchCoordinator._StepTicket"]:
        # caller holds the state lock; ``pre`` is _drain_classify()'s
        # output taken BEFORE the lock (drivers pre-classify so the
        # heavy classification never blocks the WAL writer). A None pre
        # classifies inline (tests / direct step calls).
        if pre is None:
            pre = self._drain_classify()
        _t_in, n_items, cmd_q, routes, lows = pre
        # fold the step/egress threads' own must-deliver self-publishes
        # (machine Append/Aux effects realized under the state lock —
        # including by the prev-ticket finish that just ran): they are
        # few, and folding here keeps their same-pass ordering
        if self._internal_q:
            iq = self._internal_q
            R_CMD = self._R_CMD
            if cmd_q is None:
                cmd_q, routes, lows = {}, [], []
            by = self.by_name
            n_internal = 0
            while iq:
                item = iq.popleft()
                n_internal += 1
                if item[0] == R_CMD:
                    _, name, cmd = item
                    if name not in by:
                        continue
                    if cmd.priority == "low":
                        lows.append((name, cmd))
                        continue
                    q = cmd_q.get(name)
                    if q is None:
                        cmd_q[name] = [cmd]
                    else:
                        q.append(cmd)
                else:
                    _, name, from_sid, msg = item
                    if name in by:
                        routes.append((name, from_sid, msg))
            # counted in n_items (pass-has-work accounting) but NOT in
            # ingress_ring_msgs — these never touched a ring
            n_items += n_internal
        # seed rares / AER-dirty gids parked by earlier ingest-only
        # passes (pipelined loop).
        # ALWAYS detach (aliasing trap): _route_one appends into it,
        # so keeping an alias of the live (empty) container would
        # re-seed — and re-process — this pass's rares on the next pass
        rare: List[Tuple[GroupHost, Any, Optional[ServerId]]] = (
            self._pending_rare
        )
        self._pending_rare = []
        aer_dirty: set = set()
        # appended runs: gid -> [[lo, hi, term], ...] (contiguous,
        # same-term); written: gid -> max durable idx. Run-based so the
        # device scatter is one row per touched GROUP, not per entry.
        # These ARE the staged double-buffer halves: ingest-only passes
        # leave their folds in place and the next dispatching pass
        # consumes them with zero re-merging (the WAL writer thread
        # stages durable watermarks into _staged_written directly).
        appended = self._staged_app
        written = self._staged_written
        # replies produced during routing (deferred durable acks): one
        # transport hop per destination per step, not one per group
        route_out: Dict[str, List] = {}

        by_get = self.by_name.get
        route = self._route_one
        if lows:
            low_dirty = self._low_dirty
            for name, cmd in lows:
                g = by_get(name)
                if g is not None:
                    g.low_q.append(cmd)
                    low_dirty.add(g.gid)
        if routes:
            now_mono = self.clock.monotonic()
            for name, from_sid, msg in routes:
                g = by_get(name)
                if g is not None:
                    route(g, from_sid, msg, rare, appended, written,
                          aer_dirty, route_out, now_mono)
        if route_out:
            for node_name, msgs in route_out.items():
                self._send_batch(node_name, msgs)
        if cmd_q:
            for name, cmds in cmd_q.items():
                g = by_get(name)
                if g is not None:
                    self._handle_commands(g, cmds, appended, written, aer_dirty)
        if self._low_dirty:
            self._drain_low_lane(appended, written, aer_dirty)

        if not dispatch:
            # ingest-only pass (a ticket is still being realised): the
            # drained work is already folded into the staged scatter
            # dicts the next dispatching pass consumes, and commands
            # have already reached the logs and the WAL queue — the
            # coalescing the pipeline is for happens here.
            if rare:
                self._pending_rare = rare
            if aer_dirty:
                # replication fan-out never waits for the next dispatch:
                # fresh appends ship while the in-flight step realises
                self._send_aers(aer_dirty)
            if n_items:
                self.counters.incr("staging_passes")
                _t_drain = time.perf_counter_ns()
                self._wave_h["ingress_drain"].record(_t_drain - _t_in)
                if self._trace.enabled:
                    self._trace.span("ingress_drain", self.name, _t_in,
                                     _t_drain - _t_in)
            return None
        if not (
            n_items or self._hot or rare or appended or written
            or self._pending_roles
        ):
            return None
        _t_drain = time.perf_counter_ns()

        if self._pending_roles:
            gids, roles, _ = self._pad3(
                [(gid, role, 0) for gid, role in self._pending_roles]
            )
            self._pending_roles = []
            self.state = C.set_roles(self.state, gids, roles)

        # consume the staged halves: detach so concurrent stagers (the
        # WAL writer thread, the egress thread's rare paths) start a
        # fresh buffer for the NEXT dispatch
        self._staged_app = {}
        self._staged_written = {}

        app_rows: List[Tuple[int, int, int, int]] = []
        if appended:
            legacy: List[Tuple[int, int, int]] = []  # older runs, per entry
            for gid, runs in appended.items():
                for lo, hi, term in runs[:-1]:
                    legacy.extend((gid, i, term) for i in range(lo, hi + 1))
                lo, hi, term = runs[-1]
                app_rows.append((gid, lo, hi, term))
            if legacy:
                # rare (mixed-term batches): scatter older runs first so
                # the newest run's ring slots win
                gids, idxs, terms = self._pad3(legacy)
                self.state = C.record_appended(self.state, gids, idxs, terms)
        if written and self._lat_gids:
            now_w = time.monotonic_ns()
            for gid_w in self._lat_gids:
                idx_w = written.get(gid_w)
                gw = self.groups[gid_w] if idx_w is not None else None
                if gw is None:
                    continue
                lat = gw.lat
                if lat is not None and lat[3] == 0 and idx_w >= lat[0]:
                    lat[3] = now_w
                    self._commit_h["append_durable"].record(now_w - lat[2])

        # activity-scaled path selection: groups with device-relevant
        # work this step are exactly the hot set (queued messages/term
        # hints) plus those whose log tail or durable watermark moved
        # (the quorum scan can advance their commit). Everything else
        # is provably unchanged by an empty-mailbox step.
        # The newest appended runs and the durable watermarks ride the
        # packed mailbox itself (C.MBOX_SCAT_FIELDS rows) and apply
        # inside the fused step — one transfer + one dispatch per step.
        act: Optional[list] = None
        if self._shard_state is None and self.active_set != "never":
            cand = self._hot | appended.keys() | written.keys()
            if self.active_set == "always" or len(cand) <= (self.capacity >> 2):
                act = sorted(cand)
        eg_packed = consumed = act_np = mbox_buf = None
        stepped = False
        if act is not None:
            if act:
                packed, gidx, act_np, consumed, mbox_buf = (
                    self._build_mailbox_sub(act, app_rows, written)
                )
                self.state, eg_packed = C.consensus_step_packed_sub_scat(
                    self.state, packed, gidx
                )
                stepped = True
                self.steps += 1
                self.sub_steps += 1
                self.msgs_processed += len(consumed)
        else:
            shard = self._shard_state is not None
            if shard:
                # sharded state: the mailbox shards column-wise, which
                # would split scatter rows across devices — apply the
                # log-tail scatters as separate (replicated-index) calls
                if app_rows:
                    gids, los, his, terms = self._pad4(app_rows)
                    self.state = C.record_appended_runs(
                        self.state, gids, los, his, terms
                    )
                if written:
                    gids, idxs, _ = self._pad3(
                        [(g, i, 0) for g, i in written.items()]
                    )
                    self.state = C.record_written(self.state, gids, idxs)
                packed, consumed, mbox_buf = self._build_mailbox(None, None)
                # re-pin before the fused step so it executes SPMD over
                # the mesh (no-op when the layout is already right)
                self.state = jax.device_put(self.state, self._shard_state)
                packed = jax.device_put(packed, self._shard_mbox)
                self.state, eg_packed = C.consensus_step_packed(
                    self.state, packed
                )
            else:
                packed, consumed, mbox_buf = self._build_mailbox(
                    app_rows, written
                )
                self.state, eg_packed = C.consensus_step_packed_scat(
                    self.state, packed
                )
            stepped = True
            self.steps += 1
            self.msgs_processed += len(consumed)
        # full-width steps are the shape worth pre-zeroing a spare
        # mailbox for during the next overlap window (sub-batch buffers
        # are tiny; zeroing them inline is already free)
        self._prezero_useful = stepped and act is None
        _t_pack = time.perf_counter_ns()
        # dispatch is ASYNC: eg_packed is an in-flight device value; the
        # ticket's realisation half syncs it (np.asarray) and processes
        # the egress. The sequential step_once realises inline.
        # Drain-produced AERs (fresh appends, ack-driven next_index
        # moves) leave NOW, overlapping the device compute — holding
        # them for realisation would delay the replication fan-out by a
        # whole pipeline slot. Egress-produced AERs (commit advances)
        # ride the ticket.
        sent_aers = bool(aer_dirty)
        if sent_aers:
            self._send_aers(aer_dirty)
            aer_dirty = set()
        _t_aer0 = time.perf_counter_ns()
        wh = self._wave_h
        wh["ingress_drain"].record(_t_drain - _t_in)
        if stepped:
            wh["host_pack"].record(_t_pack - _t_drain)
        if sent_aers:
            wh["aer_fanout"].record(_t_aer0 - _t_pack)
        tb = self._trace
        if tb.enabled:
            node = self.name
            tb.span("ingress_drain", node, _t_in, _t_drain - _t_in)
            if stepped:
                tb.span("host_pack", node, _t_drain, _t_pack - _t_drain)
            if sent_aers:
                tb.span("aer_fanout", node, _t_pack, _t_aer0 - _t_pack)
        return self._StepTicket(
            eg_packed=eg_packed if stepped else None,
            consumed=consumed, act=act_np, aer_dirty=aer_dirty, rare=rare,
            mbox_buf=mbox_buf, t_in=_t_in, t_drain=_t_drain, t_pack=_t_pack,
            stepped=stepped,
        )

    def _finish_ticket(self, ticket, eg_np: Optional[np.ndarray]) -> None:
        """Realise one dispatched step: process the synced egress, run
        the rare paths, fan out AERs (caller holds the state lock and
        has already synced ``eg_np`` — ideally outside the lock)."""
        aer_dirty = ticket.aer_dirty
        _t_dev = None
        if eg_np is not None:
            _t_dev = time.perf_counter_ns()
            # egress is host-synced: the device has fully consumed the
            # mailbox view, so the pack buffer may be reused
            self._mbox_release(ticket.mbox_buf)
            eg = {name: eg_np[i] for i, name in enumerate(C.EGRESS_FIELDS)}
            self._process_egress(eg, ticket.consumed, aer_dirty,
                                 act=ticket.act)
        # rare-path outbound batches per destination ACROSS the whole
        # rare loop: an election storm over 10k groups must land on a
        # peer as a handful of ring items, not one per group — per-group
        # sends overflowed the peer's bounded ingress lane and the
        # overflow was shed as lossy traffic, wedging the un-retried
        # tail of the storm (caught by the 10240-group bench election)
        rare_out: Dict[str, List] = {}
        for g, msg, from_sid in ticket.rare:
            # crash isolation for the slow paths (snapshot transfer
            # decode of untrusted bytes, membership, queries): a
            # poisoned message must not kill the step thread — every
            # group on this coordinator would freeze (the actor backend
            # gets the same guarantee from scheduler crash isolation)
            try:
                self._handle_rare(g, msg, from_sid, rare_out)
            except Exception:  # noqa: BLE001
                logger.exception(
                    "coordinator %s: dropping rare message %r for group "
                    "%s after handler crash", self.name, type(msg).__name__,
                    g.name,
                )
        for node_name, msgs in rare_out.items():
            self._send_batch(node_name, msgs)
        _t_eg = time.perf_counter_ns()
        self._send_aers(aer_dirty)
        _t_aer = time.perf_counter_ns()
        # apply progress may have released admission-window room: wake
        # parked rejected clients (no-op attribute check when none)
        self._adm_gate.open()
        # per-step wave-phase breakdown (obs.WAVE_PHASES). host_pack
        # covered queued-scatter application + mailbox build + dispatch
        # (recorded at dispatch time); device_step is the egress host
        # sync (the device-compute wait); host_egress includes apply
        # and client replies (apply also gets its own histogram).
        wh = self._wave_h
        if _t_dev is not None:
            wh["device_step"].record(_t_dev - ticket.t_pack)
            wh["host_egress"].record(_t_eg - _t_dev)
        wh["aer_fanout"].record(_t_aer - _t_eg)
        tb = self._trace
        if tb.enabled:
            # same timestamps the histograms just consumed, as timeline
            # spans: one lane per phase per node, so step-pipelining
            # overlap (or its absence) is visible in Perfetto
            node = self.name
            if _t_dev is not None:
                tb.span("device_step", node, ticket.t_pack,
                        _t_dev - ticket.t_pack)
                tb.span("host_egress", node, _t_dev, _t_eg - _t_dev)
            tb.span("aer_fanout", node, _t_eg, _t_aer - _t_eg)

    def _stage_app(self, gid: int, lo: int, hi: int, term: int) -> None:
        """Stage an appended run for the next dispatching pass's device
        scatter (caller holds the state lock). Contiguous same-term runs
        merge in place — the staging half of the double buffer."""
        runs = self._staged_app.get(gid)
        if runs is None:
            self._staged_app[gid] = [[lo, hi, term]]
        elif runs[-1][1] + 1 == lo and runs[-1][2] == term:
            runs[-1][1] = hi
        else:
            runs.append([lo, hi, term])

    def _stage_written(self, gid: int, idx: int) -> None:
        """Stage a durable watermark (caller holds the state lock)."""
        if self._staged_written.get(gid, 0) < idx:
            self._staged_written[gid] = idx

    def _pad(self, rows, width: int):
        """Pad scatter batches to power-of-two buckets so XLA compiles a
        handful of shapes instead of one per batch length. Pads use an
        out-of-bounds group id, which jitted scatters drop. Returns one
        jnp column per input column."""
        n = len(rows)
        cap = 1
        while cap < n:
            cap <<= 1
        arr = np.zeros((cap, width), np.int32)
        arr[n:, 0] = self.capacity
        if n:
            arr[:n] = rows
        return tuple(jnp.asarray(arr[:, c]) for c in range(width))

    def _pad3(self, triples):
        return self._pad(triples, 3)

    def _pad4(self, rows):
        return self._pad(rows, 4)

    # -- ingress routing ---------------------------------------------------

    def _route_one(self, g: GroupHost, from_sid, msg, rare, appended,
                   written, aer_dirty, route_out, now_mono=None):
        if now_mono is None:
            now_mono = self.clock.monotonic()
        if type(msg) is FromPeer:
            from_sid, msg = msg.peer, msg.msg
        t = type(msg)
        if t in MSG_OF_TYPE:
            if t is AppendEntriesRpc and msg.term >= g.term:
                g.last_contact = now_mono
                if self.lease_cfg.enabled:
                    # leader contact backing the stickiness promise,
                    # plus the follower freshness anchor for bounded
                    # local reads (docs/INTERNALS.md §20)
                    g.lease_contact = now_mono
                    if msg.commit_ts > g.fresh_anchor[1]:
                        if g.last_applied >= msg.leader_commit:
                            if msg.commit_ts > g.fresh_ts:
                                g.fresh_ts = msg.commit_ts
                        else:
                            g.fresh_anchor = (
                                msg.leader_commit, msg.commit_ts
                            )
            # host-side next_index bookkeeping rides on the same replies
            # the device will process
            elif t is AppendEntriesReply and g.role == C.R_LEADER:
                slot = g.slot_of(from_sid)
                if slot >= 0:
                    g.last_ack[slot] = now_mono
                    if self.lease_cfg.enabled and msg.term == g.term:
                        # any same-term reply (success or reject)
                        # proves contact: credit the send basis
                        self._lease_credit(g, slot)
                    if msg.success:
                        g.next_index[slot] = max(g.next_index[slot], msg.last_index + 1)
                        if slot < len(g.match_hint):
                            g.match_hint[slot] = max(
                                g.match_hint[slot], msg.last_index
                            )
                        vs = g.voter_status.get(slot)
                        if (
                            isinstance(vs, tuple)
                            and vs[0] == "nonvoter"
                            and msg.last_index >= vs[1]
                            and g.cluster_change_permitted
                        ):
                            # caught-up nonvoter: promote via a cluster
                            # change (reference: maybe_promote_peer,
                            # src/ra_server.erl:3977-3995)
                            self._handle_command(
                                g,
                                Command(kind=RA_CLUSTER_CHANGE,
                                        data=((from_sid, "voter"),)),
                                appended, written, aer_dirty,
                            )
                    else:
                        hint = max(1, min(msg.next_index, msg.last_index + 1))
                        g.next_index[slot] = min(g.next_index[slot], hint)
                    aer_dirty.add(g.gid)
            elif (
                self.lease_cfg.enabled
                and (t is PreVoteRpc or t is RequestVoteRpc)
                and not (t is RequestVoteRpc and msg.force)
                and g.slot_of(msg.candidate_id) != g.leader_slot
                and not self._stickiness_lapsed(g, now_mono)
            ):
                # leader stickiness (§20): within one election timeout
                # of leader contact, (pre-)votes for other candidates
                # are disregarded — denied at OUR term, without letting
                # the device adopt the higher term (the term echo would
                # depose the live leader the lease depends on).
                # TimeoutNow-forced candidacies bypass: the old leader
                # revoked its lease before soliciting the vote.
                deny = (
                    PreVoteResult(g.term, msg.token, False)
                    if t is PreVoteRpc
                    else RequestVoteResult(g.term, False)
                )
                out = route_out.get(msg.candidate_id[1])
                if out is None:
                    route_out[msg.candidate_id[1]] = out = []
                out.append((msg.candidate_id, deny, (g.name, self.name)))
                return
            g.inbox.append((from_sid, msg))
            self._hot.add(g.gid)
            return
        if isinstance(msg, Command):
            self._handle_command(g, msg, appended, written, aer_dirty)
            return
        if isinstance(msg, tuple) and msg and msg[0] == "log_event":
            _, evt = msg
            g.log.handle_event(evt)
            wi, wt = g.log.last_written()
            if written.get(g.gid, 0) < wi:
                written[g.gid] = wi
            aer_dirty.add(g.gid)
            if g.pending_ack is not None and wi >= g.pending_ack[1]:
                leader_sid, cover = g.pending_ack
                g.pending_ack = None
                ack = min(wi, cover)
                at = g.log.fetch_term(ack)
                out = route_out.get(leader_sid[1])
                if out is None:
                    route_out[leader_sid[1]] = out = []
                out.append(
                    (leader_sid,
                     AppendEntriesReply(g.term, True, ack + 1, ack,
                                        at if at is not None else wt),
                     (g.name, self.name))
                )
            return
        rare.append((g, msg, from_sid))

    def _handle_command(self, g: GroupHost, cmd: Command, appended, written, aer_dirty):
        self._handle_commands(g, (cmd,), appended, written, aer_dirty)

    # max low-priority commands appended per group per step (reference:
    # ?FLUSH_COMMANDS_SIZE, src/ra_server.hrl:34)
    FLUSH_COMMANDS_SIZE = 16

    def _drain_low_lane(self, appended, written, aer_dirty) -> None:
        """Bounded per-step drain of buffered low-priority commands —
        normal ingest always goes first; lows trickle in slices so a
        low-priority firehose cannot starve interactive traffic
        (reference: ra_ets_queue lane, src/ra_server_proc.erl:507-530).
        Non-leaders redirect buffered lows instead of dropping futures.
        Low-priority routing now happens at ring-drain time on the step
        thread (under the state lock), so ``low_q``/``_low_dirty`` have
        a single writer and need no extra lock."""
        dirty = self._low_dirty
        self._low_dirty = set()
        still: set = set()
        for gid in dirty:
            g = self.groups[gid]
            if g is None or not g.low_q:
                continue
            if g.role != C.R_LEADER:
                red = ("redirect", g.sid_of(g.leader_slot))
                for cmd in g.low_q:
                    if cmd.from_ref is not None:
                        self._reply(cmd.from_ref, red)
                g.low_q.clear()
                continue
            take = [
                g.low_q.popleft()
                for _ in range(min(self.FLUSH_COMMANDS_SIZE, len(g.low_q)))
            ]
            if g.low_q:
                still.add(gid)
            self._handle_commands(g, take, appended, written, aer_dirty)
        if still:
            self._low_dirty |= still

    def _handle_commands(self, g: GroupHost, cmds, appended, written, aer_dirty):
        """Append a batch of client commands for one group: one pass of
        log/run/reply bookkeeping instead of per-command."""
        if g.role != C.R_LEADER:
            red = ("redirect", g.sid_of(g.leader_slot))
            for cmd in cmds:
                if cmd.from_ref is not None:
                    self._reply(cmd.from_ref, red)
            return
        log = g.log
        term = g.term
        gid = g.gid
        pending = g.pending_replies
        me = (g.name, self.name)
        idx = log.next_index()
        first = idx
        # admission window: bound the group's appended-but-unapplied
        # backlog so a client cannot queue unbounded work ahead of apply
        # progress (the client analog of the reference's per-peer
        # pipeline window, src/ra_server.hrl:8). Commands past the
        # window are rejected with backoff (from_ref callers see
        # ("reject", "overloaded") and retry) or dropped and counted:
        # noreply commands owe no ack, and notify-mode pipelined
        # commands are at-most-once by contract (clients resend on a
        # missing applied notification — reference pipeline_command
        # semantics). Machine-INTERNAL commands (timer fires, Append
        # effects) fire exactly once with no retry path: never shed.
        if self.pressure.blocked():
            # storage-degraded pre-emption (docs/INTERNALS.md §21):
            # space-class WAL failure or hard disk watermark. Client
            # commands reject typed ("reject", "nospace") with the
            # pressure gate's waiter (opens when the probe write
            # succeeds); machine-internal commands still admit — they
            # fire exactly once with no retry path.
            admit2 = [c for c in cmds if c.internal]
            shed2 = [c for c in cmds if not c.internal]
            n_rej2 = 0
            for cmd in shed2:
                if cmd.from_ref is not None:
                    n_rej2 += 1
                    self._reply(
                        cmd.from_ref,
                        REJECT_NOSPACE + (self.pressure.waiter(),),
                    )
            if n_rej2:
                self.counters.incr("commands_rejected_nospace", n_rej2)
            if len(shed2) > n_rej2:
                self.counters.incr(
                    "commands_dropped_overload", len(shed2) - n_rej2
                )
            if shed2:
                self._obs_rec.record(
                    "admission_reject", node=self.name, group=g.name,
                    term=term,
                    detail=(f"nospace rejected={n_rej2} "
                            f"dropped={len(shed2) - n_rej2}"),
                )
            cmds = admit2
            if not cmds:
                return
        room = self.max_command_backlog - (first - 1 - g.last_applied)
        if room < len(cmds):
            admit: List[Command] = []
            shed: List[Command] = []
            for cmd in cmds:
                if cmd.internal or len(admit) < room:
                    admit.append(cmd)
                else:
                    shed.append(cmd)
            cmds = admit
            n_rej = 0
            for cmd in shed:
                if cmd.from_ref is not None:
                    n_rej += 1
                    # the reject carries an admission-gate waiter:
                    # api.process_command parks on it and is WOKEN on
                    # window release (apply progress) instead of
                    # sleeping a fixed backoff (docs/INTERNALS.md §16)
                    self._reply(
                        cmd.from_ref,
                        REJECT_OVERLOADED + (self._adm_gate.waiter(),),
                    )
            if n_rej:
                self.counters.incr("commands_rejected", n_rej)
            if len(shed) > n_rej:
                self.counters.incr(
                    "commands_dropped_overload", len(shed) - n_rej
                )
            if shed:
                self._obs_rec.record(
                    "admission_reject", node=self.name, group=g.name,
                    term=term,
                    detail=f"rejected={n_rej} dropped={len(shed) - n_rej}",
                )
            if not cmds:
                return
        # commit-stage sampling: bounded to groups on the sample mask,
        # and only for commands stamped with a submit ts
        sampled = (gid & self._lat_mask) == 0
        t_h0 = time.monotonic_ns() if sampled else 0
        # fast path: plain user commands owing no replies (the pipeline
        # shape) — build the run in one pass and bulk-append it
        simple = True
        for cmd in cmds:
            if cmd.kind != USR or cmd.from_ref is not None:
                simple = False
                break
        if simple:
            entries = [Entry(first + k, term, cmd) for k, cmd in enumerate(cmds)]
            _li, prev_term = log.last_index_term()
            log.append_many(entries)
            idx = first + len(cmds)
            ft = g.fresh_tail
            if ft is not None and ft[0] + len(ft[3]) == first and ft[2] == term:
                ft[3].extend(entries)  # second batch this step: one run
            else:
                g.fresh_tail = (first, prev_term, term, entries)
        else:
            for cmd in cmds:
                if cmd.kind in (RA_JOIN, RA_LEAVE, RA_CLUSTER_CHANGE):
                    if not self._prepare_cluster_cmd(g, cmd):
                        continue
                log.append(Entry(idx, term, cmd))
                if cmd.kind != USR:
                    g.specials.append(idx)
                if cmd.from_ref is not None:
                    if cmd.reply_mode == "after_log_append":
                        self._reply(cmd.from_ref, ("ok", (idx, term), me))
                    elif cmd.reply_mode == "await_consensus":
                        pending[idx] = cmd.from_ref
                idx += 1
        if idx == first:
            return  # every command was rejected
        last = idx - 1
        if sampled:
            now_ns = time.monotonic_ns()
            self._wave_h["wal_handoff"].record(now_ns - t_h0)
            ts0 = cmds[0].ts
            lat = g.lat
            if ts0 is not None and (
                lat is None or now_ns - lat[1] > 10_000_000_000
            ):
                # one in-flight sample per group; a sample stranded >10s
                # (leadership churn) is abandoned and replaced
                g.lat = [last, ts0, now_ns, 0, 0]
                self._lat_gids.add(gid)
                self._commit_h["submit_append"].record(now_ns - ts0)
        runs = appended.get(gid)
        if runs is None:
            appended[gid] = [[first, last, term]]
        else:
            tail = runs[-1]
            if tail[1] + 1 == first and tail[2] == term:
                tail[1] = last
            else:
                runs.append([first, last, term])
        if log.last_written()[0] >= last and written.get(gid, 0) < last:
            written[gid] = last
        aer_dirty.add(gid)

    # -- membership (reference: $ra_join/$ra_leave handling,
    # src/ra_server.erl:3491-3542; one change in flight at a time) --------

    def _prepare_cluster_cmd(self, g: GroupHost, cmd: Command) -> bool:
        """Leader-side cluster change: apply to the host member table
        immediately (Raft new-config-on-append rule), gate one change at
        a time. Returns False when rejected (caller must not append)."""
        if not g.cluster_change_permitted:
            if cmd.from_ref is not None:
                self._reply(cmd.from_ref, ("error", "cluster_change_not_permitted"))
            return False
        # rollback point: the leader's own uncommitted change must be
        # undoable if it is deposed and a new leader truncates this
        # suffix — same protocol as follower-side _adopt_cluster_cmd
        # (the truncation rollback in _host_write_entries covers both)
        history = (g.log.next_index(), list(g.members), dict(g.voter_status))
        if cmd.kind == RA_JOIN:
            member, voter = cmd.data
            member = tuple(member)
            if member in g.members:
                if cmd.from_ref is not None:
                    self._reply(cmd.from_ref, ("ok", "already_member"))
                return False
            slot = self._alloc_slot(g)
            if slot is None:
                if cmd.from_ref is not None:
                    self._reply(cmd.from_ref, ("error", "group_at_peer_capacity"))
                return False
            li = g.log.last_index_term()[0]
            g.members[slot] = member
            g.voter_status[slot] = "voter" if voter else ("nonvoter", li)
            g.next_index[slot] = li + 1
            g.commit_sent[slot] = 0
        elif cmd.kind == RA_LEAVE:
            member = tuple(cmd.data)
            slot = g.slot_of(member)
            if slot < 0:
                if cmd.from_ref is not None:
                    self._reply(cmd.from_ref, ("ok", "not_member"))
                return False
            g.members[slot] = None
            g.voter_status[slot] = None
        else:  # RA_CLUSTER_CHANGE: explicit voter-status updates
            for member, vs in cmd.data:
                slot = g.slot_of(tuple(member))
                if slot >= 0:
                    g.voter_status[slot] = vs
        g.cluster_history.append(history)
        del g.cluster_history[:-8]
        g.cluster_change_permitted = False
        g.cluster_index = g.log.next_index()
        self._sync_member_rows(g)
        return True

    def _alloc_slot(self, g: GroupHost) -> Optional[int]:
        for i, m in enumerate(g.members):
            if m is None:
                g.last_ack.pop(i, None)  # fresh occupant, fresh liveness
                g.match_hint[i] = 0  # nothing confirmed for the newcomer
                return i  # reuse a tombstoned slot
        if len(g.members) < self.P:
            g.members.append(None)
            g.next_index.append(1)
            g.commit_sent.append(0)
            g.match_hint.append(0)
            return len(g.members) - 1
        return None

    def _sync_member_rows(self, g: GroupHost) -> None:
        """Scatter the host member table's active/voting view to the
        device (call sites all run under the state lock)."""
        active = np.zeros(self.P, dtype=bool)
        voting = np.zeros(self.P, dtype=bool)
        for i, m in enumerate(g.members):
            if m is not None:
                active[i] = True
                voting[i] = g.voter_status.get(i) == "voter"
        self.state = self.state._replace(
            active=self.state.active.at[g.gid].set(jnp.asarray(active)),
            voting=self.state.voting.at[g.gid].set(jnp.asarray(voting)),
        )
        if self.lease_cfg.enabled:
            if g.role == C.R_LEADER:
                self._lease_revoke(g, "membership change")
            self._lease_sync(g)

    def _adopt_cluster_cmd(self, g: GroupHost, cmd: Command, entry_index: int = 0) -> None:
        """Follower-side adoption of a replicated cluster change (slot
        coordinates are node-local; only the member set must agree)."""
        g.cluster_history.append(
            (entry_index, list(g.members), dict(g.voter_status))
        )
        del g.cluster_history[:-8]
        if cmd.kind == RA_JOIN:
            member, voter = cmd.data
            member = tuple(member)
            slot = g.slot_of(member)
            if slot < 0:
                slot = self._alloc_slot(g)
                if slot is not None:
                    g.members[slot] = member
            if slot is not None and slot >= 0:
                # also covers the joining member itself learning its own
                # (non)voter status from the replicated entry; the join
                # entry's index is the catch-up target should this node
                # lead later (never 0 — that would promote a lagging
                # learner on its first ack)
                g.voter_status[slot] = (
                    "voter" if voter else ("nonvoter", entry_index)
                )
        elif cmd.kind == RA_LEAVE:
            slot = g.slot_of(tuple(cmd.data))
            if slot >= 0:
                g.members[slot] = None
                g.voter_status[slot] = None
        else:
            if cmd.data and cmd.data[0] == "replace":
                # force-shrink style replacement
                new = [tuple(m) for m, _vs in cmd.data[1]]
                me = (g.name, self.name)
                if me in new:
                    g.members = list(new)
                    g.self_slot = new.index(me)
                    g.voter_status = {i: "voter" for i in range(len(new))}
                    g.next_index = [1] * len(new)
                    g.commit_sent = [0] * len(new)
                    g.match_hint = [0] * len(new)
                    self.state = self.state._replace(
                        self_slot=self.state.self_slot.at[g.gid].set(g.self_slot)
                    )
            else:
                for member, vs in cmd.data:
                    slot = g.slot_of(tuple(member))
                    if slot >= 0:
                        g.voter_status[slot] = vs
        self._sync_member_rows(g)

    # -- mailbox build -----------------------------------------------------

    # packed mailbox row indexes (see C.MBOX_FIELDS), plus the fused
    # scatter rows that ride the same buffer (C.MBOX_SCAT_FIELDS)
    _R = {
        name: i
        for i, name in enumerate(list(C.MBOX_FIELDS) + C.MBOX_SCAT_FIELDS)
    }
    _NROWS = len(C.MBOX_FIELDS) + len(C.MBOX_SCAT_FIELDS)

    # mailbox row-index vectors for the two hot message types, in the
    # flat value order _pack_hot builds (the native rt_pack_mbox ABI)
    _REP_ROWS = np.asarray(
        [_R["msg_type"], _R["sender_slot"], _R["term"], _R["success"],
         _R["reply_next_idx"], _R["reply_last_idx"],
         _R["reply_last_term"]],
        np.int32,
    )
    _AER_ROWS = np.asarray(
        [_R["msg_type"], _R["sender_slot"], _R["term"], _R["prev_idx"],
         _R["prev_term"], _R["num_entries"], _R["entries_last_term"],
         _R["leader_commit"]],
        np.int32,
    )

    def _pack_hot(self, packed, aer_i, aer_m, aer_s, rep_i, rep_m,
                  rep_s) -> None:
        """Columnwise encode of the two hot message types into the
        packed mailbox. With the native pack path on, each class is one
        flat int64 value pass + one GIL-released scatter
        (rt_pack_mbox); otherwise (or while any failpoint is armed, or
        on a scatter bounds failure) the original per-field numpy
        column stores run — both produce byte-identical buffers."""
        if (
            (rep_i or aer_i)
            and self._nat_pack
            and not faults.anything_armed()
        ):
            t0 = time.perf_counter_ns()
            ok = True
            if rep_i:
                vals: List[int] = []
                ext = vals.extend
                for s, m in zip(rep_s, rep_m):
                    ext((C.MSG_AER_REPLY, s, m.term,
                         1 if m.success else 0, m.next_index,
                         m.last_index, m.last_term))
                ok = _native.pack_mbox(packed, rep_i, vals, self._REP_ROWS)
            if ok and aer_i:
                vals = []
                ext = vals.extend
                for s, m in zip(aer_s, aer_m):
                    ext((C.MSG_AER, s, m.term, m.prev_log_index,
                         m.prev_log_term, len(m.entries),
                         m.entries[-1].term if m.entries else 0,
                         m.leader_commit))
                ok = _native.pack_mbox(packed, aer_i, vals, self._AER_ROWS)
            if ok:
                self._wave_h["pack_native"].record(
                    time.perf_counter_ns() - t0)
                self.counters.incr("native_pack_batches")
                self.counters.incr("native_pack_msgs",
                                   len(rep_i) + len(aer_i))
                return
            # partial native success is harmless: the Python stores
            # below rewrite the same cells with the same values
            self.counters.incr("native_fallbacks")
        R = self._R
        if rep_i:
            ii = np.asarray(rep_i, np.int64)
            packed[R["msg_type"], ii] = C.MSG_AER_REPLY
            packed[R["sender_slot"], ii] = rep_s
            packed[R["term"], ii] = [m.term for m in rep_m]
            packed[R["success"], ii] = [1 if m.success else 0 for m in rep_m]
            packed[R["reply_next_idx"], ii] = [m.next_index for m in rep_m]
            packed[R["reply_last_idx"], ii] = [m.last_index for m in rep_m]
            packed[R["reply_last_term"], ii] = [m.last_term for m in rep_m]
        if aer_i:
            ii = np.asarray(aer_i, np.int64)
            packed[R["msg_type"], ii] = C.MSG_AER
            packed[R["sender_slot"], ii] = aer_s
            packed[R["term"], ii] = [m.term for m in aer_m]
            packed[R["prev_idx"], ii] = [m.prev_log_index for m in aer_m]
            packed[R["prev_term"], ii] = [m.prev_log_term for m in aer_m]
            packed[R["num_entries"], ii] = [len(m.entries) for m in aer_m]
            packed[R["entries_last_term"], ii] = [
                m.entries[-1].term if m.entries else 0 for m in aer_m
            ]
            packed[R["leader_commit"], ii] = [m.leader_commit for m in aer_m]

    def _fill_scat(self, packed: np.ndarray, app_rows, written) -> None:
        """Write the fused log-tail scatter rows: the newest appended
        run per group and the durable watermarks, pad gid = capacity
        (device scatters drop out-of-range rows)."""
        R = self._R
        packed[R["a_gid"]].fill(self.capacity)
        packed[R["w_gid"]].fill(self.capacity)
        if app_rows:
            ar = np.asarray(app_rows, np.int64)
            n = len(app_rows)
            packed[R["a_gid"], :n] = ar[:, 0]
            packed[R["a_lo"], :n] = ar[:, 1]
            packed[R["a_hi"], :n] = ar[:, 2]
            packed[R["a_term"], :n] = ar[:, 3]
        if written:
            n = len(written)
            packed[R["w_gid"], :n] = np.fromiter(written.keys(), np.int64, n)
            packed[R["w_idx"], :n] = np.fromiter(written.values(), np.int64, n)

    def _mbox_take(self, width: Optional[int] = None) -> np.ndarray:
        """Pop a zeroed pack buffer from the pool (full-width by
        default, or a power-of-two sub-batch ``width``); allocates when
        empty — pool size is bounded by the tickets in flight."""
        if width is None:
            width = self.capacity
            spare = self._spare_mbox
            if spare is not None:
                # double-buffered staging: the spare was pre-zeroed in
                # the pipeline overlap window — no take/zero cost here
                self._spare_mbox = None
                return spare
        pool = self._mbox_pool
        for k, buf in enumerate(pool):
            if buf.shape[1] == width:
                del pool[k]
                buf.fill(0)
                return buf
        return np.zeros((self._NROWS, width), np.int32)

    def _mbox_release(self, buf: Optional[np.ndarray]) -> None:
        """Return a pack buffer once its step's egress sync proves the
        device consumed the zero-copy view."""
        if buf is not None and len(self._mbox_pool) < 6:
            self._mbox_pool.append(buf)

    def _build_mailbox(self, app_rows=None, written=None):
        packed = self._mbox_take()
        self._fill_scat(packed, app_rows, written)
        R = self._R
        packed[R["host_term_idx"]].fill(-1)
        packed[R["host_term_val"]].fill(-1)
        consumed: Dict[int, Tuple[Any, Any]] = {}
        hot = self._hot
        self._hot = set()
        groups = self.groups
        # the two hot message types are encoded COLUMNWISE after the pop
        # loop (numpy scalar stores per field per message were a top
        # cost); everything else goes through the scalar _encode
        aer_i: List[int] = []
        aer_m: List[AppendEntriesRpc] = []
        aer_s: List[int] = []
        rep_i: List[int] = []
        rep_m: List[AppendEntriesReply] = []
        rep_s: List[int] = []
        for i in hot:
            g = groups[i]
            if g is None:
                continue
            if g.host_term_hint is not None:
                packed[R["host_term_idx"], i] = g.host_term_hint[0]
                packed[R["host_term_val"], i] = g.host_term_hint[1]
                g.host_term_hint = None
            if not g.inbox:
                continue
            from_sid, msg = g.inbox.popleft()
            consumed[i] = (from_sid, msg)
            t = type(msg)
            if t is AppendEntriesRpc:
                aer_i.append(i)
                aer_m.append(msg)
                aer_s.append(g.slot_of(from_sid) if from_sid else 0)
            elif t is AppendEntriesReply:
                rep_i.append(i)
                rep_m.append(msg)
                rep_s.append(g.slot_of(from_sid) if from_sid else 0)
            else:
                self._encode(g, from_sid, msg, packed, i)
            if g.inbox:
                self._hot.add(i)  # more queued: stay hot for next step
        self._pack_hot(packed, aer_i, aer_m, aer_s, rep_i, rep_m, rep_s)
        return jnp.asarray(packed), consumed, packed

    def _build_mailbox_sub(self, act, app_rows=None, written=None):
        """Compact mailbox for the active-set step: one COLUMN PER
        ACTIVE GROUP (power-of-two padded), plus the gather index vector
        mapping column -> group id. ``consumed`` is keyed by column
        position (the egress arrays come back in the same position
        space). Same pop-one-message-per-group semantics as the
        full-width builder."""
        n = len(act)
        # pad floor bounds the number of compiled shapes (straggler
        # tails would otherwise walk every power of two down to 1)
        cap = min(256, self.capacity)
        while cap < n:
            cap <<= 1
        packed = self._mbox_take(cap)
        self._fill_scat(packed, app_rows, written)
        R = self._R
        packed[R["host_term_idx"]].fill(-1)
        packed[R["host_term_val"]].fill(-1)
        gidx = np.full(cap, self.capacity, np.int32)  # pads dropped on scatter
        gidx[:n] = act
        self._hot = set()
        consumed: Dict[int, Tuple[Any, Any]] = {}
        groups = self.groups
        aer_i: List[int] = []
        aer_m: List[AppendEntriesRpc] = []
        aer_s: List[int] = []
        rep_i: List[int] = []
        rep_m: List[AppendEntriesReply] = []
        rep_s: List[int] = []
        for p, i in enumerate(act):
            g = groups[i]
            if g is None:
                continue
            if g.host_term_hint is not None:
                packed[R["host_term_idx"], p] = g.host_term_hint[0]
                packed[R["host_term_val"], p] = g.host_term_hint[1]
                g.host_term_hint = None
            if not g.inbox:
                continue
            from_sid, msg = g.inbox.popleft()
            consumed[p] = (from_sid, msg)
            t = type(msg)
            if t is AppendEntriesRpc:
                aer_i.append(p)
                aer_m.append(msg)
                aer_s.append(g.slot_of(from_sid) if from_sid else 0)
            elif t is AppendEntriesReply:
                rep_i.append(p)
                rep_m.append(msg)
                rep_s.append(g.slot_of(from_sid) if from_sid else 0)
            else:
                self._encode(g, from_sid, msg, packed, p)
            if g.inbox:
                self._hot.add(i)  # more queued: stay hot for next step
        self._pack_hot(packed, aer_i, aer_m, aer_s, rep_i, rep_m, rep_s)
        return (
            jnp.asarray(packed),
            jnp.asarray(gidx),
            np.asarray(act, np.int64),
            consumed,
            packed,
        )

    def _encode(self, g: GroupHost, from_sid, msg, p, i) -> None:
        R = self._R
        p[R["sender_slot"], i] = g.slot_of(from_sid) if from_sid else 0
        if isinstance(msg, AppendEntriesRpc):
            p[R["msg_type"], i] = C.MSG_AER
            p[R["term"], i] = msg.term
            p[R["prev_idx"], i] = msg.prev_log_index
            p[R["prev_term"], i] = msg.prev_log_term
            p[R["num_entries"], i] = len(msg.entries)
            p[R["entries_last_term"], i] = msg.entries[-1].term if msg.entries else 0
            p[R["leader_commit"], i] = msg.leader_commit
        elif isinstance(msg, AppendEntriesReply):
            p[R["msg_type"], i] = C.MSG_AER_REPLY
            p[R["term"], i] = msg.term
            p[R["success"], i] = 1 if msg.success else 0
            p[R["reply_next_idx"], i] = msg.next_index
            p[R["reply_last_idx"], i] = msg.last_index
            p[R["reply_last_term"], i] = msg.last_term
        elif isinstance(msg, RequestVoteRpc):
            p[R["msg_type"], i] = C.MSG_VOTE_REQ
            p[R["term"], i] = msg.term
            p[R["sender_slot"], i] = g.slot_of(msg.candidate_id)
            p[R["cand_last_idx"], i] = msg.last_log_index
            p[R["cand_last_term"], i] = msg.last_log_term
        elif isinstance(msg, RequestVoteResult):
            p[R["msg_type"], i] = C.MSG_VOTE_REPLY
            p[R["term"], i] = msg.term
            p[R["success"], i] = 1 if msg.vote_granted else 0
        elif isinstance(msg, PreVoteRpc):
            p[R["msg_type"], i] = C.MSG_PREVOTE_REQ
            p[R["term"], i] = msg.term
            p[R["sender_slot"], i] = g.slot_of(msg.candidate_id)
            p[R["cand_last_idx"], i] = msg.last_log_index
            p[R["cand_last_term"], i] = msg.last_log_term
            p[R["cand_machine_version"], i] = msg.machine_version
        elif isinstance(msg, PreVoteResult):
            p[R["msg_type"], i] = C.MSG_PREVOTE_REPLY
            p[R["term"], i] = msg.term
            p[R["success"], i] = 1 if msg.vote_granted else 0
            p[R["token"], i] = msg.token

    # -- egress ------------------------------------------------------------

    def _process_egress(self, eg, consumed, aer_dirty, act=None) -> None:
        """Realise one step's egress. ``act`` is None for the full-width
        step (egress row == group id) or the i64 position->gid map of an
        active-set step (egress row == position in ``act``); ``consumed``
        is keyed in the same space as the egress rows."""
        outbound: Dict[str, List[Tuple[ServerId, Any, ServerId]]] = {}

        def queue_send(to: ServerId, msg: Any, frm: ServerId):
            out = outbound.get(to[1])
            if out is None:
                outbound[to[1]] = out = []
            out.append((to, msg, frm))

        groups = self.groups
        needs_host = eg["needs_host"]
        # numpy scalar indexing (plus int()/bool() coercion) in a
        # per-message loop is slow; gather each needed field for exactly
        # the consumed rows in one vector op, then read python ints
        if consumed:
            items = list(consumed.items())
            ci = np.fromiter((i for i, _ in items), np.int64, len(items))
            nh_l = needs_host[ci].tolist()
            code_l = eg["aer_code"][ci].tolist()
            sr_l = eg["send_reply"][ci].tolist()
            term_l = eg["term"][ci].tolist()
            succ_l = eg["success"][ci].tolist()
            nxt_l = eg["next_index"][ci].tolist()
            li_l = eg["last_index"][ci].tolist()
            lt_l = eg["last_term"][ci].tolist()
            for p, (i, (from_sid, msg)) in enumerate(items):
                g = groups[i if act is None else act[i]]
                if g is None:
                    continue
                t = type(msg)
                if t is AppendEntriesRpc:
                    if nh_l[p]:
                        self._host_resolve_aer(g, from_sid, msg, queue_send)
                    elif code_l[p] == C.AER_OK:
                        # the host performs the write and owns the
                        # durable watermark, so it builds the success
                        # ack (possibly deferred until WAL fsync)
                        self._host_write_entries(g, msg)
                        self._ack_aer(g, from_sid, msg, term_l[p], outbound)
                    elif sr_l[p] and from_sid is not None:
                        queue_send(
                            from_sid,
                            AppendEntriesReply(
                                term_l[p], bool(succ_l[p]), nxt_l[p],
                                li_l[p], lt_l[p],
                            ),
                            (g.name, self.name),
                        )
                elif sr_l[p] and from_sid is not None:
                    if t is RequestVoteRpc:
                        if succ_l[p]:
                            # granting a vote resets the election timer
                            # (Raft §3.4): the granter must give its
                            # candidate a full round before campaigning
                            # itself, or dueling candidacies ping-pong
                            g.last_contact = self.clock.monotonic()
                        queue_send(
                            from_sid,
                            RequestVoteResult(term_l[p], bool(succ_l[p])),
                            (g.name, self.name),
                        )
                    elif t is PreVoteRpc:
                        if succ_l[p]:
                            g.last_contact = self.clock.monotonic()
                        queue_send(
                            from_sid,
                            PreVoteResult(term_l[p], msg.token, bool(succ_l[p])),
                            (g.name, self.name),
                        )

        # vectorized change detection: only touched groups pay Python cost
        n = self.n_groups if act is None else len(act)
        applied = (
            self._applied_np[:n] if act is None else self._applied_np[act]
        )
        interesting = np.flatnonzero(
            eg["became_candidate"][:n]
            | eg["became_leader"][:n]
            | eg["term_or_vote_changed"][:n]
            | (eg["commit_advanced_to"][:n] > applied)
            | needs_host[:n]
        )
        touched = (
            interesting.tolist() if len(consumed) == 0
            else list(set(consumed) | set(interesting.tolist()))
        )
        if touched:
            ti = np.asarray(touched, np.int64)
            role_l = eg["role"][ti].tolist()
            gterm_l = eg["term"][ti].tolist()
            leader_l = eg["leader_slot"][ti].tolist()
            tvc_l = eg["term_or_vote_changed"][ti].tolist()
            voted_l = eg["voted_for"][ti].tolist()
            bc_l = eg["became_candidate"][ti].tolist()
            bl_l = eg["became_leader"][ti].tolist()
            ca_l = eg["commit_advanced_to"][ti].tolist()
            nh2_l = needs_host[ti].tolist()
            ag_l = eg["agreed_idx"][ti].tolist()
            now_roles = self.clock.monotonic()
            for p, pos in enumerate(touched):
                i = pos if act is None else int(act[pos])
                g = groups[i]
                if g is None:
                    continue
                new_role = role_l[p]
                if new_role != g.role:
                    self._obs_rec.record(
                        "role_change", node=self.name, group=g.name,
                        term=gterm_l[p],
                        detail=f"{self._ROLE_NAMES.get(g.role, g.role)}->"
                               f"{self._ROLE_NAMES.get(new_role, new_role)}",
                    )
                    # role transitions restart the leaderless-suspicion
                    # window (a just-deposed leader must give the new
                    # one a chance to make contact before suspecting)
                    g.last_contact = now_roles
                if g.role == C.R_LEADER and new_role != C.R_LEADER:
                    # deposed: in-flight linearizable reads must not be
                    # answered from this replica's state, and pending
                    # command futures must redirect rather than hang
                    # their clients until timeout
                    self._lease_revoke(g, "left leader")
                    for q in g.pending_queries:
                        self._reply(q["fut"], ("redirect", None))
                    g.pending_queries = []
                    g.leader_slot = leader_l[p]  # hint before the sweep
                    self._fail_pending(g)
                g.role = new_role
                g.term = gterm_l[p]
                g.leader_slot = leader_l[p]
                if tvc_l[p] and self.meta is not None:
                    # Raft safety: term AND vote must both be durable
                    # before any message leaves this step, or a
                    # restarted member could vote twice in one term
                    uid = f"{g.cluster_name}_{g.name}"
                    self.meta.store(uid, "current_term", g.term)
                    self.meta.store_sync(uid, "voted_for", g.sid_of(voted_l[p]))
                if bc_l[p]:
                    self._hot.add(i)  # keep stepping (single-member self-election)
                    self._broadcast_vote_req(g, queue_send, pre=False)
                if bl_l[p]:
                    self._on_became_leader(g, aer_dirty)
                ci2 = ca_l[p]
                if ci2 > g.last_applied:
                    self._apply_group(g, ci2)
                    aer_dirty.add(i)
                if nh2_l[p] and g.host_term_hint is None:
                    # quorum term lookup outside the device window (the
                    # AER branch may already have claimed the hint slot;
                    # that one retries first and the quorum resolves
                    # next step)
                    agreed = ag_l[p]
                    t2 = g.log.fetch_term(agreed)
                    if t2 is not None:
                        g.host_term_hint = (agreed, t2)
                        self._hot.add(i)

        for node_name, msgs in outbound.items():
            self._send_batch(node_name, msgs)

    def _host_resolve_aer(self, g: GroupHost, from_sid, msg: AppendEntriesRpc, queue_send):
        """Deep backfill: resolve the prev term from the host log and
        re-enqueue with an override (or reject directly when absent)."""
        t = g.log.fetch_term(msg.prev_log_index)
        if t is None:
            li, lt = g.log.last_index_term()
            snap = g.log.snapshot_index_term()
            from ra_tpu.ops import decisions as dec

            nid = dec.aer_failure_next_index(
                g.last_applied, li, msg.prev_log_index, snap[0] if snap else 0
            )
            queue_send(
                from_sid,
                AppendEntriesReply(g.term, False, nid, li, lt),
                (g.name, self.name),
            )
            return
        g.host_term_hint = (msg.prev_log_index, t)
        g.inbox.appendleft((from_sid, msg))  # retry next step with override
        self._hot.add(g.gid)

    def _host_write_entries(self, g: GroupHost, msg: AppendEntriesRpc) -> None:
        if not msg.entries:
            return
        li, _ = g.log.last_index_term()
        if msg.entries[0].index == li + 1:
            # fast path (steady-state pipeline): strictly-new suffix
            to_write = msg.entries
        else:
            to_write = []
            for e in msg.entries:
                if e.index <= li and g.log.fetch_term(e.index) == e.term:
                    continue
                to_write = [x for x in msg.entries if x.index >= e.index]
                break
            if not to_write and msg.entries[-1].index > li:
                to_write = [e for e in msg.entries if e.index > li]
        if to_write:
            first_idx = to_write[0].index
            if first_idx <= li:
                # overwriting a divergent suffix: truncated specials are
                # gone, and any cluster adoption that rode on them must
                # be rolled back. The ack-suppression key is also
                # invalidated — its (sid, term, ack) invariant only
                # holds while acked entries are never truncated
                g.last_ok_sent = None
                # pending futures for truncated indexes are provably
                # dead (the entries are being overwritten): redirect
                # their clients to the new leader now — a clean
                # "redirect" verdict, safe to retry exactly-once
                self._fail_pending(g, from_idx=first_idx, verdict="redirect")
                if g.specials and g.specials[-1] >= first_idx:
                    g.specials = [s for s in g.specials if s < first_idx]
                if g.cluster_history:
                    keep = [h for h in g.cluster_history if h[0] < first_idx]
                    undone = [h for h in g.cluster_history if h[0] >= first_idx]
                    if undone:
                        _, members, voter = undone[0]
                        g.members = list(members)
                        g.voter_status = dict(voter)
                        g.cluster_history = keep
                        self._sync_member_rows(g)
            g.log.write(to_write)
            # followers adopt replicated cluster changes at write time
            # (reference: cluster scan on follower writes,
            # src/ra_server.erl:1005-1040) and index every non-USR
            # entry for the apply fast path. A leader-stamped plain_usr
            # batch skips the scan (the hot pipeline shape).
            if not msg.plain_usr:
                specials = g.specials
                for e in to_write:
                    c = e.cmd
                    if type(c) is not Command:
                        specials.append(e.index)
                        continue
                    k = c.kind
                    if k != USR:
                        specials.append(e.index)
                        if k in (RA_JOIN, RA_LEAVE, RA_CLUSTER_CHANGE):
                            self._adopt_cluster_cmd(g, c, e.index)
            # reconcile the device term ring exactly (clears the
            # multi-entry unknown interval next step). Raft log terms
            # are monotonic, so equal first/last terms mean ONE run —
            # the per-entry split loop only runs for term-crossing
            # batches (rare: a new leader resending mixed history)
            first = to_write[0]
            last = to_write[-1]
            if first.term == last.term:
                self._stage_app(g.gid, first.index, last.index, first.term)
            else:
                lo = prev = first.index
                term = first.term
                for e in to_write[1:]:
                    if e.term != term:
                        self._stage_app(g.gid, lo, prev, term)
                        lo, term = e.index, e.term
                    prev = e.index
                self._stage_app(g.gid, lo, prev, term)
            wi, _ = g.log.last_written()
            if wi >= to_write[-1].index:
                self._stage_written(g.gid, wi)

    def _ack_aer(self, g: GroupHost, from_sid, msg: AppendEntriesRpc, term, outbound):
        """Success ack with the host's durable watermark, anchored to
        what THIS AER covered (a shorter-logged new leader must not see
        acks above its own prev — mirrors the scalar backend); deferred
        until the WAL confirms when the write is still in flight.
        Appends into the caller's per-destination ``outbound`` map (hot
        path: one ack per follower group per step)."""
        last_entry = msg.entries[-1].index if msg.entries else msg.prev_log_index
        wi, wt = g.log.last_written()
        if wi >= last_entry:
            ack = min(wi, last_entry)
            prev = g.last_ok_sent
            now = self.clock.monotonic()
            if (
                prev is not None
                and prev[0] == from_sid
                and prev[1] == term
                and prev[2] == ack
                and now - prev[3] < self.tick_interval_s
            ):
                return  # identical ack just sent: nothing new for the leader
            g.last_ok_sent = (from_sid, term, ack, now)
            # steady state acks exactly at the watermark: reuse its term
            at = wt if ack == wi else g.log.fetch_term(ack)
            out = outbound.get(from_sid[1])
            if out is None:
                outbound[from_sid[1]] = out = []
            out.append((
                from_sid,
                AppendEntriesReply(term, True, ack + 1, ack,
                                   at if at is not None else wt),
                (g.name, self.name),
            ))
        else:
            g.pending_ack = (from_sid, last_entry)

    def _on_became_leader(self, g: GroupHost, aer_dirty) -> None:
        if self.lease_cfg.enabled:
            # fresh leadership starts bare: the lease is earned by this
            # term's own acks, never inherited from stale stamps
            gid = g.gid
            self._lease_expiry[gid] = 0.0
            self._lease_sent[gid, :] = 0.0
            self._lease_basis[gid, :] = 0.0
            self._lease_renew_t[gid] = 0.0
            self._lease_dirty.discard(gid)
        li, _ = g.log.last_index_term()
        g.next_index = [li + 1] * len(g.members)
        g.commit_sent = [0] * len(g.members)
        g.match_hint = [0] * len(g.members)
        g.last_ack = {}
        g.leader_slot = g.self_slot
        leaderboard.record(g.cluster_name, (g.name, self.name), tuple(g.members))
        # the new term's noop (commit gate + version carrier)
        idx = g.log.next_index()
        g.log.append(Entry(index=idx, term=g.term, cmd=Command(kind=NOOP)))
        g.specials.append(idx)
        g.noop_index = idx
        g.noop_committed = False
        g.cluster_change_permitted = False
        self._stage_app(g.gid, idx, idx, g.term)
        wi, _ = g.log.last_written()
        if wi >= idx:
            self._stage_written(g.gid, wi)
        aer_dirty.add(g.gid)

    def _apply_group(self, g: GroupHost, commit_index: int) -> None:
        li, _ = g.log.last_index_term()
        hi = min(commit_index, li)
        if hi <= g.last_applied:
            return
        # apply-duration histogram is SAMPLED (same mask as the commit
        # stages): at 10k groups per wave an unconditional record per
        # group is a measurable tax on the loop it measures
        _t_apply0 = (
            time.perf_counter_ns() if (g.gid & self._lat_mask) == 0 else 0
        )
        # commit-stage sample: the tracked entry commits (and applies)
        # in THIS call iff it is durable and within hi; ``lat`` stays a
        # local None otherwise so the hot loop pays one check per entry
        lat = g.lat
        if lat is not None:
            if lat[3] == 0 or lat[0] > hi:
                lat = None  # not durable yet / commits in a later round
            elif lat[4] == 0:
                lat[4] = time.monotonic_ns()
                self._commit_h["durable_commit"].record(lat[4] - lat[3])
        # hot loop: locals bound once, apply-result normalization inlined
        # (machines return (state, reply) or (state, reply, effects))
        entries = g.log.fetch_range(g.last_applied + 1, hi)
        if len(entries) != hi - g.last_applied:
            # fail fast like fold(): a gap below the commit index is a
            # log integrity violation, never something to skip silently
            raise KeyError(
                f"missing log entries applying ({g.last_applied}, {hi}] "
                f"in group {g.name}: got {len(entries)}"
            )
        pending = g.pending_replies
        machine = g.machine
        mver = g.effective_machine_version
        state = g.machine_state
        is_leader = g.role == C.R_LEADER
        specials = g.specials
        if specials and specials[0] <= g.last_applied:
            # stale entries (already applied or compacted away)
            g.specials = specials = [s for s in specials if s > g.last_applied]
        if (
            not pending
            and len(entries) > 1
            and (not specials or specials[0] > hi)
        ):
            # plain user-command run with no replies owed (the specials
            # index proves it without scanning): offer the machine the
            # whole payload batch at once (apply_many hook)
            batched = machine.which_module(mver).apply_many(
                {"index": hi, "term": entries[-1].term,
                 "machine_version": mver},
                [e.cmd.data for e in entries], state,
            )
            if batched is not None:
                g.machine_state = batched
                g.last_applied = hi
                self._applied_np[g.gid] = hi
                if self.lease_cfg.enabled:
                    self._lease_applied(g, hi)
                if lat is not None:
                    # noreply pipeline shape: the reply stage is the
                    # post-apply bookkeeping fan-out (no future owed)
                    now2 = time.monotonic_ns()
                    self._commit_h["commit_apply"].record(now2 - lat[4])
                    self._commit_gates(g, hi, is_leader)
                    self._commit_h["apply_reply"].record(
                        time.monotonic_ns() - now2
                    )
                    g.lat = None
                    self._lat_gids.discard(g.gid)
                else:
                    self._commit_gates(g, hi, is_leader)
                if _t_apply0:
                    self._wave_h["apply"].record(
                        time.perf_counter_ns() - _t_apply0
                    )
                return
        mac = machine.which_module(mver)
        apply_fn = mac.apply
        me = (g.name, self.name)
        for entry in entries:
            cmd = entry.cmd
            if not isinstance(cmd, Command):
                continue
            kind = cmd.kind
            if kind == USR:
                res = apply_fn(
                    {"index": entry.index, "term": entry.term,
                     "machine_version": mver},
                    cmd.data, state,
                )
                state = res[0]
                if len(res) > 2 and res[2]:
                    g.machine_state = state  # effects may read/snapshot it
                    self._realise_effects(g, res[2], is_leader)
                if lat is not None and entry.index == lat[0]:
                    t_ap = time.monotonic_ns()
                    self._commit_h["commit_apply"].record(t_ap - lat[4])
                    if pending:
                        fut = pending.pop(entry.index, None)
                        if fut is not None and is_leader:
                            self._reply(fut, ("ok", res[1], me))
                    self._commit_h["apply_reply"].record(
                        time.monotonic_ns() - t_ap
                    )
                    g.lat = lat = None
                    self._lat_gids.discard(g.gid)
                    continue
                if pending:
                    fut = pending.pop(entry.index, None)
                    if fut is not None and is_leader:
                        self._reply(fut, ("ok", res[1], me))
                continue
            if kind == NOOP:
                if cmd.machine_version > g.effective_machine_version:
                    # machine-version bump rides the term noop
                    # (reference: src/ra_server.erl:3357-3417)
                    old_v = g.effective_machine_version
                    g.effective_machine_version = mver = cmd.machine_version
                    mac = machine.which_module(mver)
                    apply_fn = mac.apply
                    res = apply_fn(
                        {"index": entry.index, "term": entry.term,
                         "machine_version": mver},
                        ("machine_version", old_v, mver), state,
                    )
                    state = res[0]
                if is_leader and entry.index >= g.noop_index:
                    # the new leader's own entry committed: unlock
                    # membership changes and linearizable reads
                    g.noop_committed = True
                    if entry.index >= g.cluster_index:
                        g.cluster_change_permitted = True
            elif kind in (RA_JOIN, RA_LEAVE, RA_CLUSTER_CHANGE):
                if entry.index >= g.cluster_index:
                    # change committed: the next one may proceed
                    g.cluster_change_permitted = is_leader and g.noop_committed
            if pending and is_leader:
                fut = pending.pop(entry.index, None)
                if fut is not None:
                    self._reply(fut, ("ok", None, me))
        g.machine_state = state
        g.last_applied = hi
        self._applied_np[g.gid] = hi
        if self.lease_cfg.enabled:
            self._lease_applied(g, hi)
        if lat is not None:
            # tracked entry was non-USR (rare): close the sample here
            now2 = time.monotonic_ns()
            self._commit_h["commit_apply"].record(now2 - lat[4])
            self._commit_h["apply_reply"].record(time.monotonic_ns() - now2)
            g.lat = None
            self._lat_gids.discard(g.gid)
        if _t_apply0:
            self._wave_h["apply"].record(time.perf_counter_ns() - _t_apply0)

    def _commit_gates(self, g: GroupHost, hi: int, is_leader: bool) -> None:
        """Noop-commit gate for apply paths that skip the per-entry loop
        (cluster entries always force the per-entry path, so reaching
        ``hi >= noop_index`` here means the noop itself committed)."""
        if is_leader and not g.noop_committed and hi >= g.noop_index:
            g.noop_committed = True
            if g.cluster_index <= hi:
                g.cluster_change_permitted = True

    # -- machine effects (batch-backend executor; reference vocabulary:
    # src/ra_machine.erl:131-159, realised per src/ra_server_proc.erl
    # handle_effects) -----------------------------------------------------

    def _realise_effects(self, g: GroupHost, effs, is_leader: bool = True) -> None:
        """Machine effects. Log effects (release_cursor / checkpoint)
        are realised on EVERY replica — followers must truncate too;
        the rest (send_msg, mod_call, timer, log read, reply, aux) are
        leader-only on the apply path. Monitor/demonitor effects need
        the actor runtime's monitor registry — groups using them should
        run on the per_group_actor backend."""
        for eff in effs:
            if not is_leader and not isinstance(
                eff, (fx.ReleaseCursor, fx.Checkpoint, fx.TryAppend)
            ):
                continue
            if isinstance(eff, fx.ReleaseCursor):
                mac = g.machine.which_module(g.effective_machine_version)
                g.log.update_release_cursor(
                    eff.index,
                    tuple(m for m in g.members if m is not None),
                    g.effective_machine_version,
                    eff.machine_state,
                    live_indexes=tuple(mac.live_indexes(eff.machine_state)),
                )
                self._sync_snapshot_floor(g)
            elif isinstance(eff, fx.Checkpoint):
                mac = g.machine.which_module(g.effective_machine_version)
                g.log.checkpoint(
                    eff.index,
                    tuple(m for m in g.members if m is not None),
                    g.effective_machine_version,
                    eff.machine_state,
                    live_indexes=tuple(mac.live_indexes(eff.machine_state)),
                )
            elif isinstance(eff, fx.SendMsg):
                cb = self.send_msg_cb
                if cb is not None:
                    try:
                        cb(eff.to, eff.msg, eff.options)
                    except Exception:  # noqa: BLE001
                        pass
                elif callable(getattr(eff.to, "set_result", None)) or callable(eff.to):
                    self._reply(eff.to, eff.msg)
                elif isinstance(eff.to, tuple) and len(eff.to) == 2:
                    self.transport.send(eff.to, eff.msg, from_sid=(g.name, self.name))
            elif isinstance(eff, fx.ModCall):
                try:
                    eff.fn(*eff.args)
                except Exception:  # noqa: BLE001
                    pass
            elif isinstance(eff, fx.Timer):
                self._machine_timer(g, eff)
            elif isinstance(eff, fx.LogRead):
                entries = g.log.sparse_read(list(eff.indexes))
                out = eff.fn(entries)
                if out is not None:
                    # apply runs on a drainer thread under the state
                    # lock: self-deliveries ride the internal queue
                    # straight into the next drain (never the rings —
                    # a full lane must not block the drainer on itself)
                    self._deliver_internal(g.name, out)
            elif isinstance(eff, fx.Reply):
                self._reply(eff.from_ref, eff.reply)
            elif isinstance(eff, fx.Aux):
                self._deliver_internal(g.name, ("aux", "cast", eff.cmd, None))
            elif isinstance(eff, (fx.Append, fx.TryAppend)):
                # machine-originated command re-enters via the command
                # queue: the next step's drain appends it on the leader;
                # a TryAppend on a non-leader redirects per command
                # routing (reference: src/ra_server_proc.erl:1604-1615).
                # Only the leader's copy carries the reply ref — every
                # replica realises a TryAppend, and a follower's
                # redirect must not race the leader's ok on one future
                self._deliver_internal(
                    g.name,
                    Command(kind=USR, data=eff.cmd,
                            reply_mode=eff.reply_mode,
                            from_ref=eff.from_ref if is_leader else None,
                            internal=True),
                )

    def _sync_snapshot_floor(self, g: GroupHost) -> None:
        snap = g.log.snapshot_index_term()
        if snap is not None and snap[0] > g.snap_floor:
            g.snap_floor = snap[0]
            gid = jnp.asarray([g.gid], jnp.int32)
            self.state = C.record_snapshot(
                self.state, gid,
                jnp.asarray([snap[0]], jnp.int32),
                jnp.asarray([snap[1]], jnp.int32),
            )

    def _machine_timer(self, g: GroupHost, eff: fx.Timer) -> None:
        old = g.machine_timers.pop(eff.name, None)
        if old is not None:
            old.cancel()
        if eff.ms is None:
            return

        def fire():
            g.machine_timers.pop(eff.name, None)
            if self.running and g.role == C.R_LEADER:
                self.deliver(
                    (g.name, self.name),
                    Command(kind=USR, data=("timeout", eff.name),
                            internal=True),
                    None,
                )

        t = threading.Timer(eff.ms / 1000.0, fire)
        t.daemon = True
        t.start()
        g.machine_timers[eff.name] = t

    def _fail_pending(self, g: GroupHost, counter: str = "pending_redirected",
                      from_idx: int = 0, verdict: str = "maybe") -> None:
        """Answer pending await_consensus futures instead of silently
        dropping them (root cause of the round-5 command wedge: a leader
        deposed between append and commit popped its pending futures on
        apply without replying, hanging every waiting client for its
        full timeout).

        The verdict matters for exactly-once semantics:
        - ``"redirect"`` — the entry is provably DEAD (truncated away):
          clients may retry with no duplicate risk;
        - ``"maybe"`` (default) — deposed with the entry still in the
          log: it MAY commit under the new leader. process_command
          surfaces this as an immediate error unless the caller opted
          into at-least-once retries — a transparent retry here is how
          the overload harness caught a double-applied incr.

        ``from_idx`` limits the sweep to truncated indexes; 0 fails
        all."""
        pending = g.pending_replies
        if not pending:
            return
        leader = g.sid_of(g.leader_slot)
        if leader == (g.name, self.name):
            leader = None  # never redirect a caller back to ourselves
        doomed = (
            list(pending) if from_idx <= 0
            else [i for i in pending if i >= from_idx]
        )
        for i in doomed:
            self._reply(pending.pop(i), (verdict, leader))
        if doomed:
            self.counters.incr(counter, len(doomed))
            self._obs_rec.record(
                "deposition", node=self.name, group=g.name, term=g.term,
                detail=f"{len(doomed)} pending futures answered "
                       f"{verdict!r} ({counter})",
            )

    # -- outbound ----------------------------------------------------------

    def _reply(self, fut, value) -> None:
        setter = getattr(fut, "set_result", None)
        if setter is not None:
            setter(value)
        elif callable(fut):
            fut(value)

    def _send_batch(self, node_name: str, msgs) -> None:
        """Per-destination batch send. With the started pipelined loop,
        the fan-out hands off to the dedicated sender thread through a
        bounded ring — the step/egress/WAL threads never pay transport
        cost; a full handoff ring falls back to an inline send (bounded
        handoff never drops)."""
        if self._egress_on:
            if self._egress_rings.publish((node_name, msgs)):
                return
            self.counters.incr("egress_thread_ring_full")
        self._send_batch_inline(node_name, msgs)

    def _send_batch_inline(self, node_name: str, msgs) -> None:
        node = self.registry.get(node_name)
        if node is None:
            return
        if isinstance(node, BatchCoordinator) and node is not self:
            # one hop for the whole batch; honor the same fault-injection
            # and liveness rules as InProcTransport.send
            if not node.running or (self.name, node_name) in self.transport.blocked:
                self.transport.dropped += len(msgs)
                return
            drop = self.transport.drop_fn
            if drop is None:
                triples = [(to[0], frm, msg) for to, msg, frm in msgs]
            else:
                triples = []
                for to, msg, frm in msgs:
                    if drop(to, msg):
                        self.transport.dropped += 1
                    else:
                        triples.append((to[0], frm, msg))
            if triples:
                # peer's ingress lane full: the peer sheds only the
                # lossy subset (counted here) and overflow-queues the
                # must-deliver remainder — never a batch-level drop
                self.transport.dropped += node.ingest_batch(triples)
            return
        if self._nat_egress and len(msgs) > 1:
            # remote batch: seal + length-frame every AER/ack frame for
            # this destination in ONE GIL-released native call on the
            # sender path (rt_seal_frames). -1 = native unavailable or
            # tcp failpoints armed: fall through to per-message send so
            # fire/mangle semantics apply frame by frame.
            sb = getattr(self.transport, "send_batch", None)
            if sb is not None:
                sent = sb(node_name, msgs)
                if sent >= 0:
                    self.counters.incr("native_egress_batches")
                    self.counters.incr("native_egress_frames", sent)
                    return
                self.counters.incr("native_fallbacks")
        for to, msg, frm in msgs:
            self.transport.send(to, msg, from_sid=frm)

    # -- leases (docs/INTERNALS.md §20) ------------------------------------

    def _lease_sync(self, g: GroupHost) -> None:
        """Mirror the group's voter set into the lease arrays. Runs at
        registration and on every membership scatter; a membership
        change while leading revokes (the old lease quorum may not
        intersect the new vote quorum)."""
        voting = np.zeros(self.P, dtype=bool)
        for i, m in enumerate(g.members):
            if m is not None and g.voter_status.get(i) == "voter":
                voting[i] = True
        self._lease_voters[g.gid] = voting
        self._lease_quorum[g.gid] = int(voting.sum()) // 2 + 1
        self._lease_self[g.gid] = g.self_slot

    def _lease_stamp_send(self, gid: int, slot: int, now: float) -> None:
        """Oldest-outstanding-send stamp for one peer slot (later sends
        before an ack keep the older, more conservative stamp)."""
        if self._lease_sent[gid, slot] == 0.0:
            self._lease_sent[gid, slot] = now

    def _lease_credit(self, g: GroupHost, slot: int) -> None:
        """Fold a same-term response from ``slot`` into its ack basis
        (send-basis rule — never the receive time)."""
        gid = g.gid
        t0 = self._lease_sent[gid, slot]
        if t0 == 0.0:
            return
        self._lease_sent[gid, slot] = 0.0
        if t0 > self._lease_basis[gid, slot]:
            self._lease_basis[gid, slot] = t0
            self._lease_dirty.add(gid)

    def _lease_refresh(self) -> None:
        """Recompute expiries for groups with newly credited bases: one
        vectorized k-th-largest pass over the dirty set (the (G,)-array
        analog of LeaseTracker.refresh). Expiry only ever advances."""
        d = self._lease_dirty
        if not d:
            return
        from ra_tpu.lease import lease_expiry, quorum_bases

        gids = np.fromiter(d, np.int64, len(d))
        d.clear()
        now = self.clock.monotonic()
        bases = self._lease_basis[gids].copy()
        # the leader's own slot always counts as an ack at ``now``
        bases[np.arange(len(gids)), self._lease_self[gids]] = now
        qb = quorum_bases(bases, self._lease_voters[gids],
                          self._lease_quorum[gids])
        cfg = self.lease_cfg
        exp = np.where(qb > 0.0, lease_expiry(
            qb, cfg.election_timeout_s, cfg.safety_factor,
            cfg.drift_epsilon_s), 0.0)
        cur = self._lease_expiry[gids]
        fresh = (exp > now) & (cur <= now) & (exp > cur)
        self._lease_expiry[gids] = np.maximum(cur, exp)
        if fresh.any():
            for gid in gids[fresh].tolist():
                g = self.groups[gid]
                if g is not None:
                    self._obs_rec.record(
                        "lease_acquired", node=self.name, group=g.name,
                        term=g.term,
                        detail=f"expires in "
                               f"{self._lease_expiry[gid] - now:.3f}s",
                    )

    def _lease_revoke(self, g: GroupHost, why: str) -> None:
        """Eager revocation: clears the expiry AND the stamp/basis rows
        so acks already in flight cannot resurrect a lease for a
        leadership this group no longer holds."""
        if not self.lease_cfg.enabled:
            return
        gid = g.gid
        had = self._lease_expiry[gid] > self.clock.monotonic()
        self._lease_expiry[gid] = 0.0
        self._lease_sent[gid, :] = 0.0
        self._lease_basis[gid, :] = 0.0
        self._lease_dirty.discard(gid)
        if had:
            self.counters.incr("read_lease_revocations")
            self._obs_rec.record(
                "lease_lost", node=self.name, group=g.name, term=g.term,
                detail=why,
            )

    def _stickiness_lapsed(self, g: GroupHost, now: float) -> bool:
        """False while this replica's promise to its current leader
        still stands: (pre-)votes for OTHER candidates are disregarded
        for one election timeout after the last leader contact."""
        if g.role == C.R_LEADER:
            return False
        if g.leader_slot < 0:
            return True
        return now - g.lease_contact >= self.election_timeout_s

    def _read_staleness(self, g: GroupHost) -> float:
        """Upper bound on this replica's staleness vs the leader's
        wall clock (inf until a leader stamp has been applied)."""
        if g.fresh_ts <= 0.0:
            return float("inf")
        return max(0.0, self.clock.time() - g.fresh_ts) \
            + self.lease_cfg.drift_epsilon_s

    def _staleness_hist(self):
        if self._stale_h is None:
            from ra_tpu import obs as _obs

            self._stale_h = _obs.staleness_hist(self.name)
        return self._stale_h

    def _lease_applied(self, g: GroupHost, hi: int) -> None:
        """Freshness-floor upkeep after apply reached ``hi``: leaders
        stamp their own wall clock once fully caught up; followers
        promote a pending (leader_commit, commit_ts) anchor whose
        commit point is now applied."""
        if g.role == C.R_LEADER:
            # host mirror: applied == committed, so the leader is
            # always fully caught up here
            g.fresh_ts = self.clock.time()
            return
        anchor_idx, anchor_ts = g.fresh_anchor
        if anchor_ts > 0.0 and anchor_idx <= hi:
            if anchor_ts > g.fresh_ts:
                g.fresh_ts = anchor_ts
            g.fresh_anchor = (0, 0.0)

    def _broadcast_vote_req(self, g: GroupHost, queue_send, pre: bool,
                            force: bool = False) -> None:
        li, lt = g.log.last_index_term()
        sid = (g.name, self.name)
        if pre:
            rpc = PreVoteRpc(
                term=g.term, token=g.pre_vote_token, candidate_id=sid, version=1,
                machine_version=g.machine.version(), last_log_index=li,
                last_log_term=lt,
            )
        else:
            rpc = RequestVoteRpc(
                term=g.term, candidate_id=sid, last_log_index=li,
                last_log_term=lt, force=force,
            )
        for s, member in enumerate(g.members):
            if s != g.self_slot and member is not None:
                queue_send(member, rpc, sid)

    _NEEDS_SNAPSHOT = object()  # rpc-cache sentinel

    def _send_aers(self, aer_dirty) -> None:
        outbound: Dict[str, List] = {}
        now = self.clock.monotonic()
        for gid in aer_dirty:
            g = self.groups[gid]
            if g is None:
                continue
            ft = g.fresh_tail  # valid for THIS step only, whoever we are
            g.fresh_tail = None
            if g.role != C.R_LEADER:
                continue
            li, _ = g.log.last_index_term()
            commit = g.last_applied  # host mirror of commit (applied == committed here)
            sid = (g.name, self.name)
            # lease (§20): every AER is a quorum-bearing send — stamp
            # the oldest outstanding send per peer, and carry the wall
            # clock the commit point was current at (follower
            # freshness). 0.0 when lease-off: receivers then never
            # advance their freshness floor.
            lease_on = self.lease_cfg.enabled
            cts = self.clock.time() if lease_on else 0.0
            # peers at the same next_index (the steady-state pipeline)
            # share ONE immutable rpc: one entry fetch, one object
            rpc_cache: Dict[int, Any] = {}
            for s, member in enumerate(g.members):
                if s == g.self_slot or member is None:
                    continue
                nxt = g.next_index[s]
                if nxt > li and commit <= g.commit_sent[s]:
                    continue  # nothing new to say
                if nxt <= li:
                    # per-peer pipeline window: never run more than
                    # max_pipeline_count entries ahead of the peer's
                    # CONFIRMED match (reference: Next - Match <=
                    # ?MAX_PIPELINE_COUNT, src/ra_server.erl:2308-2329).
                    # An actively-acking peer reopens the window by
                    # itself; a silent one gets an EMPTY probe at the
                    # current next point (the actor backend's
                    # empty-probe shape): its success ack rebuilds
                    # match_hint at the peer's true tail, its reject
                    # hint rewinds next_index — either resynchronizes
                    # without blindly re-sending the whole log (a fresh
                    # leader starts at match_hint 0, so a rewind-to-
                    # match here would re-replicate or snapshot-stream
                    # to every caught-up peer).
                    mh = g.match_hint[s] if s < len(g.match_hint) else 0
                    if nxt - mh > self.max_pipeline_count:
                        la = g.last_ack.get(s)
                        if la is not None and now - la <= self.tick_interval_s:
                            continue  # window full but acks are flowing
                        g.last_ack[s] = now  # one probe per tick per peer
                        self.counters.incr("stale_peer_resends")
                        prev_idx = nxt - 1
                        prev_term = g.log.fetch_term(prev_idx)
                        snap = g.log.snapshot_index_term()
                        if prev_term is None or (
                            snap is not None and prev_idx < snap[0]
                        ):
                            self._start_snapshot_sender(g, member)
                            continue
                        if lease_on:
                            self._lease_stamp_send(gid, s, now)
                        outbound.setdefault(member[1], []).append((
                            member,
                            AppendEntriesRpc(
                                term=g.term, leader_id=sid,
                                prev_log_index=prev_idx,
                                prev_log_term=prev_term,
                                leader_commit=commit, entries=(),
                                commit_ts=cts,
                            ),
                            sid,
                        ))
                        g.commit_sent[s] = commit
                        continue
                rpc = rpc_cache.get(nxt)
                if rpc is None and ft is not None and nxt >= ft[0]:
                    # steady state: the entries were appended by THIS
                    # step's _handle_commands — ship them straight
                    # through (no log re-read; all plain USR, one term)
                    first_f, prev_f, term_f, ents_f = ft
                    k = nxt - first_f
                    if k < len(ents_f):
                        rpc = AppendEntriesRpc(
                            term=g.term, leader_id=sid, prev_log_index=nxt - 1,
                            prev_log_term=prev_f if k == 0 else term_f,
                            leader_commit=commit,
                            entries=tuple(ents_f[k:k + self.aer_batch_size]),
                            plain_usr=True, commit_ts=cts,
                        )
                        rpc_cache[nxt] = rpc
                if rpc is None:
                    entries: List[Entry] = []
                    if nxt <= li:
                        entries = g.log.fetch_range(
                            nxt, min(li, nxt + self.aer_batch_size - 1)
                        )
                    prev_idx = nxt - 1
                    prev_term = g.log.fetch_term(prev_idx)
                    snap = g.log.snapshot_index_term()
                    if prev_term is None or (
                        snap is not None and prev_idx < snap[0]
                    ):
                        rpc = self._NEEDS_SNAPSHOT
                    else:
                        # stamp plain-USR batches so the receiver skips
                        # its per-entry specials scan. g.specials is
                        # only exhaustive ABOVE last_applied (older
                        # rows are pruned), so lagging-peer backfills
                        # below the applied floor never get the stamp.
                        plain = False
                        if entries and nxt > g.last_applied:
                            sp = g.specials
                            if not sp:
                                plain = True
                            else:
                                i = bisect_left(sp, nxt)
                                plain = (
                                    i >= len(sp)
                                    or sp[i] > entries[-1].index
                                )
                        rpc = AppendEntriesRpc(
                            term=g.term, leader_id=sid, prev_log_index=prev_idx,
                            prev_log_term=prev_term, leader_commit=commit,
                            entries=tuple(entries), plain_usr=plain,
                            commit_ts=cts,
                        )
                    rpc_cache[nxt] = rpc
                if rpc is self._NEEDS_SNAPSHOT:
                    # peer is behind our compacted floor: stream a snapshot
                    self._start_snapshot_sender(g, member)
                    continue
                if lease_on:
                    self._lease_stamp_send(gid, s, now)
                outbound.setdefault(member[1], []).append((member, rpc, sid))
                if rpc.entries:
                    g.next_index[s] = rpc.entries[-1].index + 1
                g.commit_sent[s] = commit
        for node_name, msgs in outbound.items():
            self._send_batch(node_name, msgs)

    # -- rare paths --------------------------------------------------------

    def _handle_rare(self, g: GroupHost, msg, from_sid,
                     rare_out: Optional[Dict[str, List]] = None) -> None:
        """``rare_out``: the realisation pass's shared per-destination
        outbound — fan-outs append into it and the caller ships ONE
        batch per destination after the whole rare loop (a per-group
        send per election would overflow a peer's bounded ingress lane
        under a 10k-group storm). A None caller (direct invocations in
        tests) ships inline."""
        if isinstance(msg, ElectionTimeout):
            if g.role == C.R_LEADER:
                return
            if msg.armed_at and g.last_contact > msg.armed_at:
                # stale detector trigger: the group has seen contact (or
                # restarted its own election window) since the suspicion
                # was confirmed — a trigger delayed behind a stall (jit
                # compile, long egress) must not pile a second election
                # onto a round that is already resolving
                return
            if g.voter_status.get(g.self_slot) != "voter":
                return  # nonvoters never start elections
            if self.lease_cfg.enabled and not self._stickiness_lapsed(
                g, self.clock.monotonic()
            ):
                # standing is stickiness-gated too (§20): a candidate
                # grants its own vote, and could be the one quorum-
                # intersection voter a live leader's lease counts on
                return
            self._obs_rec.record(
                "election", node=self.name, group=g.name, term=g.term,
                detail="pre_vote round started",
            )
            # start pre-vote host-side: queue the role scatter (batched
            # across groups at the next step), broadcast the rpc
            self._pending_roles.append((g.gid, C.R_PRE_VOTE))
            g.role = C.R_PRE_VOTE
            g.pre_vote_token += 1
            g.last_contact = self.clock.monotonic()  # election-retry window restarts
            self._hot.add(g.gid)  # force steps so the election progresses
            if len(g.members) == 1:
                return  # the next device steps self-elect
            outbound: Dict[str, List] = (
                {} if rare_out is None else rare_out
            )

            def queue_send(to, m, frm):
                outbound.setdefault(to[1], []).append((to, m, frm))

            self._broadcast_vote_req(g, queue_send, pre=True)
            if rare_out is None:
                for node_name, msgs in outbound.items():
                    self._send_batch(node_name, msgs)
            return
        if isinstance(msg, tuple) and msg and msg[0] == "local_query":
            # ("local_query", fn, fut) or a 4-tuple carrying the
            # caller's max_staleness_s bound (docs/INTERNALS.md §20):
            # the bounded form only answers when the leader-stamped
            # freshness floor proves local state is recent enough
            fn, fut = msg[1], msg[2]
            if len(msg) > 3 and msg[3] is not None:
                staleness = self._read_staleness(g)
                self._staleness_hist().record_seconds(
                    min(staleness, 3600.0)
                )
                if staleness > msg[3]:
                    self.counters.incr("read_stale_rejected")
                    self._reply(
                        fut, ("stale", staleness, g.sid_of(g.leader_slot))
                    )
                    return
                self.counters.incr("read_local_bounded")
            self._reply(fut, ("ok", fn(g.machine_state), g.sid_of(g.leader_slot)))
            return
        if isinstance(msg, TimeoutNow):
            # leadership-transfer trigger from any backend's leader: a
            # FORCED election, no pre-vote round (Raft §3.10; matches
            # the scalar backend's _call_for_election on TimeoutNow) —
            # one round trip to leadership, and correct independent of
            # any leader-stickiness in the pre-vote grant.
            if g.role == C.R_LEADER or g.voter_status.get(g.self_slot) != "voter":
                return
            g.role = C.R_CANDIDATE
            g.term += 1
            g.leader_slot = -1
            g.last_contact = self.clock.monotonic()
            if self.meta is not None:
                # term AND self-vote must be durable before any vote
                # request leaves this node (restart double-vote safety)
                uid = f"{g.cluster_name}_{g.name}"
                self.meta.store(uid, "current_term", g.term)
                self.meta.store_sync(uid, "voted_for", (g.name, self.name))
            self.state = C.force_elections(
                self.state, jnp.asarray([g.gid], jnp.int32)
            )
            self._hot.add(g.gid)  # keep stepping (single-member self-election)
            outbound2: Dict[str, List] = (
                {} if rare_out is None else rare_out
            )

            def queue_send2(to, m, frm):
                outbound2.setdefault(to[1], []).append((to, m, frm))

            # forced candidacy (§20): the transferring leader revoked
            # its lease before sending TimeoutNow, so voters may skip
            # stickiness for this request
            self._broadcast_vote_req(g, queue_send2, pre=False, force=True)
            if rare_out is None:
                for node_name, msgs in outbound2.items():
                    self._send_batch(node_name, msgs)
            return
        if isinstance(msg, tuple) and msg and msg[0] == "transfer_leadership":
            _, target, fut = msg
            me = (g.name, self.name)
            if g.role != C.R_LEADER:
                self._reply(fut, ("redirect", g.sid_of(g.leader_slot)))
                return
            target = tuple(target)
            if target == me:
                self._reply(fut, ("ok", "already_leader"))
                return
            slot = g.slot_of(target)
            if slot < 0:
                self._reply(fut, ("error", "unknown_member"))
                return
            if g.voter_status.get(slot) != "voter":
                self._reply(fut, ("error", "non_voter"))
                return
            li, _ = g.log.last_index_term()
            # gate on the device's CONFIRMED match for the slot — the
            # host next_index advances optimistically at send time, so
            # a pipelined-to-but-unacked peer must not pass (mirrors
            # the scalar backend's match_index gate). One device read;
            # transfers are rare.
            confirmed = int(np.asarray(self.state.match_index)[g.gid, slot])
            if confirmed != li:
                self._reply(fut, ("error", "not_up_to_date"))
                return
            self._reply(fut, ("ok", None))
            # revoke BEFORE the transfer trigger leaves this node: the
            # target's forced (stickiness-bypassing) election is only
            # safe because no lease-holding leader remains (§20)
            self._lease_revoke(g, "leadership transfer")
            self._send_batch(target[1], [(target, TimeoutNow(), me)])
            return
        if isinstance(msg, tuple) and msg and msg[0] == "lane_recover":
            # watchdog strike 1: force a device re-step (fresh quorum
            # scan over current match/written state) and probe every
            # peer — their acks or reject hints resynchronize
            # replication from the confirmed point
            self.counters.incr("lane_recoveries")
            self._hot.add(g.gid)
            if g.role == C.R_LEADER:
                now = self.clock.monotonic()
                for s, m in enumerate(g.members):
                    if (
                        m is not None and s != g.self_slot
                        and s < len(g.commit_sent)
                    ):
                        g.commit_sent[s] = -1
                        g.last_ack.setdefault(s, now)
                self._send_aers({g.gid})
            return
        if isinstance(msg, tuple) and msg and msg[0] == "lane_fail":
            # watchdog second strike: recovery did not move the lane —
            # bound the failure so clients retry elsewhere instead of
            # hanging until their timeout
            self._fail_pending(g, counter="lane_redirects")
            return
        if isinstance(msg, tuple) and msg and msg[0] == "resync":
            if g.role == C.R_LEADER:
                now = self.clock.monotonic()
                for s in msg[1]:
                    if s < len(g.commit_sent):
                        # -1 sentinel: the probe must fire even at
                        # commit 0 (a fresh leader's lost noop AER)
                        g.commit_sent[s] = -1
                        g.last_ack.setdefault(s, now)
                self._send_aers({g.gid})
            return
        if isinstance(msg, tuple) and msg and msg[0] == "machine_tick":
            mac = g.machine.which_module(g.effective_machine_version)
            effs = mac.tick(msg[1], g.machine_state)
            if effs and g.role == C.R_LEADER:
                self._realise_effects(g, effs)
            return
        if isinstance(msg, tuple) and msg and msg[0] == "consistent_query":
            self._handle_consistent_query(g, msg[1], msg[2])
            return
        if isinstance(msg, HeartbeatRpc):
            # follower side of the query-index leadership confirmation.
            # A higher term is adopted before acking (the scalar backend
            # goes through _update_term, server.py; an ack from a member
            # that never acknowledged the term would be meaningless).
            if from_sid is not None:
                if msg.term >= g.term:
                    g.last_contact = self.clock.monotonic()
                    if self.lease_cfg.enabled:
                        g.lease_contact = g.last_contact
                    if msg.term > g.term or g.role != C.R_FOLLOWER:
                        self._adopt_term(g, msg.term, leader_sid=from_sid)
                    elif g.leader_slot < 0:
                        g.leader_slot = g.slot_of(from_sid)
                    reply = HeartbeatReply(term=msg.term, query_index=msg.query_index)
                else:
                    reply = HeartbeatReply(term=g.term, query_index=-1)
                self._send_batch(
                    from_sid[1], [(from_sid, reply, (g.name, self.name))]
                )
            return
        if isinstance(msg, HeartbeatReply):
            self._handle_heartbeat_reply(g, msg, from_sid)
            return
        if isinstance(msg, tuple) and msg and msg[0] == "aux":
            self._handle_aux(g, msg[1], msg[2], msg[3])
            return
        if isinstance(msg, tuple) and msg and msg[0] == "state_query":
            _, fn, fut = msg
            self._reply(fut, ("ok", fn(g), g.sid_of(g.leader_slot)))
            return
        if isinstance(msg, tuple) and msg and msg[0] == "force_shrink":
            # disaster recovery: restrict the cluster to this member and
            # elect. Mirrors the Server path: membership shrinks, a
            # durable 'replace' marker is appended (meaningful when the
            # group's log is persistent), and an election follows.
            me = (g.name, self.name)
            self._lease_revoke(g, "force_shrink")
            idx = g.log.next_index()
            g.log.append(Entry(index=idx, term=g.term, cmd=Command(
                kind="ra_cluster_change", data=("replace", ((me, "voter"),)))))
            g.specials.append(idx)
            self._stage_app(g.gid, idx, idx, g.term)
            g.members = [me]
            g.self_slot = 0
            g.next_index = [idx + 1]
            g.commit_sent = [0]
            g.match_hint = [0]
            g.voter_status = {0: "voter"}
            g.last_ack = {}
            g.cluster_change_permitted = True
            onehot = np.zeros(self.P, dtype=bool)
            onehot[0] = True
            self.state = self.state._replace(
                voting=self.state.voting.at[g.gid].set(jnp.asarray(onehot)),
                active=self.state.active.at[g.gid].set(jnp.asarray(onehot)),
                self_slot=self.state.self_slot.at[g.gid].set(0),
            )
            self.state = C.set_roles(
                self.state,
                jnp.asarray([g.gid], jnp.int32),
                jnp.asarray([C.R_PRE_VOTE], jnp.int32),
            )
            g.role = C.R_PRE_VOTE
            g.pre_vote_token += 1
            self._hot.add(g.gid)
            if len(msg) > 1 and msg[1] is not None:
                self._reply(msg[1], ("ok", None))
            return
        if isinstance(msg, InstallSnapshotRpc):
            self._receive_snapshot_chunk(g, msg, from_sid)
            return
        if isinstance(msg, (InstallSnapshotAck, InstallSnapshotResult)):
            sender = g.snap_senders.get(from_sid)
            if sender is not None:
                if isinstance(msg, InstallSnapshotAck):
                    sender.on_ack(msg)
                else:
                    sender.on_result(msg)
            return
        if isinstance(msg, tuple) and msg and msg[0] == "snap_send_done":
            _, to, result = msg
            g.snap_senders.pop(to, None)
            if result is not None and g.role == C.R_LEADER:
                slot = g.slot_of(to)
                if slot >= 0:
                    g.next_index[slot] = max(g.next_index[slot], result.last_index + 1)
                    if slot < len(g.match_hint):
                        g.match_hint[slot] = max(
                            g.match_hint[slot], result.last_index
                        )
                    # feed the result through the device path for match
                    g.inbox.append((to, AppendEntriesReply(
                        result.term, True, result.last_index + 1,
                        result.last_index, result.last_term)))
                    self._hot.add(g.gid)
                    # resume pipelining the post-snapshot tail right away
                    self._send_aers({g.gid})
            return

    _ROLE_NAMES = {0: "follower", 1: "pre_vote", 2: "candidate", 3: "leader"}

    class _AuxServerShim:
        """Duck-types the Server surface AuxContext reads, over a
        GroupHost (machine state, membership, indexes, log)."""

        def __init__(self, coord: "BatchCoordinator", g: GroupHost):
            self.machine_state = g.machine_state
            self.leader_id = g.sid_of(g.leader_slot)
            self.current_term = g.term
            self.commit_index = g.last_applied
            self.last_applied = g.last_applied
            self.log = g.log
            self._g = g
            self._coord = coord

        def members(self):
            return [m for m in self._g.members if m is not None]

        def overview(self):
            g = self._g
            return {
                "id": (g.name, self._coord.name),
                "backend": "tpu_batch",
                "role": BatchCoordinator._ROLE_NAMES.get(g.role, g.role),
                "term": g.term,
                "last_applied": g.last_applied,
                "machine": g.machine.overview(g.machine_state),
            }

    def _handle_aux(self, g: GroupHost, kind: str, cmd, from_ref) -> None:
        """Aux machine plumbing for batch-backed groups (reference:
        ra_aux surface, src/ra_aux.erl:8-23)."""
        from ra_tpu.aux import AuxContext

        if not g.aux_inited:
            g.aux_state = g.machine.init_aux(g.cluster_name)
            g.aux_inited = True
        from ra_tpu.machine import normalize_aux_result

        res = g.machine.handle_aux(
            self._ROLE_NAMES.get(g.role, "follower"), kind, cmd, g.aux_state,
            AuxContext(self._AuxServerShim(self, g)),
        )
        reply, g.aux_state, effs = normalize_aux_result(res, g.aux_state)
        if effs:
            # aux effects are realized regardless of role (matching the
            # proc backend, which executes them ungated)
            self._realise_effects(g, effs, True)
        if kind == "call" and from_ref is not None:
            self._reply(from_ref, ("ok", reply, (g.name, self.name)))

    def _voter_count(self, g: GroupHost) -> int:
        return sum(
            1 for i, m in enumerate(g.members)
            if m is not None and g.voter_status.get(i) == "voter"
        )

    def _handle_consistent_query(self, g: GroupHost, fn, fut) -> None:
        """Linearizable read: confirm leadership with a voter heartbeat
        quorum round before answering, gated on the leader's own noop
        having committed (Raft read-index; reference: query_index
        heartbeat protocol, src/ra_server.erl consistent queries)."""
        if g.role != C.R_LEADER:
            self._reply(fut, ("redirect", g.sid_of(g.leader_slot)))
            return
        if not g.noop_committed:
            # a fresh leader may hold committed-but-unapplied entries
            # from the previous term; ask the caller to retry
            self._reply(fut, ("redirect", None))
            return
        me = (g.name, self.name)
        if self._voter_count(g) <= 1:
            self._reply(fut, ("ok", fn(g.machine_state), me))
            return
        now = self.clock.monotonic()
        if self.lease_cfg.enabled:
            # lease fast path (§20): within a quorum-earned lease the
            # read is served locally at read_index = commit (== applied
            # on this backend) with zero quorum traffic. Demand-driven
            # renewal: reads in the back half of the window trigger a
            # stamped heartbeat round (throttled to one per quarter-
            # window) so a read-only workload renews at an amortized
            # one round per window instead of one per read.
            self._lease_refresh()
            gid = g.gid
            exp = self._lease_expiry[gid]
            if exp > now:
                self.counters.incr("read_lease_served")
                self._reply(fut, ("ok", fn(g.machine_state), me))
                if (
                    exp - now < self.lease_cfg.window_s / 2.0
                    and now - self._lease_renew_t[gid]
                    >= self.lease_cfg.window_s / 4.0
                ):
                    self._lease_renew_t[gid] = now
                    hb0 = HeartbeatRpc(
                        term=g.term, leader_id=me,
                        query_index=g.query_seq,
                    )
                    ob0: Dict[str, List] = {}
                    for s0, m0 in enumerate(g.members):
                        if (
                            m0 is None or s0 == g.self_slot
                            or g.voter_status.get(s0) != "voter"
                        ):
                            continue
                        self._lease_stamp_send(gid, s0, now)
                        ob0.setdefault(m0[1], []).append((m0, hb0, me))
                    for nn0, msgs0 in ob0.items():
                        self._send_batch(nn0, msgs0)
                return
            if exp > 0.0:
                # held a lease, lapsed: count the expiry once. Bases
                # stay — they are still honest promises and the
                # fallback round's acks re-earn the lease.
                self.counters.incr("read_lease_expirations")
                self._obs_rec.record(
                    "lease_lost", node=self.name, group=g.name,
                    term=g.term, detail="expired",
                )
                self._lease_expiry[gid] = 0.0
            self.counters.incr("read_quorum_fallback")
        fresh = []
        for q in g.pending_queries:
            if now - q["t"] < 10.0:
                fresh.append(q)
            else:
                # quorum never arrived (lost heartbeat, shrunk voter
                # set): tell the caller to retry instead of hanging
                self._reply(q["fut"], ("redirect", None))
        g.pending_queries = fresh
        g.query_seq += 1
        qid = g.query_seq
        g.pending_queries.append(
            {"qi": g.last_applied, "qid": qid, "fn": fn, "fut": fut,
             "acks": set(), "t": now}
        )
        hb = HeartbeatRpc(term=g.term, leader_id=me, query_index=qid)
        outbound: Dict[str, List] = {}
        for s, member in enumerate(g.members):
            if (
                member is None
                or s == g.self_slot
                or g.voter_status.get(s) != "voter"
            ):
                continue  # only voter acks may confirm leadership
            if self.lease_cfg.enabled:
                # the fallback round's acks re-earn the lease
                self._lease_stamp_send(g.gid, s, now)
            outbound.setdefault(member[1], []).append((member, hb, me))
        for node_name, msgs in outbound.items():
            self._send_batch(node_name, msgs)

    def _adopt_term(self, g: GroupHost, term: int, leader_sid=None) -> None:
        """Adopt a higher term seen outside the device mailbox (call
        sites hold the state lock): revert to follower on host AND
        device, persist the term, drop in-flight linearizable reads."""
        if g.role == C.R_LEADER:
            self._lease_revoke(g, "deposed by higher term")
            for q in g.pending_queries:
                self._reply(q["fut"], ("redirect", None))
            g.pending_queries = []
        bumped = term > g.term
        g.term = max(g.term, term)
        was_leader = g.role == C.R_LEADER
        g.role = C.R_FOLLOWER
        g.last_contact = self.clock.monotonic()
        g.leader_slot = g.slot_of(leader_sid) if leader_sid is not None else -1
        if was_leader:
            # deposed outside the device mailbox: same redirect contract
            # as the egress role-transition path
            self._fail_pending(g)
        if bumped and self.meta is not None:
            # entering a new term clears the durable vote (the device
            # mailbox path resets voted_for on term bumps identically)
            uid = f"{g.cluster_name}_{g.name}"
            self.meta.store(uid, "current_term", g.term)
            self.meta.store_sync(uid, "voted_for", None)
        voted = (
            self.state.voted_for.at[g.gid].set(-1)
            if bumped else self.state.voted_for
        )
        self.state = self.state._replace(
            current_term=self.state.current_term.at[g.gid].max(term),
            voted_for=voted,
            leader_slot=self.state.leader_slot.at[g.gid].set(g.leader_slot),
            role=self.state.role.at[g.gid].set(C.R_FOLLOWER),
        )

    def _handle_heartbeat_reply(self, g: GroupHost, msg: HeartbeatReply, from_sid) -> None:
        if msg.term > g.term:
            # a deposed leader must step down now, not wait for AER
            # traffic while its pending queries ride the redirect timeout
            self._adopt_term(g, msg.term)
            return
        if g.role != C.R_LEADER or from_sid is None or msg.term != g.term:
            return
        slot = g.slot_of(from_sid)
        if slot < 0 or g.voter_status.get(slot) != "voter":
            return
        if self.lease_cfg.enabled:
            self._lease_credit(g, slot)
        quorum = self._voter_count(g) // 2 + 1
        me = (g.name, self.name)
        done = []
        for q in g.pending_queries:
            if msg.query_index >= q["qid"]:
                q["acks"].add(from_sid)
                if len(q["acks"]) + 1 >= quorum and g.last_applied >= q["qi"]:
                    self._reply(q["fut"], ("ok", q["fn"](g.machine_state), me))
                    done.append(q)
        for q in done:
            g.pending_queries.remove(q)

    # -- snapshot transfer (batch-backed groups) ---------------------------

    def _snap_ack(self, g: GroupHost, chunk_no: int) -> InstallSnapshotAck:
        """Chunk ack with receiver-paced credits (docs/INTERNALS.md
        §21): 0 while this node is storage-blocked, so the sender parks
        instead of streaming chunks at a disk that cannot spool them."""
        window = max(1, self.snapshot_credit_window)
        credits = self.pressure.snapshot_credits(window)
        if credits:
            self.counters.incr("snapshot_credits_granted", credits)
        else:
            self.counters.incr("snapshot_credit_waits")
        self.counters.put("snapshot_credit_window", credits)
        return InstallSnapshotAck(g.term, chunk_no, credits)

    def _receive_snapshot_chunk(self, g: GroupHost, msg: InstallSnapshotRpc, from_sid):
        """Host-side 4-phase chunked install; the device learns the new
        floor via a record_snapshot scatter on completion."""
        me = (g.name, self.name)

        def send_one(m):
            self._send_batch(from_sid[1], [(from_sid, m, me)])

        if msg.term < g.term:
            li, lt = g.log.last_index_term()
            send_one(InstallSnapshotResult(g.term, li, lt))
            return
        g.last_contact = self.clock.monotonic()
        if self.lease_cfg.enabled:
            g.lease_contact = g.last_contact
        if msg.chunk_phase == CHUNK_INIT:
            # INIT always starts a fresh accumulator — a retried transfer
            # at the same index must not append onto stale chunks. Chunk
            # bodies spool straight to disk when the group's log store
            # supports it ("accept" is None on memory logs: RAM fallback)
            old = g.snap_accept
            if old is not None:
                oa = old.get("accept")
                if oa is not None and not oa.done:
                    oa.abort()
            g.snap_accept = {
                "meta": msg.meta, "chunks": [], "next": 1,
                "accept": g.log.begin_accept_snapshot(msg.meta),
            }
            send_one(self._snap_ack(g, msg.chunk_no))
            return
        acc = g.snap_accept
        if acc is None or acc["meta"].index != msg.meta.index:
            return  # no transfer in progress for this snapshot: ignore
        if msg.chunk_phase == CHUNK_PRE:
            acc["next"] = max(acc["next"], msg.chunk_no + 1)
            for e in msg.data:
                if g.log.fetch_term(e.index) is None:
                    g.log.write_sparse(e)
            send_one(self._snap_ack(g, msg.chunk_no))
            return
        if msg.chunk_no < acc["next"]:
            send_one(self._snap_ack(g, msg.chunk_no))
            return
        if msg.chunk_no > acc["next"]:
            return
        a = acc.get("accept")
        if a is not None and isinstance(msg.data, (bytes, bytearray)):
            a.accept_chunk(msg.data)  # straight to the disk spool
        else:
            if a is not None:
                # non-byte chunk (in-proc direct-object transfer): falls
                # back to RAM — always the first chunk, nothing is lost
                a.abort()
                acc["accept"] = a = None
            acc["chunks"].append(msg.data)
        acc["next"] += 1
        if msg.chunk_phase != CHUNK_LAST:
            send_one(self._snap_ack(g, msg.chunk_no))
            return
        # complete: install host-side, then scatter the floor to device
        from ra_tpu.log.snapshot import decode_snapshot_chunks

        meta = acc["meta"]
        try:
            if a is not None:
                # seal + streaming-decode + promote: the spool dir IS
                # the new snapshot; no second serialization
                state_obj = g.log.complete_accept_snapshot(a)
            else:
                state_obj = decode_snapshot_chunks(acc["chunks"])
                g.log.install_snapshot(meta, state_obj)
        except Exception:
            # undecodable body (e.g. a machine-state type the wire
            # allowlist does not know here): abort THIS transfer so a
            # retry restarts from INIT; never poison the step thread
            g.snap_accept = None
            logger.exception(
                "coordinator %s: snapshot body for group %s failed wire "
                "decode; transfer aborted (register_wire_type missing?)",
                self.name, g.name,
            )
            return
        g.machine_state = state_obj
        g.effective_machine_version = meta.machine_version
        g.last_applied = max(g.last_applied, meta.index)
        g.snap_floor = max(g.snap_floor, meta.index)
        g.last_ok_sent = None  # log identity changed under the ack key
        # installing a snapshot forces follower: any leftover pending
        # command futures must redirect, not hang
        self._fail_pending(g)
        if g.specials:
            g.specials = [s for s in g.specials if s > meta.index]
        # adopt the snapshot's member set (node-local slot coordinates)
        if meta.cluster:
            new = [tuple(m) for m in meta.cluster]
            me = (g.name, self.name)
            if me in new and set(new) != {m for m in g.members if m is not None}:
                g.members = list(new)
                g.self_slot = new.index(me)
                g.voter_status = {i: "voter" for i in range(len(new))}
                g.next_index = [meta.index + 1] * len(new)
                g.commit_sent = [0] * len(new)
                g.match_hint = [0] * len(new)
                g.last_ack = {}
                self.state = self.state._replace(
                    self_slot=self.state.self_slot.at[g.gid].set(g.self_slot)
                )
                self._sync_member_rows(g)
        self._applied_np[g.gid] = g.last_applied
        g.term = max(g.term, msg.term)
        g.leader_slot = g.slot_of(msg.leader_id)
        g.snap_accept = None
        self._obs_rec.record(
            "snapshot_install", node=self.name, group=g.name, term=g.term,
            detail=f"installed at index {meta.index} (term {meta.term})",
        )
        gid = jnp.asarray([g.gid], jnp.int32)
        self.state = C.record_snapshot(
            self.state, gid, jnp.asarray([meta.index], jnp.int32),
            jnp.asarray([meta.term], jnp.int32),
        )
        self.state = self.state._replace(
            current_term=self.state.current_term.at[g.gid].max(msg.term),
            leader_slot=self.state.leader_slot.at[g.gid].set(g.leader_slot),
            role=self.state.role.at[g.gid].set(C.R_FOLLOWER),
        )
        send_one(InstallSnapshotResult(g.term, meta.index, meta.term))

    class _SenderShim:
        """Adapts a coordinator group to the interface proc.SnapshotSender
        expects (transport / server.id / enqueue / ack timeout)."""

        def __init__(self, coord: "BatchCoordinator", g: GroupHost):
            self._coord = coord
            self._g = g
            self.transport = coord.transport
            self.snapshot_ack_timeout_s = 60.0
            self.server = type(
                "S", (),
                {"id": (g.name, coord.name),
                 # the sender counts credit starvation through the
                 # server surface; route it to coordinator counters
                 "_c": staticmethod(
                     lambda field, n=1: coord.counters.incr(field, n)
                 )},
            )()

        def enqueue(self, msg, front: bool = False):
            tag = msg[0]
            to = msg[1]
            result = msg[2] if tag == "snapshot_send_done" else None
            self._coord.deliver(
                (self._g.name, self._coord.name), ("snap_send_done", to, result), None
            )

    def _start_snapshot_sender(self, g: GroupHost, to: ServerId) -> None:
        if to in g.snap_senders:
            return
        # prefer the disk-streaming reader (no decode, no blob in RAM);
        # memory-backed group logs fall back to the whole-state capture
        chunk_size = 1024 * 1024
        state_obj = chunk_iter = None
        stream = g.log.begin_snapshot_read(chunk_size)
        if stream is not None:
            meta, chunk_iter = stream
        else:
            got = g.log.read_snapshot()
            if got is None:
                return
            meta, state_obj = got
        live_entries = (
            g.log.sparse_read(list(meta.live_indexes)) if meta.live_indexes else []
        )
        from ra_tpu.runtime.proc import SnapshotSender

        sender = SnapshotSender(
            self._SenderShim(self, g), to, meta, state_obj, live_entries, g.term,
            chunk_size, chunk_iter=chunk_iter,
        )
        g.snap_senders[to] = sender
        sender.start()

    # -- failure detection -------------------------------------------------

    def _detect_loop(self) -> None:
        cooldown: Dict[int, float] = {}
        # suspicion arming: first sighting arms a randomized deadline
        # (the textbook randomized election timeout); the election only
        # fires if the group is STILL suspicious at the deadline. Breaks
        # dueling candidacies: the node whose trigger lands first gets a
        # full round before rivals pile in.
        armed: Dict[int, float] = {}
        # command-lane watchdog state per gid:
        # (applied_seen, oldest_pending_idx, since, strikes)
        lane_watch: Dict[int, Tuple[int, int, float, int]] = {}
        last_tick = self.clock.monotonic()
        while self.running:
            try:
                now0 = self.clock.monotonic()
                if now0 - last_tick >= self.tick_interval_s:
                    last_tick = now0
                    self._lane_watchdog(lane_watch, now0)
                    # aggregate commit rate across all groups (the
                    # batch-backend ra_li feed for system_overview /
                    # placement decisions)
                    applied_total = int(
                        self._applied_np[: self.n_groups].sum()
                    )
                    prev = self._commit_li_prev
                    self._commit_li_prev = (now0, applied_total)
                    if prev is not None:
                        rate = self._commit_li.sample(
                            max(0, applied_total - prev[1]), now0 - prev[0]
                        )
                        self.counters.put("commit_rate", int(round(rate)))
                    # reclaim lanes of exited producer threads, then
                    # publish the registered-lane gauge (one lane per
                    # live producer; off the hot drain path)
                    prune = getattr(self._rings, "prune_dead", None)
                    if prune is not None:
                        prune()
                    self.counters.put(
                        "ingress_ring_lanes", self._rings.lanes()
                    )
                    self._health_scan(now0)
                    ms = int(self.clock.time() * 1000)
                    for i in range(self.n_groups):
                        g = self.groups[i]
                        if g is None:
                            continue
                        if g.has_tick:
                            self.deliver((g.name, self.name), ("machine_tick", ms), None)
                        if g.role == C.R_LEADER:
                            # peers silent for two ticks may have missed
                            # AERs (drops/partitions advance next_index
                            # optimistically): probe them so their reject
                            # hints rewind replication (zero cost while
                            # acks flow)
                            stale = [
                                s for s, m in enumerate(g.members)
                                if m is not None and s != g.self_slot
                                and now0 - g.last_ack.get(s, 0.0)
                                > 2 * self.tick_interval_s
                            ]
                            if stale:
                                self.deliver(
                                    (g.name, self.name), ("resync", stale), None
                                )
                # a stopped node unregisters: include previously-seen
                # names so disappearance reads as death
                known = set(self.registry.names()) | set(self._node_status)
                for other in known:
                    if other == self.name:
                        continue
                    alive = self.transport.node_alive(other)
                    prev = self._node_status.get(other)
                    self._node_status[other] = alive
                    if prev is True and not alive:
                        self._on_node_down(other)
                # suspicion sweep. Three leaderless shapes need retry —
                # without it a partition heal can wedge a group forever
                # (nobody re-elects once every node is "alive" again):
                #   1. a stalled election (pre-vote/candidate whose
                #      messages were lost) — mirror the actor backend's
                #      state-enter election timer;
                #   2. a follower with a known leader: a dead leader
                #      node counts once the follower has ALSO been
                #      without contact for one election timeout (vote
                #      grants refresh contact, so a member that just
                #      endorsed a campaigning rival holds off); an
                #      alive-but-silent leader (deposed, never re-won)
                #      times out on lost contact — the resync probe
                #      guarantees a live leader contacts every peer
                #      within ~2 ticks;
                #   3. a follower with NO known leader (term bumped by a
                #      failed election) — contact timeout, gated on
                #      term > 0 so fresh clusters still boot quiet until
                #      explicitly triggered (reference: ra:start_cluster
                #      calls trigger_election; no idle heartbeats).
                # window >> the 2-tick probe cadence: device pre-vote
                # grants have no leader-stickiness, so a trigger-happy
                # sweep could dethrone a healthy but loaded leader
                now = self.clock.monotonic()
                contact_window = max(
                    5 * self.tick_interval_s, 6 * self.election_timeout_s
                )
                for i in range(self.n_groups):
                    g = self.groups[i]
                    if g is None or g.role == C.R_LEADER:
                        continue
                    if g.voter_status.get(g.self_slot) != "voter":
                        continue
                    leader = g.sid_of(g.leader_slot)
                    if g.role in (C.R_PRE_VOTE, C.R_CANDIDATE):
                        suspicious = (
                            now - g.last_contact > 2 * self.election_timeout_s
                        )
                    elif leader is not None and leader[1] != self.name:
                        # a dead leader node is suspicious only once it
                        # has also been SILENT for an election timeout:
                        # last_contact refreshes on vote grants, so a
                        # member that just endorsed a campaigning rival
                        # holds off instead of racing it (the round-5
                        # takeover duel)
                        suspicious = (
                            not self.transport.node_alive(leader[1])
                            and now - g.last_contact > self.election_timeout_s
                        ) or now - g.last_contact > contact_window
                    else:
                        suspicious = (
                            g.term > 0
                            and now - g.last_contact > contact_window
                        )
                    if not suspicious:
                        armed.pop(i, None)
                    elif now >= cooldown.get(i, 0.0):
                        dl = armed.get(i)
                        if dl is None:
                            armed[i] = now + self.election_timeout_s * (
                                0.1 + random.random()
                            )
                        elif now >= dl:
                            armed.pop(i, None)
                            cooldown[i] = (
                                now + 2 * self.election_timeout_s
                                + random.random() * 2 * self.election_timeout_s
                            )
                            self.deliver(
                                (g.name, self.name), ElectionTimeout(now),
                                None,
                            )
            except Exception:  # noqa: BLE001
                pass
            time.sleep(self._detector_poll_s)

    def _lane_watchdog(
        self, lane_watch: Dict[int, Tuple[int, int, float, int]], now0: float
    ) -> None:
        """Per-command-deadline lane watchdog (runs on the detector
        thread, once per tick): a group holding pending client futures
        whose apply floor AND oldest pending index both sat still for
        ``command_deadline_s`` is a wedged lane. Strike 1 recovers
        (device re-step + peer resync probe); a further strike bounds
        the failure by redirecting the stuck clients. Turns the round-5
        class of bug (accepted command, no commit, silent 10 s client
        hang) into a detected, counted, bounded event."""
        for i in range(self.n_groups):
            g = self.groups[i]
            if g is None:
                continue
            pending = g.pending_replies
            if not pending:
                lane_watch.pop(i, None)
                continue
            try:
                oldest = min(pending)
            except (ValueError, RuntimeError):
                continue  # raced the step thread's mutation: next tick
            st = lane_watch.get(i)
            if st is None or st[0] != g.last_applied or st[1] != oldest:
                lane_watch[i] = (g.last_applied, oldest, now0, 0)
                continue
            if now0 - st[2] <= self.command_deadline_s:
                continue
            strikes = st[3] + 1
            lane_watch[i] = (g.last_applied, oldest, now0, strikes)
            self.counters.incr("lane_wedges")
            self._obs_rec.record(
                "watchdog_strike", node=self.name, group=g.name,
                term=g.term,
                detail=f"strike {strikes}: oldest pending {oldest}, "
                       f"applied {g.last_applied}",
            )
            logger.warning(
                "coordinator %s: command lane wedged for group %s "
                "(oldest pending idx %d, applied %d, role %d, strike %d)",
                self.name, g.name, oldest, g.last_applied, g.role, strikes,
            )
            self.deliver(
                (g.name, self.name),
                ("lane_recover",) if strikes == 1 else ("lane_fail",),
                None,
            )

    def _health_scan(self, now: float) -> None:
        """Per-group health pass (docs/INTERNALS.md §14), once per tick
        on the detector thread: ONE device fetch over the existing
        consensus mirrors (proven by the scans==fetches counter
        invariant), then a fully vectorized gauge/anomaly update in
        ra_tpu.health — no per-group Python loop, so the cost scales
        with capacity at numpy speed, not with groups at Python speed."""
        from ra_tpu import health as H

        n = self.n_groups
        if n == 0:
            return
        with self._state_lock:
            st = self.state
            # the fused step DONATES the state buffers, so a reference
            # read outside the lock can die under us — but holding the
            # lock across the host transfer would stall the step thread
            # behind the async dispatch queue. Enqueue device-side
            # COPIES under the lock (dispatch only, microseconds; the
            # copies' buffers are fresh, never donated) ...
            snap = tuple(jnp.copy(a) for a in (
                st.current_term, st.commit_index, st.last_index, st.role,
                st.leader_slot, st.self_slot, st.match_index, st.active,
            ))
        # ... and pay the transfer/queue wait OUTSIDE it: one
        # device_get per scan (the health_fetches == health_scans
        # counter invariant) with the step loop free to run
        dev = jax.device_get(snap)
        sc = self._health
        sc.counters.incr("health_fetches")
        term, commit, last, role, leader_slot, self_slot, match, active = (
            a[:n] for a in dev
        )
        applied = self._applied_np[:n]
        # follower match gap (leaders only): own tail minus the slowest
        # ACTIVE peer's confirmed match, self slot excluded
        cols = np.arange(match.shape[1])
        peers = active & (cols[None, :] != self_slot[:, None])
        slowest = np.where(
            peers, match.astype(np.int64), np.iinfo(np.int64).max
        ).min(axis=1)
        is_leader = role == C.R_LEADER
        has_peer = peers.any(axis=1)
        match_gap = np.where(
            is_leader & has_peer,
            np.maximum(last.astype(np.int64) - slowest, 0), 0,
        )
        leader_key = np.where(
            leader_slot >= 0, leader_slot.astype(np.int64), H.NO_LEADER_KEY
        )
        slots = np.asarray(self._hslots[:n], np.int64)
        sc.scan(now, slots, role, term, applied, commit, last, match_gap,
                leader_key)

    def _on_node_down(self, node_name: str) -> None:
        for i in range(self.n_groups):
            g = self.groups[i]
            if g is None or g.role == C.R_LEADER:
                continue
            leader = g.sid_of(g.leader_slot)
            if leader is not None and leader[1] == node_name:
                delay = self.election_timeout_s * (1 + random.random())
                # stamp the suspicion-confirmation time NOW: stamping at
                # fire time would make the staleness guard in
                # _handle_rare unable to drop the trigger when the
                # leader re-establishes contact during the delay
                armed = self.clock.monotonic()
                threading.Timer(
                    delay,
                    lambda gg=g, at=armed: self.deliver(
                        (gg.name, self.name),
                        ElectionTimeout(at), None,
                    ),
                ).start()

    def overview(self) -> dict:
        return {
            "node": self.name,
            "backend": "tpu_batch",
            "groups": self.n_groups,
            "steps": self.steps,
            "sub_steps": self.sub_steps,
            "msgs": self.msgs_processed,
            "commit_rate": self.counters.get("commit_rate"),
            "counters": self.counters.to_dict(),
        }
