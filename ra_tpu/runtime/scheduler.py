"""Actor scheduler: thousands of mailboxes over a small thread pool.

Replaces the reference's one-BEAM-process-per-group model (reference:
``ra_server_proc`` gen_statem per group) with event-driven actors: each
actor has a mailbox and an ``on_batch`` handler; a fixed worker pool runs
at most one drain per actor at a time (per-actor serialization, batched
delivery — the same property gen_statem + selective receive provides,
engineered for CPython where a thread per group would not scale).
"""

from __future__ import annotations

import logging
import threading
from collections import deque
from typing import Any, Callable, List, Optional


logger = logging.getLogger("ra_tpu")


class Actor:
    """Mailbox + serialized batch handler."""

    __slots__ = (
        "name", "on_batch", "_mailbox", "_lock", "_scheduled", "_sched",
        "alive", "_idle",
    )

    def __init__(self, name: str, on_batch: Callable[[List[Any]], None], sched: "Scheduler"):
        self.name = name
        self.on_batch = on_batch
        self._mailbox: deque = deque()
        self._lock = threading.Lock()
        self._scheduled = False
        self._sched = sched
        self.alive = True
        self._idle = threading.Event()
        self._idle.set()

    def send(self, msg: Any, front: bool = False) -> None:
        with self._lock:
            if not self.alive:
                return
            if front:
                self._mailbox.appendleft(msg)
            else:
                self._mailbox.append(msg)
            if not self._scheduled:
                self._scheduled = True
                self._sched._submit(self)

    def _drain(self, max_batch: int) -> None:
        while True:
            with self._lock:
                if not self._mailbox or not self.alive:
                    self._scheduled = False
                    self._idle.set()
                    return
                batch = []
                while self._mailbox and len(batch) < max_batch:
                    batch.append(self._mailbox.popleft())
                self._idle.clear()
            try:
                self.on_batch(batch)
            except Exception:  # noqa: BLE001 — actor crash isolation
                logger.exception("actor %r crashed", self.name)
                self._sched.on_actor_crash(self)
                with self._lock:
                    self._scheduled = False
                    self._idle.set()
                return

    def kill(self, quiesce_timeout: float = 5.0) -> None:
        """Stop the actor; blocks until any in-flight batch handler has
        finished, so callers may safely read the actor-owned state."""
        with self._lock:
            self.alive = False
            self._mailbox.clear()
        self._idle.wait(quiesce_timeout)


class Scheduler:
    def __init__(self, workers: int = 4, max_batch: int = 64):
        self.max_batch = max_batch
        self._queue: deque = deque()
        self._cv = threading.Condition()
        self._closed = False
        self.on_crash: Optional[Callable[[Actor], None]] = None
        self._threads = [
            threading.Thread(target=self._run, name=f"ra-sched-{i}", daemon=True)
            for i in range(workers)
        ]
        for t in self._threads:
            t.start()

    def actor(self, name: str, on_batch: Callable[[List[Any]], None]) -> Actor:
        return Actor(name, on_batch, self)

    def _submit(self, actor: Actor) -> None:
        with self._cv:
            self._queue.append(actor)
            self._cv.notify()

    def on_actor_crash(self, actor: Actor) -> None:
        if self.on_crash is not None:
            try:
                self.on_crash(actor)
            except Exception:  # noqa: BLE001
                pass

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._queue and not self._closed:
                    # event-driven idle: _submit notifies per enqueue,
                    # close() notifies all — idle scheduler workers
                    # consume zero CPU (docs/INTERNALS.md §16)
                    self._cv.wait()
                if self._closed:
                    return
                actor = self._queue.popleft()
            actor._drain(self.max_batch)

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        for t in self._threads:
            t.join(timeout=2)
