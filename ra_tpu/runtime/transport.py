"""Transport: async server-to-server messaging with backpressure status.

Abstraction over the reference's use of Erlang distribution (async casts
with noconnect/nosuspend, reference: src/ra_server_proc.erl:1875-1881,
2094-2110). Two implementations:

- ``InProcTransport``: every "node" lives in this process; sends are
  direct mailbox enqueues. Supports scripted fault injection (drop /
  partition) for nemesis tests — the counterpart of the reference's
  inet_tcp_proxy trick.
- ``TcpTransport`` (ra_tpu.runtime.tcp): length-framed pickle over TCP
  for real multi-process clusters.

Delivery is at-most-once and unordered across peers (like the reference
across reconnects); the consensus protocol tolerates loss.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, Optional, Set, Tuple

from ra_tpu.protocol import ServerId


class NodeRegistry:
    """Process-global registry of in-proc nodes (name -> RaNode)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self.nodes: Dict[str, Any] = {}

    def register(self, name: str, node: Any) -> None:
        with self._lock:
            if name in self.nodes:
                raise RuntimeError(f"node {name!r} already registered")
            self.nodes[name] = node

    def unregister(self, name: str) -> None:
        with self._lock:
            self.nodes.pop(name, None)

    def get(self, name: str) -> Optional[Any]:
        return self.nodes.get(name)

    def names(self):
        return list(self.nodes.keys())


_global_registry = NodeRegistry()


def registry() -> NodeRegistry:
    return _global_registry


class InProcTransport:
    def __init__(self, node_name: str, nodes: Optional[NodeRegistry] = None):
        self.node_name = node_name
        self.nodes = nodes or _global_registry
        self._lock = threading.Lock()
        self.blocked: Set[Tuple[str, str]] = set()  # directed (from, to) node pairs
        self.drop_fn: Optional[Callable[[ServerId, Any], bool]] = None
        self.dropped = 0

    # -- fault injection ---------------------------------------------------

    def block(self, a: str, b: str) -> None:
        with self._lock:
            self.blocked.add((a, b))

    def unblock_all(self) -> None:
        with self._lock:
            self.blocked.clear()

    # -- sending -----------------------------------------------------------

    def send(self, to: ServerId, msg: Any, from_sid: Optional[ServerId] = None) -> bool:
        """Async send; returns False when known-undeliverable (node down
        or blocked) so callers can update peer status."""
        _, node_name = to
        if (self.node_name, node_name) in self.blocked:
            self.dropped += 1
            return False
        if self.drop_fn is not None and self.drop_fn(to, msg):
            self.dropped += 1
            return False
        node = self.nodes.get(node_name)
        if node is None or not getattr(node, "running", False):
            self.dropped += 1
            return False
        return node.deliver(to, msg, from_sid)

    def node_alive(self, node_name: str) -> bool:
        if (self.node_name, node_name) in self.blocked:
            return False
        node = self.nodes.get(node_name)
        return node is not None and getattr(node, "running", False)

    def proc_alive(self, sid: ServerId) -> bool:
        """Best-effort: is the server proc behind sid still running? Used
        to distinguish live leader contact from stale in-flight messages
        of a dead leader. Over in-proc transport this is exact; remote
        transports approximate with node aliveness."""
        if not self.node_alive(sid[1]):
            return False
        node = self.nodes.get(sid[1])
        procs = getattr(node, "procs", None)
        if procs is None:
            return True
        return sid[0] in procs

    def known_nodes(self):
        return self.nodes.names()
