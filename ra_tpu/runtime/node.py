"""RaNode: one running "system" on one node.

Bundles what the reference's per-system supervision tree owns (reference:
ra_system_sup -> {ra_log_ets, ra_log_sup {meta, segment writer, wal},
ra_server_sup_sup} plus ra_directory / ra_system_recover): storage infra
shared by every group on the node, the server-proc registry, the actor
scheduler, timers, background workers, client notification routing, the
node failure detector, and crash-restart supervision for server procs.
"""

from __future__ import annotations

import logging
import os
import shutil
import threading
from collections import deque
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Callable, Dict, List, Optional, Tuple

from ra_tpu import counters as ra_counters
from ra_tpu import effects as fx
from ra_tpu.directory import Directory
from ra_tpu.log.log import Log
from ra_tpu.log.meta_store import FileMeta
from ra_tpu.log.segment_writer import SegmentWriter
from ra_tpu.log.tables import TableRegistry
from ra_tpu.log.wal import Wal
from ra_tpu.machine import Machine
from ra_tpu.protocol import DownEvent, ElectionTimeout, FromPeer, LogEvent, ServerId
from ra_tpu.runtime.proc import ServerProc
from ra_tpu.runtime.scheduler import Scheduler
from ra_tpu.runtime.timers import TimerService
from ra_tpu.runtime.transport import InProcTransport, NodeRegistry, registry as node_registry
from ra_tpu.server import Server, ServerConfig
from ra_tpu.system import SystemConfig


logger = logging.getLogger("ra_tpu")


class Monitors:
    """watcher server-id -> monitored targets (reference: ra_monitors)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        # (kind, target) -> {(watcher_sid, component)}
        self._tab: Dict[Tuple[str, Any], set] = {}

    def add(self, watcher: ServerId, kind: str, target: Any, component: str) -> None:
        with self._lock:
            self._tab.setdefault((kind, target), set()).add((watcher, component))

    def remove(self, watcher: ServerId, kind: str, target: Any) -> None:
        with self._lock:
            s = self._tab.get((kind, target))
            if s:
                self._tab[(kind, target)] = {(w, c) for w, c in s if w != watcher}

    def watchers(self, kind: str, target: Any) -> List[Tuple[ServerId, str]]:
        return list(self._tab.get((kind, target), ()))


class RaNode:
    def __init__(
        self,
        name: str,
        config: Optional[SystemConfig] = None,
        nodes: Optional[NodeRegistry] = None,
        tick_interval_s: float = 0.25,
        election_timeout_s: float = 0.15,
        detector_poll_s: float = 0.1,
        scheduler_workers: int = 4,
        tcp: bool = False,
        clock=None,
    ):
        self.name = name
        from ra_tpu.runtime.clock import WALL

        self.clock = clock or WALL
        self.config = config or SystemConfig(name="default")
        self.dir = os.path.join(self.config.data_dir, name)
        os.makedirs(self.dir, exist_ok=True)
        self.tick_interval_s = tick_interval_s
        self.election_timeout_s = election_timeout_s

        self.tables = TableRegistry()
        self.scheduler = Scheduler(workers=scheduler_workers)
        self.scheduler.on_crash = self._on_actor_crash
        # background work gets its OWN scheduler: a disk-heavy
        # compaction must never occupy a raft worker and starve
        # mailbox drains (heartbeats, elections)
        self.bg_scheduler = Scheduler(workers=2)
        self.timers = TimerService(clock=self.clock)
        self.bg = ThreadPoolExecutor(max_workers=2, thread_name_prefix=f"ra-bg-{name}")
        self.monitors = Monitors()
        self._bg_actors: Dict[str, Any] = {}  # per-server ordered bg queues
        self.procs: Dict[str, ServerProc] = {}
        self.ra_state: Dict[str, Tuple[str, str, Any]] = {}
        self._client_sinks: Dict[Any, Callable[[ServerId, list], None]] = {}
        self._lock = threading.Lock()

        # boot order mirrors the reference's ra_log_sup: meta/directory
        # first, then PRE-INIT registers every server's snapshot floor,
        # THEN WAL recovery runs — so recovery can skip dead indexes
        # instead of resurrecting them (reference:
        # src/ra_log_pre_init.erl:31-45, src/ra_log_sup.erl:20-63)
        from ra_tpu.log.sync_pool import SyncPool

        self.sync_pool = SyncPool()  # serialized snapshot fsyncs (ra_log_sync)
        self.meta = FileMeta(os.path.join(self.dir, "meta.dat"))
        self.meta.fault_scope = name
        self.directory = Directory(self.meta)
        self._pre_init()
        self.sw = SegmentWriter(
            os.path.join(self.dir, "data"),
            self.tables,
            self._log_notify,
            max_entries=self.config.segment_max_entries,
            threaded=True,
        )
        self.sw.fault_scope = name
        self.wal = Wal(
            os.path.join(self.dir, "wal"),
            self.tables,
            self._log_notify,
            segment_writer=self.sw,
            max_size_bytes=self.config.wal_max_size_bytes,
            max_batch_size=self.config.wal_max_batch_size,
            sync_method=self.config.wal_sync_method,
            compute_checksums=self.config.wal_compute_checksums,
            threaded=True,
            group_commit_max_delay_s=self.config.wal_group_commit_max_delay_s,
            group_commit_min_gain=self.config.wal_group_commit_min_gain,
        )
        self.wal.fault_scope = name
        # bulk written-event channel (docs/INTERNALS.md §16): one
        # callback per fsync batch, fanned to the server actors in one
        # pass — the actor-backend mirror of the batch coordinator's
        # wal_notify_many handoff (acks ride the WAL writer thread,
        # never a per-writer callback loop through the Wal)
        self.wal.notify_many = self._log_notify_many
        self.wal.on_failure = self._on_wal_failure
        # supervision intensity accounting (see SystemConfig
        # infra_restart_intensity): restart episodes stamped here; when
        # the window overflows, infra_down latches and healing stops
        self.infra_down = False
        self._infra_restarts: deque = deque()
        # storage-pressure survival plane (docs/INTERNALS.md §21):
        # degraded/hard admission state, byte watermarks, slow-disk
        # brownout — all ticked from the detector loop below
        from ra_tpu.pressure import (
            BrownoutDetector,
            DiskWatermark,
            StoragePressure,
        )

        self.pressure = StoragePressure(name)
        self._watermark = DiskWatermark(
            soft_bytes=self.config.disk_soft_limit_bytes,
            hard_bytes=self.config.disk_hard_limit_bytes,
        )
        self._brownout = BrownoutDetector(
            enter_us=self.config.brownout_enter_us,
            exit_us=self.config.brownout_exit_us,
            streak=self.config.brownout_streak,
        )
        self.pressure.counter.put(
            "disk_soft_limit_bytes", self.config.disk_soft_limit_bytes)
        self.pressure.counter.put(
            "disk_hard_limit_bytes", self.config.disk_hard_limit_bytes)
        self._last_disk_check = 0.0
        self._reclaim_baseline: Optional[int] = None
        self._shed_busy = False
        from ra_tpu import health as ra_health
        from ra_tpu.detector import PhiAccrualDetector

        self.detector = PhiAccrualDetector(owner=name)
        # per-group health scanner (docs/INTERNALS.md §14): the actor-
        # backend mirror of the coordinator's vectorized scan, fed once
        # per tick from the detector thread
        self._health = ra_health.register(name, backend="per_group_actor")
        self._registry = nodes or node_registry()
        if tcp:
            # real sockets: name must be "host:port"; peers are remote
            # processes (reference analog: Erlang distribution carriers)
            from ra_tpu.runtime.tcp import TcpTransport

            self.transport = TcpTransport(name, self.deliver)
            self.transport.detector = self.detector  # adaptive liveness
            self.transport.on_proc_down_cb = self.on_proc_down
            self.transport.on_mgmt_cb = self._handle_mgmt
        else:
            self.transport = InProcTransport(name, self._registry)
        self.running = True
        # the local registry serves in-process clients (api module) even
        # for TCP nodes
        self._registry.register(name, self)

        self._node_status: Dict[str, bool] = {}
        self._detector_poll_s = detector_poll_s
        self._detector = threading.Thread(
            target=self._detect_loop, name=f"ra-detector-{name}", daemon=True
        )
        self._detector.start()

        if self.config.server_recovery_strategy == "registered":
            self.recover_registered()

    # ------------------------------------------------------------------
    # server lifecycle (reference: ra_server_sup_sup start/restart/delete)

    # config keys that may change when a server restarts (reference:
    # ?MUTABLE_CONFIG_KEYS, src/ra_server_sup_sup.erl:12-21)
    MUTABLE_CONFIG_KEYS = frozenset(
        {"machine_config", "max_pipeline_count", "max_aer_batch_size",
         "max_command_backlog", "machine_upgrade_strategy",
         "lease", "lease_safety_factor", "lease_drift_epsilon_s"}
    )

    # _extra_cfg keys re-extracted from the persisted __server_config__
    # blob on restart/recovery — a key missing here silently reverts to
    # its default after a crash (the lease knobs MUST survive restarts:
    # a harness-restarted server running lease-off would skew safety
    # and bench runs)
    _PERSISTED_EXTRA_KEYS = (
        "max_pipeline_count", "max_aer_batch_size", "max_command_backlog",
        "machine_upgrade_strategy", "lease", "lease_safety_factor",
        "lease_drift_epsilon_s",
    )

    def start_server(
        self,
        name: str,
        cluster_name: str,
        machine: Optional[Machine],
        initial_members: Tuple[ServerId, ...],
        uid: Optional[str] = None,
        machine_config: Optional[dict] = None,
        machine_factory: Optional[str] = None,
        _extra_cfg: Optional[dict] = None,
    ) -> ServerId:
        with self._lock:
            if name in self.procs:
                raise RuntimeError(f"server {name!r} already running on {self.name}")
            uid = uid or self.directory.uid_of(name) or f"{cluster_name}_{name}"
            sid: ServerId = (name, self.name)
            if machine is None:
                if machine_factory is None:
                    raise ValueError("machine or machine_factory required")
                from ra_tpu.machine import resolve_machine_factory

                machine = resolve_machine_factory(machine_factory, machine_config)
            self.directory.register(uid, name, cluster_name)
            # persist enough config to restart this server after a crash
            # — including a resolvable machine factory, so a COLD restart
            # (fresh process) can rebuild the machine from disk
            self.meta.store_sync(
                uid,
                "__server_config__",
                {"name": name, "cluster": cluster_name,
                 "members": tuple(initial_members),
                 "machine_config": machine_config or {},
                 "machine_factory": machine_factory,
                 **(_extra_cfg or {})},
            )
            self._machines = getattr(self, "_machines", {})
            self._machines[uid] = machine
            log = Log(
                uid,
                os.path.join(self.dir, "data", uid),
                self.tables,
                self.wal,
                min_snapshot_interval=self.config.min_snapshot_interval,
                min_checkpoint_interval=self.config.min_checkpoint_interval,
                # major compaction passes for one server run in order
                # on its bg queue (never concurrently with each other)
                bg_submit=(lambda fn, _uid=uid: self.submit_bg(
                    fx.BgWork(fn, None), key=_uid)),
                segment_index_mode=self.config.segment_index_mode,
                sync_pool=self.sync_pool,
            )
            extra = _extra_cfg or {}
            cfg = ServerConfig(
                server_id=sid,
                uid=uid,
                cluster_name=cluster_name,
                machine=machine,
                initial_members=tuple(initial_members),
                max_pipeline_count=extra.get(
                    "max_pipeline_count", self.config.default_max_pipeline_count
                ),
                max_aer_batch_size=extra.get(
                    "max_aer_batch_size",
                    self.config.default_max_append_entries_rpc_batch_size,
                ),
                max_command_backlog=extra.get(
                    "max_command_backlog",
                    self.config.default_max_command_backlog,
                ),
                machine_config=machine_config,
                machine_upgrade_strategy=extra.get(
                    "machine_upgrade_strategy",
                    self.config.machine_upgrade_strategy,
                ),
                # check-quorum default: generous vs both the election
                # timeout (a connected follower's ack cadence) and the
                # tick (our own evaluation cadence), so only a genuinely
                # silent quorum — the one-way-partition stale-leader
                # shape — trips a step-down
                check_quorum_window_s=extra.get(
                    "check_quorum_window_s",
                    max(6 * self.election_timeout_s,
                        10 * self.tick_interval_s),
                ),
                # clock-bound leader lease (docs/INTERNALS.md §20):
                # default off; the follower promise window is the
                # node's election timeout BASE (timers randomize
                # upward only), and the core shares the node clock so
                # the sim/test planes can skew every lease comparison
                clock=self.clock,
                election_timeout_s=self.election_timeout_s,
                lease=extra.get("lease", False),
                lease_safety_factor=extra.get("lease_safety_factor", 0.8),
                lease_drift_epsilon_s=extra.get(
                    "lease_drift_epsilon_s", 0.002
                ),
                # storage-pressure plane (docs/INTERNALS.md §21): every
                # server on this node shares the node's pressure gate
                pressure=self.pressure,
                snapshot_credit_window=self.config.snapshot_credit_window,
            )
            server = Server(cfg, log, self.meta)
            server.recover()
            proc = ServerProc(self, server)
            self.procs[name] = proc
            return sid

    def restart_server(
        self, name: str, overrides: Optional[dict] = None, orderly: bool = True
    ) -> ServerId:
        """Restart from persisted config; ``overrides`` may change only
        MUTABLE_CONFIG_KEYS (reference: restart with mutable keys,
        src/ra_server_sup_sup.erl:12-21)."""
        uid = self.directory.uid_of(name)
        if uid is None:
            raise RuntimeError(f"unknown server {name!r}")
        rec = self.meta.fetch(uid, "__server_config__")
        if rec is None:
            raise RuntimeError(f"no persisted config for {name!r}")
        if overrides:
            bad = set(overrides) - self.MUTABLE_CONFIG_KEYS
            if bad:
                raise ValueError(f"immutable config keys on restart: {sorted(bad)}")
            rec = {**rec, **overrides}
            self.meta.store_sync(uid, "__server_config__", rec)
        machine = getattr(self, "_machines", {}).get(uid)
        if overrides and "machine_config" in overrides:
            # a changed machine_config only takes effect through the
            # factory; the cached machine instance holds the old config
            if rec.get("machine_factory") is None:
                raise ValueError(
                    "machine_config override requires a machine_factory"
                )
            machine = None
        self.stop_server(name, orderly=orderly)
        return self.start_server(
            name, rec["cluster"], machine, rec["members"], uid=uid,
            machine_config=rec.get("machine_config"),
            machine_factory=rec.get("machine_factory"),
            _extra_cfg={
                k: rec[k] for k in self._PERSISTED_EXTRA_KEYS if k in rec
            },
        )

    def stop_server(self, name: str, orderly: bool = True) -> None:
        with self._lock:
            proc = self.procs.pop(name, None)
        if proc is not None:
            self._health.release(name)  # restart re-learns from scratch
            proc.kill()
            bg = self._bg_actors.pop(proc.server.cfg.uid, None)
            if bg is not None:
                bg.kill()
            if orderly:
                # capture AFTER the actor stopped: last_applied and
                # machine_state must be a coherent pair (a live actor
                # could apply between the two reads)
                self._write_recovery_checkpoint(proc)
            proc.server.log.close()
            self.ra_state.pop(proc.server.cfg.uid, None)
            # leader-process monitoring: tell every node this proc died
            # (the reference's erlang monitors on the leader,
            # follower_leader_change src/ra_server_proc.erl:1958)
            sid = proc.server.id
            reg = getattr(self.transport, "nodes", None)
            others = list(reg.nodes.values()) if reg is not None else [self]
            for other in others:
                try:
                    other.on_proc_down(sid)
                except Exception:  # noqa: BLE001
                    pass
            # over TCP, announce to remote peers explicitly (the wire
            # stand-in for remote process monitors)
            broadcast = getattr(self.transport, "broadcast_proc_down", None)
            if broadcast is not None:
                broadcast(sid)

    def delete_server(self, name: str) -> None:
        from ra_tpu import leaderboard

        uid = self.directory.uid_of(name)
        self.stop_server(name)
        # deletion (unlike stop/restart) removes the member for good:
        # the leaderboard must not keep routing clients at the ghost
        leaderboard.forget_member((name, self.name))
        if uid:
            self.directory.unregister(uid)
            self.meta.delete(uid)
            self.tables.delete_mem_table(uid)
            self.tables.delete_snapshot_state(uid)
            shutil.rmtree(os.path.join(self.dir, "data", uid), ignore_errors=True)

    def _handle_mgmt(self, op: str, kw: dict):
        """Remote management plane (reference: start_server_rpc /
        restart_server_rpc / delete_server_rpc over rpc:call,
        src/ra_server_sup_sup.erl:33-50). Remote starts must name a
        machine_factory — machine objects do not travel."""
        if op == "start_server":
            return self.start_server(
                kw["name"], kw["cluster_name"], None,
                tuple(tuple(m) for m in kw["members"]),
                machine_config=kw.get("machine_config"),
                machine_factory=kw["machine_factory"],
            )
        if op == "restart_server":
            return self.restart_server(kw["name"], overrides=kw.get("overrides"))
        if op == "stop_server":
            return self.stop_server(kw["name"])
        if op == "delete_server":
            return self.delete_server(kw["name"])
        if op == "trigger_election":
            self.deliver((kw["name"], self.name), ElectionTimeout(), None)
            return None
        if op == "overview":
            return self.overview()
        raise ValueError(f"unknown management op {op!r}")

    def _pre_init(self) -> None:
        """Register snapshot floors for every registered server BEFORE
        WAL recovery (reference: ra_log_pre_init.erl:31-45)."""
        from ra_tpu.log.snapshot import SnapshotStore
        from ra_tpu.utils.seq import Seq

        for uid, _name, _cluster in self.directory.registered():
            d = os.path.join(self.dir, "data", uid)
            if not os.path.isdir(d):
                continue
            try:
                meta = SnapshotStore(d).current()
            except Exception:  # noqa: BLE001 — unreadable: no floor
                continue
            if meta is not None:
                self.tables.set_snapshot_state(
                    uid, meta.index, Seq.from_list(meta.live_indexes)
                )

    def _note_infra_restart(self) -> bool:
        """Supervision intensity accounting (the OTP supervisor
        intensity/period analog): stamp one restart episode; when more
        than ``infra_restart_intensity`` land inside
        ``infra_restart_window_s``, mark the node's storage infra DOWN
        and tell the caller to throttle — a disk failing every few
        seconds is not healing, and unthrottled restart churn would
        just burn I/O while servers flap between wal_down/wal_up.
        Healing is throttled to one attempt per window (never refused
        outright: a disk that recovers minutes later must still heal
        the node), and ``infra_down`` clears on the next success."""
        import time as _t

        now = _t.monotonic()
        dq = self._infra_restarts
        dq.append(now)
        while dq and now - dq[0] > self.config.infra_restart_window_s:
            dq.popleft()
        if len(dq) > self.config.infra_restart_intensity:
            dq.pop()  # a throttled attempt does not count as an episode
            if not self.infra_down:
                self.infra_down = True
                logger.error(
                    "supervision: >%d log-infra restarts within %.1fs on %s "
                    "— marking storage infra DOWN (healing throttled to one "
                    "attempt per window; recover_infra() forces one now)",
                    self.config.infra_restart_intensity,
                    self.config.infra_restart_window_s, self.name,
                )
            return False
        return True

    def recover_infra(self) -> None:
        """Operator hook: clear the intensity window and run one healing
        cycle immediately (fresh WAL file, wal_up resend) — the 'disk
        replaced, bring the node back now' path."""
        self._infra_restarts.clear()
        self.infra_down = False
        if not self.sw.thread_alive():
            self.sw.revive_thread()
        self._on_wal_failure(RuntimeError("operator recover_infra"))

    def _on_wal_failure(self, exc: BaseException) -> None:
        """The shared WAL failed (I/O error or dead writer thread): put
        every server into await_condition, then restart the WAL on a
        fresh file with backoff (the supervision analog; on success
        servers get wal_up and resend their unwritten tails).

        Space-class failures (ENOSPC/EDQUOT — docs/INTERNALS.md §21)
        take the storage_degraded branch instead: same wal_down fan-out
        (entries park in memtables, unacked), but admission flips to
        typed RA_NOSPACE rejects, emergency reclamation runs, and a
        probe-write loop — NOT the supervision intensity budget —
        brings the node back when space returns. Raft control traffic
        (heartbeats, elections, lease reads) needs no new disk and
        keeps running throughout.
        """
        # NO dedup guard here: every failure episode must get a healer
        # (Wal._fail one-shots per episode; the supervisor only fires on
        # a dead thread while not failed). A duplicate cycle costs a
        # redundant wal_down/wal_up round, which servers tolerate; a
        # DROPPED episode would wedge the node forever.
        from ra_tpu.pressure import CLASS_SPACE, classify_storage_error

        for proc in list(self.procs.values()):
            proc.enqueue(LogEvent(("wal_down",)))
        if classify_storage_error(exc) == CLASS_SPACE and self.wal.degraded:
            self._enter_storage_degraded(exc)
            return
        throttled = not self._note_infra_restart()

        def restart():
            import time as _t

            if throttled:
                # intensity exceeded: cool down for one window before
                # the next attempt (the wal stays failed meanwhile, so
                # no further episodes stack behind this one)
                _t.sleep(self.config.infra_restart_window_s)
            delay = 0.05
            while self.running:
                if self.wal.reopen():
                    self.infra_down = False
                    for proc in list(self.procs.values()):
                        proc.enqueue(LogEvent(("wal_up",)))
                    return
                # keep retrying forever with capped backoff: a disk that
                # recovers minutes later must still heal the node
                _t.sleep(delay)
                delay = min(delay * 2, 5.0)

        threading.Thread(
            target=restart, name=f"ra-wal-restart-{self.name}", daemon=True
        ).start()

    def _enter_storage_degraded(self, exc: BaseException) -> None:
        """Space-class WAL failure: degrade instead of restart. The
        degraded episode deliberately does NOT consume the supervision
        intensity budget — running out of disk repeatedly is expected
        under pressure and is not the restart-churn shape the intensity
        latch protects against."""
        if not self.pressure.enter_degraded(
            detail=f"{type(exc).__name__}: {exc}"
        ):
            return  # an earlier space episode already owns the probe loop
        # reclaim first: the probe only succeeds once bytes come back
        self._trigger_reclaim("storage_degraded")

        def probe():
            import time as _t

            delay = 0.05
            while self.running:
                self.pressure.counter.incr("disk_probe_attempts")
                if self.wal.reopen():
                    # probe write succeeded (fresh file + magic bytes):
                    # space is back. Wake parked RA_NOSPACE clients,
                    # then resend the memtable tails.
                    self.pressure.exit_degraded()
                    for proc in list(self.procs.values()):
                        proc.enqueue(LogEvent(("wal_up",)))
                    return
                _t.sleep(delay)
                delay = min(delay * 2, 5.0)

        threading.Thread(
            target=probe, name=f"ra-wal-probe-{self.name}", daemon=True
        ).start()

    def _trigger_reclaim(self, why: str) -> None:
        """Kick one emergency reclamation pass (docs/INTERNALS.md §21):
        every server force-snapshots at its applied index (bypassing
        min_snapshot_interval), advances its release cursor machinery,
        and major-compacts — on its own actor thread, through the
        existing log seams. Freed bytes are accounted on the next
        watermark check against the baseline captured here."""
        from ra_tpu import obs
        from ra_tpu.pressure import dir_bytes

        c = self.pressure.counter
        c.incr("disk_reclaims")
        if self._reclaim_baseline is None:
            self._reclaim_baseline = dir_bytes(self.dir)
        obs.flight_recorder().record(
            "disk_reclaim", node=self.name, detail=why)
        for proc in list(self.procs.values()):
            proc.enqueue(("reclaim_storage",))

    def _tick_storage(self, now: float) -> None:
        """Watermark + brownout controller tick (detector thread)."""
        if now - self._last_disk_check < self.config.disk_check_interval_s:
            return
        self._last_disk_check = now
        from ra_tpu import obs
        from ra_tpu.pressure import dir_bytes

        c = self.pressure.counter
        rec = obs.flight_recorder()
        used = dir_bytes(self.dir)
        c.put("disk_used_bytes", used)
        if self._reclaim_baseline is not None:
            if used < self._reclaim_baseline:
                c.incr("disk_reclaimed_bytes", self._reclaim_baseline - used)
            self._reclaim_baseline = None
        for ev in self._watermark.tick(used):
            if ev == "soft_enter":
                c.incr("disk_soft_trips")
            elif ev == "hard_enter":
                c.incr("disk_hard_trips")
                self.pressure.set_hard(True)
            elif ev == "hard_exit":
                self.pressure.set_hard(False)
            rec.record("disk_pressure", node=self.name,
                       detail=f"{ev} used={used}")
        c.put("disk_pressure_state", self._watermark.state)
        self._health.note_disk_pressure(self._watermark.state)
        if self._watermark.soft:
            # reclaim every check while over the soft line: each pass
            # may free more (new applied entries -> higher snapshot)
            self._trigger_reclaim("soft_watermark")
        # slow-disk brownout: difference the WAL's cumulative fsync
        # counters into a mean-latency sample for the detector
        wc = self.wal.counter
        evs = self._brownout.sample(
            wc.get("fsyncs"), wc.get("fsync_time_us"))
        c.put("brownout_fsync_us", int(self._brownout.smoothed_us))
        for ev in evs:
            if ev == "enter":
                self.pressure.brownout = True
                c.incr("brownout_entered")
                c.put("brownout_active", 1)
                rec.record(
                    "brownout", node=self.name,
                    detail=f"enter fsync_us={int(self._brownout.smoothed_us)}",
                )
            else:
                self.pressure.brownout = False
                c.incr("brownout_exited")
                c.put("brownout_active", 0)
                rec.record("brownout", node=self.name, detail="exit")
        if self.pressure.brownout:
            # attempted every tick while the episode lasts: the first
            # transfer routinely loses to a not-yet-caught-up target
            # (transfer_leadership demands a confirmed match_index)
            self._shed_leaderships()

    def _shed_leaderships(self) -> None:
        """Browned out: hand every led group to a live peer. The
        transfer blocks on a future, so it runs off the detector
        thread; failures are fine — the next brownout tick retries
        while the episode lasts."""
        from ra_tpu.server import LEADER

        if self._shed_busy:
            return
        for name, proc in list(self.procs.items()):
            srv = proc.server
            if srv.role != LEADER:
                continue
            targets = [
                m for m in srv.members()
                if m != srv.id and self.transport.proc_alive(m)
            ]
            if not targets:
                continue
            self.pressure.counter.incr("brownout_sheds")

            self._shed_busy = True

            def xfer(sid=srv.id, to=targets[0]):
                from ra_tpu import api

                try:
                    api.transfer_leadership(sid, to, timeout=5.0)
                except Exception:  # noqa: BLE001
                    pass
                finally:
                    self._shed_busy = False

            threading.Thread(
                target=xfer, name=f"ra-brownout-shed-{name}", daemon=True
            ).start()

    def recover_registered(self) -> None:
        """server_recovery_strategy=registered: restart every registered
        server — machines come from the in-memory table or, on a cold
        boot, from the persisted machine factory."""
        for uid, name, cluster in self.directory.registered():
            machine = getattr(self, "_machines", {}).get(uid)
            rec = self.meta.fetch(uid, "__server_config__")
            if rec is None or name in self.procs:
                continue
            if machine is None and rec.get("machine_factory") is None:
                continue  # not reconstructable: skip (legacy servers)
            try:
                self.start_server(
                    name, cluster, machine, rec["members"], uid=uid,
                    machine_config=rec.get("machine_config"),
                    machine_factory=rec.get("machine_factory"),
                    _extra_cfg={
                        k: rec[k]
                        for k in self._PERSISTED_EXTRA_KEYS if k in rec
                    },
                )
            except Exception:  # noqa: BLE001 — one bad server must not
                # block recovery of the rest (or the whole node boot)
                logger.exception("recovery of server %r skipped", name)

    def _write_recovery_checkpoint(self, proc) -> None:
        """Orderly-shutdown capture so the next boot can skip replay
        (reference: maybe_write_recovery_checkpoint,
        src/ra_server.erl:2708-2762)."""
        from ra_tpu.protocol import SnapshotMeta

        srv = proc.server
        try:
            # the tick-driven last_applied persistence is async; make the
            # final watermark durable so boot replay targets it even if
            # the checkpoint below is unusable
            self.meta.store_sync(srv.cfg.uid, "last_applied", srv.last_applied)
            idx = srv.last_applied
            snap = srv.log.snapshot_index_term()
            if idx <= (snap[0] if snap else 0):
                return  # snapshot already covers the applied prefix
            term = srv.log.fetch_term(idx)
            if term is None:
                return
            mac = srv.machine.which_module(srv.effective_machine_version)
            srv.log.write_recovery_checkpoint(
                SnapshotMeta(
                    index=idx, term=term, cluster=tuple(srv.members()),
                    machine_version=srv.effective_machine_version,
                    live_indexes=tuple(mac.live_indexes(srv.machine_state)),
                ),
                srv.machine_state,
            )
        except Exception:  # noqa: BLE001 — best-effort: boot replays
            pass

    def _on_actor_crash(self, actor) -> None:
        """Supervision: restart a crashed server proc (rest_for_one
        equivalent for the proc+worker pair)."""
        name = actor.name
        try:
            # crashed state is suspect: no recovery checkpoint
            self.restart_server(name, orderly=False)
        except Exception:  # noqa: BLE001
            logger.exception("supervision: restart of %r failed", name)

    # ------------------------------------------------------------------
    # message delivery

    def deliver(self, to: ServerId, msg: Any, from_sid: Optional[ServerId]) -> bool:
        proc = self.procs.get(to[0])
        if proc is None:
            return False
        proc.enqueue(FromPeer(from_sid, msg) if from_sid is not None else msg)
        return True

    def _log_notify(self, uid: str, evt: Any) -> None:
        """Route WAL/segment-writer events to the owning proc."""
        name = self.directory.name_of(uid)
        if name is None:
            return
        proc = self.procs.get(name)
        if proc is not None:
            proc.enqueue(LogEvent(evt))

    def _log_notify_many(self, items: List[Tuple[str, Any]]) -> None:
        """Bulk WAL written-event fan-out: ONE call per fsync batch
        (the Wal emits at most one written event per writer per batch),
        enqueued to the server actors in a single pass on the WAL
        writer thread — durable acks leave without re-entering any
        shared queue (docs/INTERNALS.md §16)."""
        name_of = self.directory.name_of
        procs = self.procs
        for uid, evt in items:
            name = name_of(uid)
            if name is None:
                continue
            proc = procs.get(name)
            if proc is not None:
                proc.enqueue(LogEvent(evt))

    # ------------------------------------------------------------------
    # client plumbing

    def register_client_sink(self, who: Any, cb: Callable[[ServerId, list], None]) -> None:
        self._client_sinks[who] = cb

    def notify_client(self, who: Any, from_sid: ServerId, correlations: list) -> None:
        cb = self._client_sinks.get(who)
        if cb is not None:
            try:
                cb(from_sid, correlations)
            except Exception:  # noqa: BLE001
                pass

    def send_msg(self, to: Any, msg: Any, options) -> None:
        cb = self._client_sinks.get(to)
        if cb is not None:
            try:
                cb(None, [msg])
            except Exception:  # noqa: BLE001
                pass

    def submit_bg(self, eff: fx.BgWork, key: Optional[str] = None) -> None:
        """Run background work. With ``key`` (a server uid), jobs for
        the same key execute STRICTLY IN ORDER on a per-key queue while
        different keys proceed concurrently — the reference's per-server
        ra_worker contract (src/ra_worker.erl:12-26). Today the keyed
        producers are major-compaction passes (so one server's majors
        never overlap each other) and machine BgWork effects; snapshot
        writes run inline on the server thread and serialize against
        compaction through the SegmentSet lock. Keyless jobs use the
        shared pool."""
        if key is None:
            def run():
                try:
                    eff.fn()
                except BaseException as e:  # noqa: BLE001
                    if eff.err_fn is not None:
                        eff.err_fn(e)

            self.bg.submit(run)
            return
        actor = self._bg_actors.get(key)
        if actor is None:
            def run_batch(batch):
                for fn, err_fn in batch:
                    try:
                        fn()
                    except BaseException as e:  # noqa: BLE001
                        if err_fn is not None:
                            try:
                                err_fn(e)
                            except Exception:  # noqa: BLE001
                                logger.exception("bg err_fn for %r raised", key)
                        else:
                            logger.exception("bg job for %r failed", key)

            actor = self.bg_scheduler.actor(f"__bg__{key}", run_batch)
            self._bg_actors[key] = actor
        actor.send((eff.fn, eff.err_fn))

    # ------------------------------------------------------------------
    # failure detection (reference: aten poll-based node suspicion)

    def _supervise_log_infra(self) -> None:
        """one_for_all-style supervision of the shared log infra
        (reference: ra_system_sup / ra_log_sup restart the WAL and
        segment writer as a unit, src/ra_system_sup.erl:26-40,
        src/ra_log_sup.erl:20-63). Dependency order: the segment writer
        is revived FIRST — the WAL hands rollover flushes to it — then a
        dead WAL thread goes through the same wal_down -> reopen ->
        wal_up healing cycle as an I/O failure, with no operator
        action."""
        if not self.sw.thread_alive():
            # throttled (intensity exceeded): retry on a later poll,
            # once the oldest episode decays out of the window
            if self._note_infra_restart():
                logger.error(
                    "supervision: segment-writer thread died; reviving")
                self.sw.revive_thread()
                if not self.wal.failed and self.wal.thread_alive():
                    # the revive succeeded and the WAL is healthy: the
                    # sw-only throttle episode is over (the WAL restart
                    # path clears the flag on its own success)
                    self.infra_down = False
        if not self.wal.thread_alive() and not self.wal.failed:
            logger.error("supervision: wal thread died; restarting log infra")
            self._on_wal_failure(RuntimeError("wal writer thread died"))

    def _health_sweep(self, now: float) -> None:
        """Actor-backend health scan (docs/INTERNALS.md §14): one host
        sweep over the live procs' scalar mirrors (bounded by PROC
        count, not group count — the thousands-of-groups path is the
        coordinator's vectorized fetch), folded into the shared
        vectorized scanner so both backends classify identically."""
        import numpy as np

        from ra_tpu import health as ra_health

        rows = []
        for name, proc in list(self.procs.items()):
            try:
                rows.append((name,) + proc.server.health_row())
            except Exception:  # noqa: BLE001 — raced a restart: next tick
                continue
        if not rows:
            return
        sc = self._health
        sc.counters.incr("health_fetches")  # one sweep == one fetch operation
        slots = np.fromiter(
            (sc.ensure(r[0], r[1]) for r in rows), np.int64, len(rows)
        )
        col = lambda i, dt: np.fromiter(  # noqa: E731
            (r[i] for r in rows), dt, len(rows)
        )
        leader_key = np.fromiter(
            (ra_health.NO_LEADER_KEY if r[8] is None else r[8]
             for r in rows),
            np.int64, len(rows),
        )
        sc.scan(
            now, slots, col(2, np.int8), col(3, np.int64), col(4, np.int64),
            col(5, np.int64), col(6, np.int64), col(7, np.int64), leader_key,
        )

    def _detect_loop(self) -> None:
        _t = self.clock

        last_health = 0.0
        while self.running:
            try:
                self._supervise_log_infra()
                _now_h = _t.monotonic()
                if _now_h - last_health >= self.tick_interval_s:
                    last_health = _now_h
                    self._health_sweep(_now_h)
                    self.detector.publish()
                self._tick_storage(_now_h)
                # include previously-seen names: a stopped node
                # unregisters, and its disappearance must read as death
                known = set(self.transport.known_nodes()) | set(self._node_status)
                for other in known:
                    if other == self.name:
                        continue
                    # over TCP, node_alive consults the phi-accrual
                    # detector fed by pong arrivals (adaptive window);
                    # in-proc, registry membership is ground truth
                    alive = self.transport.node_alive(other)
                    prev = self._node_status.get(other)
                    if prev is None:
                        self._node_status[other] = alive
                        continue
                    if prev != alive:
                        self._node_status[other] = alive
                        status = "up" if alive else "down"
                        for proc in list(self.procs.values()):
                            proc.on_node_event(other, status)
                # suspicion sweep: transitions can be missed (a leader
                # that dies before its node was ever recorded alive).
                # Three leaderless shapes arm an election timer (the
                # same shapes the batch coordinator retries — a live
                # leader's tick sends an empty commit-sync AER to every
                # peer, so "no contact for several ticks" is a reliable
                # leaderless signal here too):
                #   - known leader on a DEAD node, stale contact;
                #   - known leader alive but SILENT well past the tick
                #     cadence (a deposed leader that never re-won);
                #   - NO known leader after a term bump (a failed
                #     election left everyone leaderless). term > 0 keeps
                #     fresh boots quiet until explicitly triggered.
                from ra_tpu.server import AWAIT_CONDITION, FOLLOWER

                now = _t.monotonic()
                contact_window = max(
                    5 * self.tick_interval_s, 6 * self.election_timeout_s
                )
                for proc in list(self.procs.values()):
                    srv = proc.server
                    if (
                        srv.role not in (FOLLOWER, AWAIT_CONDITION)
                        or not srv.is_voter_self()
                        or proc._election_ref is not None
                    ):
                        continue
                    leader = srv.leader_id
                    stale = now - proc.last_leader_contact
                    if leader is not None and leader != srv.id:
                        if (
                            not self.transport.node_alive(leader[1])
                            and stale > 2 * self.election_timeout_s
                        ) or stale > contact_window:
                            proc.arm_election_timer()
                    elif srv.current_term > 0 and stale > contact_window:
                        proc.arm_election_timer()
            except Exception:  # noqa: BLE001
                pass
            _t.sleep(self._detector_poll_s)

    def on_proc_down(self, sid: ServerId) -> None:
        """A proc (possibly remote) died: followers whose leader it was
        arm election timers; machine monitors fire DownEvents."""
        from ra_tpu.server import AWAIT_CONDITION, FOLLOWER

        for proc in list(self.procs.values()):
            srv = proc.server
            if (
                srv.leader_id == sid
                and srv.role in (FOLLOWER, AWAIT_CONDITION)
                and srv.is_voter_self()
            ):
                proc.arm_election_timer()
        for watcher, component in self.monitors.watchers("process", sid):
            proc = self.procs.get(watcher[0])
            if proc is not None:
                proc.on_monitor_down(sid, "noproc", component)

    # ------------------------------------------------------------------

    def overview(self) -> dict:
        return {
            "node": self.name,
            "servers": {
                uid: {"name": n, "role": r, "leader": l}
                for uid, (n, r, l) in self.ra_state.items()
            },
            "wal": self.wal.overview(),
            "infra_down": self.infra_down,
            "infra_restarts_in_window": len(self._infra_restarts),
            "storage_degraded": self.pressure.degraded,
            "disk_pressure_state": self._watermark.state,
            "brownout": self.pressure.brownout,
        }

    def stop(self) -> None:
        self.running = False
        from ra_tpu import health as ra_health

        ra_health.unregister(self.name)
        self.pressure.delete()
        # the detect loop publishes phi gauges: join it BEFORE closing
        # the detector, or an in-flight publish() re-registers the
        # gauge vectors close() just deleted (registry ghost)
        try:
            self._detector.join(timeout=2 * self._detector_poll_s + 1)
        except RuntimeError:
            pass  # stop() issued from the detector thread itself
        self.detector.close()
        for name in list(self.procs):
            self.stop_server(name)
        self.wal.close()
        self.sw.close()
        self.sync_pool.close()
        self.meta.close()
        self.scheduler.close()
        self.bg_scheduler.close()
        self.timers.close()
        self.bg.shutdown(wait=False)
        closer = getattr(self.transport, "close", None)
        if closer is not None:
            closer()
        self._registry.unregister(self.name)
