"""ServerProc: the runtime shell around one consensus core.

The counterpart of the reference's ``ra_server_proc`` gen_statem
(``src/ra_server_proc.erl``): owns the mailbox, realises effects
(sends, replies, vote fan-out, snapshot sender, timers, monitors,
leaderboard records, background work), manages election/tick timers, and
batches client commands per mailbox drain (the reference's low-priority
command queue + AER batching play this role).

Election liveness follows the reference's no-idle-heartbeats design
(reference: docs/internals/INTERNALS.md:290-327): followers arm a
randomized election timer only on leader-down evidence (node failure
detector, leader proc DOWN) and disarm it on any contact from the
leader; pre-vote/candidate states keep a timer armed to retry stalled
elections.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional

from ra_tpu import effects as fx
from ra_tpu import leaderboard
from ra_tpu.protocol import (
    AppendEntriesRpc,
    CHUNK_INIT,
    CHUNK_LAST,
    CHUNK_NEXT,
    CHUNK_PRE,
    Command,
    DownEvent,
    ElectionTimeout,
    FromPeer,
    HeartbeatRpc,
    InstallSnapshotAck,
    InstallSnapshotResult,
    InstallSnapshotRpc,
    LogEvent,
    NodeEvent,
    ServerId,
    Tick,
    USR,
)
from ra_tpu.server import (
    AWAIT_CONDITION,
    CANDIDATE,
    ConditionTimeout,
    FOLLOWER,
    LEADER,
    PRE_VOTE,
    RECEIVE_SNAPSHOT,
    Server,
)


class SnapshotSender:
    """Chunked snapshot sender to one peer (the reference spawns a
    transient process per transfer: src/ra_server_proc.erl:1691-1735).

    The snapshot payload (meta, body source, live entries) is captured
    on the owning proc thread *before* this thread starts — the log is
    single-owner and must not be read concurrently. Preferred body
    source is ``chunk_iter``, a byte-chunk iterator reading the
    already-serialized body straight FROM DISK (the fd was opened at
    capture time, so the stream survives snapshot pruning) — peak sender
    memory is O(chunk), matching the reference's begin_read/read_chunk
    protocol (src/ra_snapshot.erl:135-210). ``state_obj`` is the
    fallback for memory-backed logs: pickled in one blob on this
    thread."""

    def __init__(
        self,
        proc: "ServerProc",
        to: ServerId,
        meta,
        state_obj,
        live_entries: list,
        term: int,
        chunk_size: int,
        chunk_iter=None,
    ):
        self.proc = proc
        self.to = to
        self.meta = meta
        self.state_obj = state_obj
        self.chunk_iter = chunk_iter
        self.chunk_size = chunk_size
        self.live_entries = live_entries
        self.term = term
        self.acks: "threading.Condition" = threading.Condition()
        self.last_ack: int = -1
        # receiver-paced credit window (docs/INTERNALS.md §21): highest
        # chunk_no the receiver has authorized = last ack's chunk_no +
        # its granted credits. Old-format acks default credits=1, which
        # reproduces stop-and-wait exactly.
        self.window_until: int = 0
        self.result: Optional[InstallSnapshotResult] = None
        self.thread = threading.Thread(
            target=self._run, name=f"ra-snap-send-{to[0]}", daemon=True
        )

    def start(self) -> None:
        self.thread.start()

    def on_ack(self, ack: InstallSnapshotAck) -> None:
        with self.acks:
            self.last_ack = max(self.last_ack, ack.chunk_no)
            credits = max(0, getattr(ack, "credits", 1))
            self.window_until = max(self.window_until, ack.chunk_no + credits)
            self.acks.notify()

    def on_result(self, res: InstallSnapshotResult) -> None:
        with self.acks:
            self.result = res
            self.acks.notify()

    def _await_ack(self, chunk_no: int, timeout: float) -> str:
        """-> "ack" | "result" (terminal reply: stop streaming) |
        "timeout". Wall clock on purpose (clock-seam audit, INTERNALS
        §19): this blocks a real Condition on a real sender thread —
        paths the simulation plane never runs."""
        deadline = time.monotonic() + timeout
        with self.acks:
            while True:
                if self.result is not None:
                    return "result"
                if self.last_ack >= chunk_no:
                    return "ack"
                left = deadline - time.monotonic()
                if left <= 0:
                    return "timeout"
                self.acks.wait(timeout=left)

    def _acquire_credit(self, no: int, timeout: float, send) -> str:
        """Block until the receiver's credit window covers chunk ``no``
        -> "ok" | "result" | "timeout". Credits ride acks, and a
        storage-blocked receiver grants 0 — with no chunks in flight it
        would never ack again, so starvation is probed by re-sending an
        already-acked chunk number (a duplicate the receiver re-acks
        with its CURRENT grant, without appending). Starvation past the
        ack timeout fails the transfer into the existing
        backoff-and-retry machinery (docs/INTERNALS.md §21)."""
        deadline = time.monotonic() + timeout
        while True:
            with self.acks:
                if self.result is not None:
                    return "result"
                if self.window_until >= no:
                    return "ok"
                left = deadline - time.monotonic()
                if left <= 0:
                    return "timeout"
                starved = not self.acks.wait(timeout=min(0.5, left))
                probe_no = self.last_ack
            if starved and probe_no >= 0:
                # outside the lock: transports may deliver inline
                count = getattr(self.proc.server, "_c", None)
                if count is not None:
                    count("snapshot_credit_waits")
                send(probe_no, CHUNK_NEXT)

    def _run(self) -> None:
        proc = self.proc
        try:
            if self.chunk_iter is not None:
                chunk_src = self.chunk_iter  # lazy reads from disk
            else:
                # memory-backed fallback: serialization happens HERE,
                # off the consensus threads — the state object was
                # captured immutably by the owning thread
                import pickle

                blob = pickle.dumps(self.state_obj)
                cs = self.chunk_size
                chunk_src = iter(
                    [blob[o : o + cs] for o in range(0, max(len(blob), 1), cs)]
                    or [b""]
                )
            timeout = proc.snapshot_ack_timeout_s

            def send(no, phase, data=b""):
                proc.transport.send(
                    self.to,
                    InstallSnapshotRpc(
                        term=self.term, leader_id=proc.server.id, meta=self.meta,
                        chunk_no=no, chunk_phase=phase, data=data,
                    ),
                    from_sid=proc.server.id,
                )

            def finish_on(status) -> bool:
                if status == "timeout":
                    proc.enqueue(("snapshot_send_failed", self.to))
                    return True
                if status == "result":
                    # terminal reply mid-transfer (e.g. stale term):
                    # surface it and stop streaming
                    proc.enqueue(("snapshot_send_done", self.to, self.result))
                    return True
                return False

            send(0, CHUNK_INIT)
            if finish_on(self._await_ack(0, timeout)):
                return
            no = 1
            if self.live_entries:
                send(no, CHUNK_PRE, self.live_entries)
                if finish_on(self._await_ack(no, timeout)):
                    return
                no += 1
            # body chunks stream under the receiver-granted credit
            # window (in-flight <= credits; old acks grant 1, which IS
            # stop-and-wait) — a one-chunk lookahead tags the final
            # chunk CHUNK_LAST while holding at most two chunks in
            # memory
            pending = next(chunk_src, b"")
            for chunk in chunk_src:
                if finish_on(self._acquire_credit(no, timeout, send)):
                    return
                send(no, CHUNK_NEXT, pending)
                no += 1
                pending = chunk
            if finish_on(self._acquire_credit(no, timeout, send)):
                return
            send(no, CHUNK_LAST, pending)
            # final result arrives as InstallSnapshotResult; wait for it
            deadline = time.monotonic() + timeout
            with self.acks:
                while self.result is None and time.monotonic() < deadline:
                    self.acks.wait(timeout=0.1)
            if self.result is None:
                proc.enqueue(("snapshot_send_failed", self.to))
            else:
                proc.enqueue(("snapshot_send_done", self.to, self.result))
        except Exception:  # noqa: BLE001
            proc.enqueue(("snapshot_send_failed", self.to))


class ServerProc:
    def __init__(self, node, server: Server):
        self.node = node
        self.server = server
        self.transport = node.transport
        self.timers = node.timers
        self.clock = getattr(node, "clock", None)
        if self.clock is None:
            from ra_tpu.runtime.clock import WALL

            self.clock = WALL
        self.name = server.id[0]
        self.actor = node.scheduler.actor(self.name, self._on_batch)
        self.tick_interval_s = node.tick_interval_s
        self.election_timeout_s = node.election_timeout_s
        self.snapshot_ack_timeout_s = 120.0
        # default await_condition hold before the condition's timeout
        # path runs (reference: ?DEFAULT_AWAIT_CONDITION_TIMEOUT 30 s,
        # src/ra_server_proc.erl:69); a Condition can override per-hold
        self.await_condition_timeout_s = getattr(
            node, "await_condition_timeout_s", 30.0
        )
        self._election_ref: Optional[int] = None
        self._condition_ref: Optional[int] = None
        self._tick_ref: Optional[int] = None
        self.last_leader_contact: float = self.clock.monotonic()
        # commit-rate gauge (reference: ra_li leaky integrator driving the
        # commit_rate overview gauge)
        from ra_tpu.li import LeakyIntegrator

        self._commit_rate = LeakyIntegrator()
        # seed with the recovered commit index so the first sample
        # measures new traffic, not the entire recovered history
        self._last_commit_sample = (self.clock.monotonic(), server.commit_index)
        self._senders: Dict[ServerId, SnapshotSender] = {}
        self._snap_retry: Dict[ServerId, Any] = {}  # peer -> retry timer ref
        self._machine_timers: Dict[Any, int] = {}
        # buffered low-priority commands (reference: ra_ets_queue)
        from collections import deque as _deque

        self._low_q = _deque()
        self._stale_h = None  # lazy follower_read_staleness histogram
        self.running = True
        self._set_tick_timer()
        # a server that starts without evidence of a LIVE leader must arm
        # an election timer, or a restarted ex-leader (leader_id == self,
        # excluded from every suspicion check) wedges the whole cluster:
        # the behind followers lose pre-votes against its longer log and
        # IT never stands (reference: servers arm a state timeout on
        # entering follower after recovery). First AER contact disarms.
        if (
            server.role == FOLLOWER
            and server.is_voter_self()
            and (server.leader_id is None or server.leader_id == server.id)
        ):
            self.arm_election_timer()
        self._update_state_table()

    # ------------------------------------------------------------------

    def enqueue(self, msg: Any, front: bool = False) -> None:
        self.actor.send(msg, front=front)

    def _stop_self(self) -> None:
        try:
            self.node.stop_server(self.name)
        except Exception:  # noqa: BLE001 — already stopped is fine
            pass

    def kill(self) -> None:
        self.running = False
        self.timers.cancel(self._tick_ref)
        self.timers.cancel(self._election_ref)
        self.actor.kill()

    # ------------------------------------------------------------------

    # max low-priority commands appended per drain (reference:
    # ?FLUSH_COMMANDS_SIZE, src/ra_server.hrl:34)
    FLUSH_COMMANDS_SIZE = 16

    def _on_batch(self, batch: List[Any]) -> None:
        server = self.server
        i = 0
        n = len(batch)
        while i < n:
            msg = batch[i]
            # coalesce consecutive client commands into one core call;
            # low-priority commands are set aside and drained in bounded
            # slices after normal traffic (reference: ra_ets_queue lane,
            # src/ra_server_proc.erl:507-530)
            if isinstance(msg, Command) and server.role == LEADER:
                cmds = [msg]
                while i + 1 < n and isinstance(batch[i + 1], Command):
                    i += 1
                    cmds.append(batch[i])
                low = [c for c in cmds if c.priority == "low"]
                if low:
                    self._low_q.extend(low)
                    cmds = [c for c in cmds if c.priority != "low"]
                effects = (
                    server.handle(cmds if len(cmds) > 1 else cmds[0])
                    if cmds
                    else []
                )
            elif isinstance(msg, tuple) and msg and msg[0] == "flush_low":
                effects = []  # drain happens below once per batch
            elif isinstance(msg, tuple) and msg and msg[0] in (
                "snapshot_send_done",
                "snapshot_send_failed",
            ):
                effects = self._handle_sender_event(msg)
            elif isinstance(msg, tuple) and msg and msg[0] == "reclaim_storage":
                self._reclaim_storage()
                effects = []
            elif isinstance(msg, tuple) and msg and msg[0] in (
                "local_query",
                "leader_query",
                "state_query",
                "consistent_query",
            ):
                effects = self._handle_query(msg)
            elif isinstance(msg, FromPeer) and isinstance(
                msg.msg, (InstallSnapshotAck, InstallSnapshotResult)
            ) and msg.peer in self._senders:
                sender = self._senders[msg.peer]
                if isinstance(msg.msg, InstallSnapshotAck):
                    sender.on_ack(msg.msg)
                else:
                    sender.on_result(msg.msg)
                effects = []
            else:
                if isinstance(msg, FromPeer):
                    self._note_contact(msg)
                elif isinstance(msg, Tick):
                    self._sample_commit_rate()
                    if server.role == LEADER:
                        # reconnect probing: peers marked disconnected by
                        # failed sends are retried once reachable again
                        # (the reference flips status on nodeup; proc
                        # restarts on a live node need the same)
                        for sid, p in server.peers().items():
                            if p.status == "disconnected" and self.transport.proc_alive(sid):
                                p.status = "normal"
                effects = server.handle(msg)
            self._execute(effects)
            i += 1
        if self._low_q and server.role == LEADER:
            take = [
                self._low_q.popleft()
                for _ in range(min(self.FLUSH_COMMANDS_SIZE, len(self._low_q)))
            ]
            self._execute(server.handle(take if len(take) > 1 else take[0]))
            if self._low_q:
                # keep the actor hot until the lane drains (dedicated
                # sentinel: a synthetic Tick would run the full leader
                # tick and skew the commit-rate gauge per slice)
                self.enqueue(("flush_low",))
        self._update_state_table()

    def _note_contact(self, msg: FromPeer) -> None:
        """A message from a live leader disarms the election timer. A
        stale in-flight message from an already-dead sender is NOT
        liveness evidence — without this check a dead leader's last AERs
        can cancel the armed timer and leave the cluster leaderless."""
        if not isinstance(msg.msg, (AppendEntriesRpc, InstallSnapshotRpc, HeartbeatRpc)):
            return
        self.last_leader_contact = self.clock.monotonic()
        if (
            self.server.role in (FOLLOWER, AWAIT_CONDITION, RECEIVE_SNAPSHOT)
            and self._election_ref is not None
            and self.transport.proc_alive(msg.peer)
        ):
            self.timers.cancel(self._election_ref)
            self._election_ref = None

    def _handle_query(self, msg) -> List[fx.Effect]:
        """Queries served at the proc layer (reference: ra_server_proc
        query/5 handling — local/leader direct, consistent via the core's
        heartbeat round)."""
        server = self.server
        kind = msg[0]
        if kind == "consistent_query":
            _, fn, fut = msg
            if server.role == LEADER:
                return server.handle(("consistent_query", fn, fut))
            self._reply(fut, ("redirect", server.leader_id))
            return []
        if kind == "local_query":
            # ("local_query", fn, fut) or a 4-tuple carrying the
            # caller's max_staleness_s bound: the bounded form only
            # answers when the leader-stamped freshness floor proves
            # local state is recent enough (docs/INTERNALS.md §20);
            # otherwise ("stale", bound, leader_hint) so the caller can
            # retry against the leader
            fn, fut = msg[1], msg[2]
            if len(msg) > 3 and msg[3] is not None:
                staleness = server.read_staleness_s()
                self._staleness_hist().record_seconds(
                    min(staleness, 3600.0)
                )
                if staleness > msg[3]:
                    server._c("read_stale_rejected")
                    self._reply(fut, ("stale", staleness, server.leader_id))
                    return []
                server._c("read_local_bounded")
            self._reply(fut, ("ok", fn(server.machine_state), server.leader_id))
            return []
        _, fn, fut = msg
        if kind == "state_query":
            self._reply(fut, ("ok", fn(server), server.leader_id))
        elif kind == "leader_query":
            if server.role == LEADER:
                self._reply(fut, ("ok", fn(server.machine_state), server.id))
            else:
                self._reply(fut, ("redirect", server.leader_id))
        return []

    def _staleness_hist(self):
        if self._stale_h is None:
            from ra_tpu import obs as _obs

            self._stale_h = _obs.staleness_hist(self.server.id[1])
        return self._stale_h

    def _reclaim_storage(self) -> None:
        """Emergency reclamation on the owning thread (storage-pressure
        plane, docs/INTERNALS.md §21): force a machine snapshot at the
        applied index — bypassing min_snapshot_interval — which
        truncates memtables, retires segments, prunes superseded
        snapshots/checkpoints, and schedules minor-driven compaction;
        then run one explicit major compaction pass. Best-effort: a
        snapshot write that itself hits ENOSPC leaves the log exactly
        as it was."""
        srv = self.server
        try:
            idx = srv.last_applied
            snap = srv.log.snapshot_index_term()
            if idx > (snap[0] if snap else 0):
                mac = srv.machine.which_module(srv.effective_machine_version)
                srv.log.force_snapshot(
                    idx, tuple(srv.members()), srv.effective_machine_version,
                    srv.machine_state,
                    live_indexes=tuple(mac.live_indexes(srv.machine_state)),
                )
                if srv.log.snapshot_index_term() != snap:
                    srv._c("snapshots_written")
                    srv._c("releases")
            srv.log.major_compaction()
        except Exception:  # noqa: BLE001 — reclamation must never kill
            pass  # the proc; the watermark tick just retries

    def _handle_sender_event(self, msg) -> List[fx.Effect]:
        if msg[0] == "snapshot_send_done":
            _, to, result = msg
            self._senders.pop(to, None)
            return self.server.handle(result, from_peer=to)
        _, to = msg
        self._senders.pop(to, None)
        # exponential backoff instead of an immediate pipeline retry
        # (reference: snapshot_sender_exponential_backoff)
        return self.server.handle(("snapshot_sender_down", to, "failed"))

    # ------------------------------------------------------------------
    # effect executor (reference: handle_effects src/ra_server_proc.erl:1530)

    def _execute(self, effects: List[fx.Effect]) -> None:
        # machine append effects are collected and front-enqueued as one
        # ordered block after the loop — per-effect appendleft would
        # reverse their relative order vs the reference's in-order
        # next_event realisation (src/ra_server_proc.erl:1604-1615)
        appends: List[Command] = []
        for eff in effects:
            if isinstance(eff, fx.SendRpc):
                ok = self.transport.send(eff.to, eff.msg, from_sid=self.server.id)
                if not ok:
                    peer = self.server.cluster.get(eff.to)
                    if peer is not None and peer.status == "normal":
                        peer.status = "disconnected"
            elif isinstance(eff, fx.SendVoteRequests):
                for to, rpc in eff.requests:
                    self.transport.send(to, rpc, from_sid=self.server.id)
            elif isinstance(eff, fx.NextEvent):
                m = eff.msg
                self.enqueue(m, front=True)
            elif isinstance(eff, fx.Reply):
                self._reply(eff.from_ref, eff.reply)
            elif isinstance(eff, fx.Notify):
                self.node.notify_client(eff.who, self.server.id, list(eff.correlations))
            elif isinstance(eff, fx.SendMsg):
                self.node.send_msg(eff.to, eff.msg, eff.options)
            elif isinstance(eff, fx.RecordLeader):
                leaderboard.record(eff.cluster_name, eff.leader, eff.members)
            elif isinstance(eff, fx.SendSnapshot):
                self._start_snapshot_sender(eff.to)
            elif isinstance(eff, fx.StateEnter):
                self._on_state_enter(eff.role)
            elif isinstance(eff, fx.StopServer):
                # the server's own removal committed: terminate off the
                # actor thread (stop_server joins this actor); the
                # proc-down broadcast lets the rest of the cluster elect
                threading.Thread(
                    target=self._stop_self, name=f"ra-stop-{self.name}",
                    daemon=True,
                ).start()
            elif isinstance(eff, fx.StartSnapshotRetryTimer):
                self._arm_snapshot_retry(eff.to, eff.delay_ms)
            elif isinstance(eff, fx.Timer):
                self._machine_timer(eff)
            elif isinstance(eff, fx.ModCall):
                try:
                    eff.fn(*eff.args)
                except Exception:  # noqa: BLE001
                    pass
            elif isinstance(eff, fx.BgWork):
                self.node.submit_bg(eff, key=self.server.cfg.uid)
            elif isinstance(eff, fx.Monitor):
                self.node.monitors.add(self.server.id, eff.kind, eff.target, eff.component)
            elif isinstance(eff, fx.Demonitor):
                self.node.monitors.remove(self.server.id, eff.kind, eff.target)
            elif isinstance(eff, fx.LogRead):
                entries = self.server.log.sparse_read(list(eff.indexes))
                out = eff.fn(entries)
                if out is not None:
                    self.enqueue(out)
            elif isinstance(eff, fx.Aux):
                self.enqueue(("aux", "cast", eff.cmd, None))
            elif isinstance(eff, fx.Append):
                # leader-only machine append, re-entering as a command
                # (reference: {append, ...} -> next_event,
                # src/ra_server_proc.erl:1604-1609)
                if self.server.role == LEADER:
                    appends.append(Command(
                        kind=USR, data=eff.cmd, reply_mode=eff.reply_mode,
                        from_ref=eff.from_ref, internal=True,
                    ))
            elif isinstance(eff, fx.TryAppend):
                # attempted in ANY raft state; a non-leader's command
                # routing redirects it (reference:
                # src/ra_server_proc.erl:1610-1615). Only the leader's
                # copy carries the reply ref — every replica realises
                # this effect, and a follower's redirect must not race
                # the leader's ok on the same future
                appends.append(Command(
                    kind=USR, data=eff.cmd, reply_mode=eff.reply_mode,
                    from_ref=(
                        eff.from_ref if self.server.role == LEADER else None
                    ),
                    internal=True,
                ))
        # front-enqueue in reverse so the mailbox reads in emission order
        for cmd in reversed(appends):
            self.enqueue(cmd, front=True)

    def _reply(self, from_ref: Any, reply: Any) -> None:
        setter = getattr(from_ref, "set_result", None)
        if setter is not None:
            setter(reply)
        elif callable(from_ref):
            from_ref(reply)

    # ------------------------------------------------------------------
    # timers

    def _set_tick_timer(self) -> None:
        if not self.running:
            return
        self._tick_ref = self.timers.after(self.tick_interval_s, self._on_tick)

    def _on_tick(self) -> None:
        if not self.running:
            return
        self.enqueue(Tick(now_ms=int(self.clock.time() * 1000)))
        self._set_tick_timer()

    def _sample_commit_rate(self) -> None:
        """Runs on the actor thread (single-owner server state)."""
        now = self.clock.monotonic()
        prev_t, prev_ci = self._last_commit_sample
        ci = self.server.commit_index
        rate = self._commit_rate.sample(max(0, ci - prev_ci), now - prev_t)
        self._last_commit_sample = (now, ci)
        if self.server.counter is not None:
            # round, don't truncate: sub-1/s rates must not read as idle
            self.server.counter.put("commit_rate", int(round(rate)))

    def arm_election_timer(self, immediate: bool = False) -> None:
        from ra_tpu.runtime.timers import randomized_election_timeout

        if not self.running:
            return
        self.timers.cancel(self._election_ref)
        delay = 0.0 if immediate else randomized_election_timeout(self.election_timeout_s)
        self._election_ref = self.timers.after(delay, self._on_election_timeout)

    def _on_election_timeout(self) -> None:
        self._election_ref = None
        if self.running:
            self.enqueue(ElectionTimeout())

    def _on_condition_timeout(self, generation: int) -> None:
        self._condition_ref = None
        if self.running:
            self.enqueue(ConditionTimeout(generation=generation))

    def _on_state_enter(self, role: str) -> None:
        if role != LEADER and self._low_q:
            # leadership lost with lows still buffered: drop them —
            # replaying them under a later term would double-apply
            # commands the client already resent to the new leader
            # (pipeline commands are at-most-once; clients track
            # correlations). A buffered command with a reply future must
            # hear the redirect, not hang until timeout.
            leader = self.server.leader_id
            for cmd in self._low_q:
                fut = getattr(cmd, "from_ref", None)
                if fut is not None:
                    self._reply(fut, ("redirect", leader))
            self._low_q.clear()
        if role != AWAIT_CONDITION and self._condition_ref is not None:
            self.timers.cancel(self._condition_ref)
            self._condition_ref = None
        if role in (PRE_VOTE, CANDIDATE):
            self.arm_election_timer()  # retry a stalled election round
        elif role == AWAIT_CONDITION:
            # the condition timer runs the Condition's timeout path
            # (repeating a catch-up failure reply, falling back to
            # leader); the election timer is armed ONLY with leaderless
            # evidence — a transferring ex-leader or a holding follower
            # whose leader is alive must not start disruptive pre-votes
            # (the failure detector arms it if the leader dies later)
            leader = self.server.leader_id
            if (
                leader is not None
                and leader != self.server.id
                and not self.transport.proc_alive(leader)
                and self.server.is_voter_self()
            ):
                self.arm_election_timer()
            else:
                self.timers.cancel(self._election_ref)
                self._election_ref = None
            cond = self.server.condition
            dur_s = self.await_condition_timeout_s
            if cond is not None and cond.timeout_duration_ms is not None:
                dur_s = cond.timeout_duration_ms / 1000.0
            gen = self.server.condition_generation
            self.timers.cancel(self._condition_ref)
            self._condition_ref = self.timers.after(
                dur_s, lambda: self._on_condition_timeout(gen)
            )
        elif role == LEADER:
            self.timers.cancel(self._election_ref)
            self._election_ref = None
        elif role == FOLLOWER:
            # reverting to follower on a stale message from a dead leader
            # must keep an election pending, or the cluster livelocks
            leader = self.server.leader_id
            if (
                leader is not None
                and leader != self.server.id
                and not self.transport.proc_alive(leader)
                and self.server.is_voter_self()
            ):
                self.arm_election_timer()
            else:
                self.timers.cancel(self._election_ref)
                self._election_ref = None

    def _machine_timer(self, eff: fx.Timer) -> None:
        old = self._machine_timers.pop(eff.name, None)
        self.timers.cancel(old)
        if eff.ms is None:
            return

        def fire():
            self._machine_timers.pop(eff.name, None)
            if self.running and self.server.role == LEADER:
                from ra_tpu.protocol import USR

                self.enqueue(Command(kind=USR, data=("timeout", eff.name),
                                     internal=True))

        self._machine_timers[eff.name] = self.timers.after(eff.ms / 1000.0, fire)

    # ------------------------------------------------------------------

    def _arm_snapshot_retry(self, to: ServerId, delay_ms: int) -> None:
        old = self._snap_retry.pop(to, None)
        self.timers.cancel(old)

        def fire():
            self._snap_retry.pop(to, None)
            if self.running:
                self.enqueue(("snapshot_retry_timeout", to))

        self._snap_retry[to] = self.timers.after(delay_ms / 1000.0, fire)

    def _start_snapshot_sender(self, to: ServerId) -> None:
        from ra_tpu.server import status_kind

        if to in self._senders:
            return
        old = self._snap_retry.pop(to, None)
        self.timers.cancel(old)
        peer = self.server.cluster.get(to)
        # a retry emits SendSnapshot while the peer still carries its
        # snapshot_backoff count; the send flips it to sending_snapshot
        # WITH the count so another death keeps backing off
        if peer is not None and status_kind(peer.status) == "snapshot_backoff":
            peer.status = ("sending_snapshot", peer.status[1])
        # capture the payload here, on the proc thread: the log is
        # single-owner and must not be read from the sender thread.
        # Prefer the disk-streaming reader (no decode, no blob) and fall
        # back to the whole-state read for memory-backed logs
        chunk_size = self.node.config.snapshot_chunk_size
        state = None
        chunk_iter = None
        stream = self.server.log.begin_snapshot_read(chunk_size)
        if stream is not None:
            meta, chunk_iter = stream
        else:
            got = self.server.log.read_snapshot()
            if got is None:
                if peer is not None and status_kind(peer.status) == "sending_snapshot":
                    peer.status = "normal"
                return
            meta, state = got
        live_entries = (
            self.server.log.sparse_read(list(meta.live_indexes))
            if meta.live_indexes
            else []
        )
        sender = SnapshotSender(
            self, to, meta, state, live_entries, self.server.current_term,
            chunk_size, chunk_iter=chunk_iter,
        )
        self._senders[to] = sender
        sender.start()

    def _update_state_table(self) -> None:
        self.node.ra_state[self.server.cfg.uid] = (
            self.name,
            self.server.role,
            self.server.leader_id,
        )

    # ------------------------------------------------------------------
    # failure-detector input

    def on_monitor_down(self, target, info, component: str) -> None:
        """Dispatch a monitor DOWN to the registered component
        (reference: ra_monitors routes DOWNs to machine / aux /
        snapshot_sender, src/ra_monitors.erl:10-22)."""
        if component == "aux":
            self.enqueue(("aux", "cast", ("down", target, info), None))
        elif component == "snapshot_sender":
            # treat like a failed transfer to that peer: backoff/retry
            if target in self._senders:
                self.enqueue(("snapshot_send_failed", target))
        else:  # "machine" (default): the down builtin via consensus
            self.enqueue(DownEvent(target, info))

    def on_node_event(self, node_name: str, status: str) -> None:
        """Called (via mailbox) when the failure detector flips a node."""
        srv = self.server
        if status == "down":
            leader = srv.leader_id
            if (
                srv.role in (FOLLOWER, AWAIT_CONDITION)
                and leader is not None
                and leader[1] == node_name
                and srv.is_voter_self()
            ):
                self.arm_election_timer()
        if srv.role == LEADER:
            self.enqueue(NodeEvent(node_name, status))
