"""TCP transport: real multi-process/multi-host clusters.

The distributed communication backend (counterpart of the reference's
use of Erlang distribution: async casts with noconnect/nosuspend
semantics and backpressure-aware peer status, reference:
src/ra_server_proc.erl:1875-1881, 2094-2110):

- node names are ``host:port`` strings; each node runs one
  ``TcpTransport`` that accepts inbound connections and lazily dials
  outbound ones;
- wire format: length-framed ``HMAC-SHA256(cookie) || pickle`` of
  ``(to_name, from_sid, msg)``. Every frame is authenticated with a
  shared-secret cookie before it is unpickled (the counterpart of the
  Erlang distribution cookie): a frame with a bad MAC kills the
  connection without touching pickle. **Trust model**: inbound frames
  deserialize through a RESTRICTED unpickler — only the protocol/effect
  vocabulary, plain containers, and application-registered payload
  types resolve (``register_wire_type``); a cookie holder cannot smuggle
  os/subprocess/functools gadget chains. Still set a secret cookie
  (``RA_TPU_COOKIE`` env or the ``cookie=`` arg): authenticated peers
  can of course drive the full management plane;
- sends are async and never block the caller: each peer has a bounded
  outbox drained by a writer thread — when the outbox overflows, sends
  report failure (the peer status flips, exactly like distribution
  buffer backpressure in the reference);
- at-most-once delivery; reconnection is lazy on next send.

``TcpNodeBridge`` glues a transport to a local RaNode/BatchCoordinator:
inbound messages are delivered into the local registry, and the node's
``InProcTransport`` is replaced so outbound remote sends go over TCP
while local names stay in-process.
"""

from __future__ import annotations

import hashlib
import hmac
import logging
import os
import pickle
import socket
import struct
import threading
from collections import deque
from typing import Any, Dict, Optional, Tuple

from ra_tpu import faults
from ra_tpu.protocol import ServerId

logger = logging.getLogger("ra_tpu")

_LEN = struct.Struct("<I")
MAX_FRAME = 64 * 1024 * 1024
_MAC_LEN = 16  # truncated HMAC-SHA256 prefix on every frame

# restricted wire deserialization: see ra_tpu.utils.wire (inbound
# frames resolve classes through an allowlist — a cookie holder cannot
# smuggle gadget chains). Re-exported here for discoverability.
from ra_tpu.utils.wire import (  # noqa: F401 (re-export)
    register_wire_type,
    unregister_wire_type,
    wire_loads as _wire_loads,
)


class _Peer:
    def __init__(self, addr: Tuple[str, int], outbox_cap: int):
        self.addr = addr
        # elements are (wire_bytes, frame_count): wire_bytes is already
        # length-prefixed, so the writer joins and sends without any
        # per-frame work; a natively sealed batch rides as ONE element
        # carrying its frame count for exact drop accounting
        self.outbox: deque = deque()
        self.cap = outbox_cap
        self.cv = threading.Condition()
        self.sock: Optional[socket.socket] = None
        self.thread: Optional[threading.Thread] = None
        self.closed = False


class TcpTransport:
    """Duck-type compatible with InProcTransport (send / node_alive /
    proc_alive / blocked set for fault injection).

    The ``blocked`` set holds DIRECTED ``(from, to)`` node pairs checked
    on the sender's side only, so the nemesis plane's one-way partitions
    (``testing.partition_oneway`` / the soak's ``oneway`` dimension) work
    identically over TCP: arming ``(a, b)`` on a's transport drops a's
    sends to b while b's sends to a still flow — the stale-leader
    scenario (acks lost, AppendEntries delivered) needs exactly that
    asymmetry. A symmetric partition arms both directions, each on its
    own side's transport."""

    def __init__(
        self,
        node_name: str,
        deliver,  # fn(to_sid, msg, from_sid) -> bool
        bind: Optional[Tuple[str, int]] = None,
        outbox_cap: int = 10_000,
        cookie: Optional[str] = None,
    ):
        host, port = node_name.rsplit(":", 1)
        self.node_name = node_name
        self.deliver = deliver
        self.outbox_cap = outbox_cap
        self._cookie = (
            cookie or os.environ.get("RA_TPU_COOKIE") or "ra_tpu_default_cookie"
        ).encode()
        self.blocked: set = set()
        self.drop_fn = None
        self.dropped = 0
        self._peers: Dict[str, _Peer] = {}
        self._lock = threading.Lock()
        self._closed = False

        bind_addr = bind or (host, int(port))
        self._server = socket.create_server(bind_addr, reuse_port=False)
        self._server.settimeout(0.5)
        self._accept_thread = threading.Thread(
            target=self._accept_loop, name=f"ra-tcp-accept-{node_name}", daemon=True
        )
        self._accept_thread.start()
        # liveness: ping every known peer; a peer is alive while pongs
        # are fresh. With a ``detector`` (ra_tpu.detector.
        # PhiAccrualDetector) attached, pong ARRIVALS feed it and
        # node_alive uses the adaptive phi window instead of the fixed
        # timeout — jittery links widen their window, steady links
        # tighten (the aten role; both backends share this transport,
        # so liveness semantics stay uniform)
        self.ping_interval_s = 0.2
        self.pong_timeout_s = 1.0
        self.detector = None
        self._last_pong: Dict[str, float] = {}
        # set by the owning node: called with a ServerId when a remote
        # peer announces one of its procs died
        self.on_proc_down_cb = None
        # management plane (reference: rpc:call start/restart/delete on
        # remote nodes, src/ra_server_sup_sup.erl:33-50): the owning
        # node sets on_mgmt_cb(op, kwargs) -> result; mgmt_call() is the
        # client side
        self.on_mgmt_cb = None
        self._mgmt_futs: Dict[int, Tuple[threading.Event, dict]] = {}
        self._mgmt_seq = 0
        self._mgmt_lock = threading.Lock()
        self._ping_thread = threading.Thread(
            target=self._ping_loop, name=f"ra-tcp-ping-{node_name}", daemon=True
        )
        self._ping_thread.start()

    # ------------------------------------------------------------------

    def send(self, to: ServerId, msg: Any, from_sid: Optional[ServerId] = None) -> bool:
        node_name = to[1]
        if node_name == self.node_name:
            return self.deliver(to, msg, from_sid)
        if (self.node_name, node_name) in self.blocked or self._closed:
            self.dropped += 1
            return False
        if self.drop_fn is not None and self.drop_fn(to, msg):
            self.dropped += 1
            return False
        try:
            # injected send fault: raise -> reported undeliverable (the
            # caller's resend machinery covers it); latency just delays
            faults.fire("tcp.send", self.node_name)
        except OSError:
            self.dropped += 1
            return False
        peer = self._peer(node_name)
        if peer is None:
            self.dropped += 1
            return False
        from ra_tpu.protocol import sanitize_for_wire

        try:
            frame = self._seal(
                pickle.dumps((to[0], from_sid, sanitize_for_wire(msg)))
            )
        except Exception:  # noqa: BLE001 — unpicklable payload
            self.dropped += 1
            return False
        if len(frame) > MAX_FRAME:
            # the receiver would kill the connection (and every queued
            # frame behind this one); report failure to the caller instead
            self.dropped += 1
            return False
        with peer.cv:
            if len(peer.outbox) >= peer.cap:
                # backpressure: report undeliverable, do not block
                self.dropped += 1
                return False
            peer.outbox.append((_LEN.pack(len(frame)) + frame, 1))
            peer.cv.notify()
        return True

    def send_batch(self, node_name: str, msgs) -> int:
        """Batch send of ``(to_sid, msg, from_sid)`` triples to ONE
        node: every frame is sealed (HMAC) + length-prefixed in a
        single GIL-released native call (ra_tpu.native.seal_frames)
        and enqueued as one outbox element — the egress fan-out's
        native fast path (docs/INTERNALS.md §18). Byte-identical on
        the wire to per-message ``send``. Returns the number of frames
        enqueued (drops counted per message, exactly like ``send``),
        or -1 when the native sealer is unavailable or a tcp failpoint
        is armed — the caller falls back to per-message ``send`` so
        fire/mangle fault semantics stay per frame."""
        from ra_tpu import native as _native

        if (
            node_name == self.node_name
            or self._closed
            or faults.any_armed("tcp.send", "tcp.frame")
            or not _native.entry_points()["egress"]
        ):
            return -1
        if (self.node_name, node_name) in self.blocked:
            self.dropped += len(msgs)
            return 0
        peer = self._peer(node_name)
        if peer is None:
            self.dropped += len(msgs)
            return 0
        from ra_tpu.protocol import sanitize_for_wire

        drop = self.drop_fn
        payloads = []
        for to, msg, frm in msgs:
            if drop is not None and drop(to, msg):
                self.dropped += 1
                continue
            try:
                p = pickle.dumps((to[0], frm, sanitize_for_wire(msg)))
            except Exception:  # noqa: BLE001 — unpicklable payload
                self.dropped += 1
                continue
            if len(p) + _MAC_LEN > MAX_FRAME:
                self.dropped += 1
                continue
            payloads.append(p)
        if not payloads:
            return 0
        blob = _native.seal_frames(payloads, self._cookie, _MAC_LEN)
        if blob is None:
            # the lib vanished between the probe and the call (never in
            # practice); at-most-once transport: count as dropped, the
            # resend machinery covers it
            self.dropped += len(payloads)
            return 0
        with peer.cv:
            if len(peer.outbox) >= peer.cap:
                self.dropped += len(payloads)
                return 0
            peer.outbox.append((blob, len(payloads)))
            peer.cv.notify()
        return len(payloads)

    def node_alive(self, node_name: str) -> bool:
        if node_name == self.node_name:
            return not self._closed
        if (self.node_name, node_name) in self.blocked:
            return False
        peer = self._peers.get(node_name)
        if peer is None or peer.sock is None:
            return False
        import time as _t

        last = self._last_pong.get(node_name)
        if last is None:
            return False
        d = self.detector
        if d is not None:
            return not d.suspect(node_name)
        return (_t.monotonic() - last) < self.pong_timeout_s

    def proc_alive(self, sid: ServerId) -> bool:
        # remote proc liveness is not observable over TCP; approximate
        # with connection liveness (documented contract in transport.py)
        return self.node_alive(sid[1])

    def known_nodes(self):
        return [self.node_name] + list(self._peers.keys())

    def block(self, a: str, b: str) -> None:
        self.blocked.add((a, b))

    def unblock_all(self) -> None:
        self.blocked.clear()

    def close(self) -> None:
        self._closed = True
        try:
            self._server.close()
        except OSError:
            pass
        with self._lock:
            peers = list(self._peers.values())
        for p in peers:
            with p.cv:
                p.closed = True
                p.cv.notify_all()

    # ------------------------------------------------------------------

    def _seal(self, payload: bytes) -> bytes:
        mac = hmac.new(self._cookie, payload, hashlib.sha256).digest()[:_MAC_LEN]
        # injected frame corruption (torn -> truncated, raise -> bit
        # flip): the receiver's MAC check kills the connection, the
        # sender reconnects lazily — the wire-corruption drill
        return faults.mangle("tcp.frame", mac + payload, self.node_name)

    def _open(self, frame: bytes) -> Optional[bytes]:
        if len(frame) < _MAC_LEN:
            return None
        mac, payload = frame[:_MAC_LEN], frame[_MAC_LEN:]
        want = hmac.new(self._cookie, payload, hashlib.sha256).digest()[:_MAC_LEN]
        return payload if hmac.compare_digest(mac, want) else None

    def _peer(self, node_name: str) -> Optional[_Peer]:
        with self._lock:
            if self._closed:
                # close() already swept the peer table: a late send
                # must not spawn a writer that would park (untimed)
                # with nobody left to close it
                return None
            p = self._peers.get(node_name)
            if p is not None:
                return p
            try:
                host, port = node_name.rsplit(":", 1)
                p = _Peer((host, int(port)), self.outbox_cap)
            except ValueError:
                return None
            self._peers[node_name] = p
            p.thread = threading.Thread(
                target=self._writer_loop, args=(p,),
                name=f"ra-tcp-out-{node_name}", daemon=True,
            )
            p.thread.start()
            return p

    def _writer_loop(self, peer: _Peer) -> None:
        while not self._closed and not peer.closed:
            with peer.cv:
                while not peer.outbox and not peer.closed and not self._closed:
                    # event-driven idle: every enqueue notifies the
                    # peer cv and close() marks peer.closed under it —
                    # an idle sender consumes zero CPU
                    # (docs/INTERNALS.md §16)
                    peer.cv.wait()
                if peer.closed or self._closed:
                    break
                frames = []
                nf = 0
                while peer.outbox and len(frames) < 512:
                    chunk, n = peer.outbox.popleft()
                    frames.append(chunk)
                    nf += n
            if peer.sock is None:
                try:
                    peer.sock = socket.create_connection(peer.addr, timeout=2)
                    peer.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                except OSError:
                    self.dropped += nf
                    peer.sock = None
                    continue
            try:
                # elements are pre-framed at enqueue: the writer is a
                # pure join + sendall, no per-frame length packing
                peer.sock.sendall(b"".join(frames))
            except OSError:
                self.dropped += nf
                try:
                    peer.sock.close()
                except OSError:
                    pass
                peer.sock = None  # reconnect lazily on next batch

    def _ping_loop(self) -> None:
        import time as _t

        while not self._closed:
            with self._lock:
                peers = list(self._peers.keys())
            for name in peers:
                self._enqueue_control(name, "__ping__")
            _t.sleep(self.ping_interval_s)

    def _enqueue_control(self, node_name: str, kind: str, payload=None) -> bool:
        peer = self._peer(node_name)
        if peer is None:
            return False  # unaddressable node name
        frame = self._seal(pickle.dumps((kind, self.node_name, payload)))
        with peer.cv:
            if len(peer.outbox) >= peer.cap:
                return False
            peer.outbox.append((_LEN.pack(len(frame)) + frame, 1))
            peer.cv.notify()
        return True

    def mgmt_call(self, node_name: str, op: str, kwargs: dict, timeout: float = 10.0):
        """Synchronous management RPC against a remote node (start /
        restart / stop / delete server, overview). Raises on timeout or
        remote error."""
        with self._mgmt_lock:
            self._mgmt_seq += 1
            corr = self._mgmt_seq
            ev, slot = threading.Event(), {}
            self._mgmt_futs[corr] = (ev, slot)
        try:
            if not self._enqueue_control(node_name, "__mgmt__", (corr, op, kwargs)):
                raise RuntimeError(
                    f"mgmt {op}: node {node_name!r} unaddressable or outbox full"
                )
            if not ev.wait(timeout):
                raise TimeoutError(f"mgmt {op} on {node_name} timed out")
        finally:
            with self._mgmt_lock:
                self._mgmt_futs.pop(corr, None)
        status, value = slot["r"]
        if status != "ok":
            raise RuntimeError(f"mgmt {op} on {node_name} failed: {value}")
        return value

    def broadcast_proc_down(self, sid: ServerId) -> None:
        """Tell every connected peer that a local server proc died (the
        TCP stand-in for remote process monitors)."""
        with self._lock:
            peers = list(self._peers.keys())
        for name in peers:
            self._enqueue_control(name, "__proc_down__", sid)

    def _accept_loop(self) -> None:
        while not self._closed:
            try:
                conn, _addr = self._server.accept()
            except socket.timeout:
                continue
            except OSError:
                return
            threading.Thread(
                target=self._reader_loop, args=(conn,),
                name="ra-tcp-in", daemon=True,
            ).start()

    def _reader_loop(self, conn: socket.socket) -> None:
        conn.settimeout(None)
        buf = b""
        try:
            while not self._closed:
                data = conn.recv(1 << 16)
                if not data:
                    return
                buf += data
                while len(buf) >= _LEN.size:
                    (ln,) = _LEN.unpack_from(buf)
                    if ln > MAX_FRAME:
                        return  # corrupt/hostile stream: drop connection
                    if len(buf) < _LEN.size + ln:
                        break
                    frame = buf[_LEN.size : _LEN.size + ln]
                    buf = buf[_LEN.size + ln :]
                    payload = self._open(frame)
                    if payload is None:
                        return  # unauthenticated frame: drop connection
                    try:
                        to_name, from_sid, msg = _wire_loads(payload)
                    except Exception:  # noqa: BLE001
                        # with the wire allowlist this is the primary
                        # failure mode for LEGITIMATE traffic carrying an
                        # unregistered payload type — never drop silently
                        # (the peer would reconnect and loop forever)
                        logger.exception(
                            "tcp %s: dropping connection on frame decode "
                            "failure (unregistered wire type? see "
                            "ra_tpu.utils.wire.register_wire_type)",
                            self.node_name,
                        )
                        return
                    if to_name == "__ping__":
                        self._enqueue_control(from_sid, "__pong__")
                        continue
                    if to_name == "__pong__":
                        import time as _t

                        self._last_pong[from_sid] = _t.monotonic()
                        d = self.detector
                        if d is not None:
                            d.heartbeat(from_sid)
                        continue
                    if to_name == "__mgmt__":
                        corr, op, kwargs = msg
                        cb = self.on_mgmt_cb

                        # off the receive thread: start/restart do WAL
                        # recovery + disk I/O, which must not stall the
                        # peer's Raft traffic on this connection
                        def run_mgmt(corr=corr, op=op, kwargs=kwargs, frm=from_sid):
                            try:
                                r = (
                                    ("ok", cb(op, kwargs))
                                    if cb is not None
                                    else ("error", "management not supported")
                                )
                            except Exception as e:  # noqa: BLE001
                                r = ("error", repr(e))
                            self._enqueue_control(frm, "__mgmt_reply__", (corr, r))

                        threading.Thread(
                            target=run_mgmt, name="ra-tcp-mgmt", daemon=True
                        ).start()
                        continue
                    if to_name == "__mgmt_reply__":
                        corr, r = msg
                        with self._mgmt_lock:
                            fut = self._mgmt_futs.get(corr)
                        if fut is not None:
                            fut[1]["r"] = r
                            fut[0].set()
                        continue
                    if to_name == "__proc_down__":
                        cb = self.on_proc_down_cb
                        if cb is not None and msg is not None:
                            try:
                                cb(tuple(msg))
                            except Exception:  # noqa: BLE001
                                pass
                        continue
                    self.deliver((to_name, self.node_name), msg, from_sid)
        except OSError:
            return
        finally:
            try:
                conn.close()
            except OSError:
                pass
