"""The clock seam: every behavioral time read in the runtime goes
through an injectable clock object.

``WallClock`` (the process-wide ``WALL`` default) is a zero-overhead
facade over the ``time`` module — production behavior is unchanged.
The simulation plane (``ra_tpu/sim``) injects a ``VirtualClock`` whose
``monotonic()`` is advanced by the deterministic event loop, which is
what lets one seed fully determine an execution: election windows,
check-quorum windows, tick cadences and TTL deadlines all read THIS
seam instead of ``time.monotonic()``.

Contract (docs/INTERNALS.md §19):

- ``monotonic()``/``monotonic_ns()`` — never goes backwards; the basis
  for every deadline, window and timer in the runtime.
- ``time()`` — wall-clock epoch seconds; feeds ``Tick.now_ms`` and
  machine ``system_time`` uses. Virtual clocks derive it from the same
  advancing counter so it is equally deterministic.
- ``sleep()`` — only ever called from real threads; a virtual clock
  must refuse it (nothing in a simulation may block), which doubles as
  an assertion that no thread-based code path runs under the sim.

Instrumentation-only stamps (latency histogram deltas in
``coordinator.py``/``server.py`` hot paths) intentionally stay on
``time.monotonic_ns`` where noted: they measure real elapsed host time
and are meaningless under simulation, which never runs those paths.
"""

from __future__ import annotations

import time


class WallClock:
    """The real clock: thin wrappers so the seam costs one attribute
    lookup on hot paths that already paid a method call."""

    __slots__ = ()

    def monotonic(self) -> float:
        return time.monotonic()

    def monotonic_ns(self) -> int:
        return time.monotonic_ns()

    def time(self) -> float:
        return time.time()

    def sleep(self, seconds: float) -> None:
        time.sleep(seconds)


WALL = WallClock()
