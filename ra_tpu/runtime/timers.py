"""Timer service: one thread, a heap of (deadline, ref, callback).

Backs election timeouts (randomized tiers), server ticks and machine
timers — the roles gen_statem timeouts play in the reference
(reference: election_timeout_action tiers src/ra_server_proc.erl:
1931-1950, tick timer :1954).
"""

from __future__ import annotations

import heapq
import itertools
import random
import logging
import threading
from typing import Any, Callable, Dict, Optional

from ra_tpu.runtime.clock import WALL


logger = logging.getLogger("ra_tpu")



class TimerService:
    def __init__(self, clock=None) -> None:
        self._clock = clock or WALL
        self._heap: list = []
        self._cancelled: set = set()
        self._live: set = set()
        self._cv = threading.Condition()
        self._closed = False
        self._refs = itertools.count(1)
        self._thread = threading.Thread(target=self._run, name="ra-timers", daemon=True)
        self._thread.start()

    def after(self, delay_s: float, cb: Callable[[], None]) -> int:
        ref = next(self._refs)
        with self._cv:
            heapq.heappush(self._heap, (self._clock.monotonic() + delay_s, ref, cb))
            self._live.add(ref)
            self._cv.notify()
        return ref

    def cancel(self, ref: Optional[int]) -> None:
        if ref is None:
            return
        with self._cv:
            # only pending timers can be cancelled; marking fired refs
            # would leak them in the set forever
            if ref in self._live:
                self._cancelled.add(ref)

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._heap and not self._closed:
                    self._cv.wait(timeout=0.5)
                if self._closed:
                    return
                deadline, ref, cb = self._heap[0]
                now = self._clock.monotonic()
                if deadline > now:
                    self._cv.wait(timeout=min(deadline - now, 0.5))
                    continue
                heapq.heappop(self._heap)
                self._live.discard(ref)
                if ref in self._cancelled:
                    self._cancelled.discard(ref)
                    continue
            # NOTE: a cancel() arriving after this point cannot stop the
            # callback; consumers treat late fires as spurious (e.g. an
            # ElectionTimeout with a live leader aborts harmlessly)
            try:
                cb()
            except Exception:  # noqa: BLE001
                logger.exception("timer callback crashed")

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        self._thread.join(timeout=2)


def randomized_election_timeout(base_s: float, rng: Optional[random.Random] = None) -> float:
    """Randomized timeout so colliding candidates de-synchronize. An
    explicit ``rng`` makes the draw seed-deterministic (sim plane)."""
    return base_s * (1.0 + (rng or random).random())
