"""The pure consensus core — one Raft server's transition function.

This is the framework's equivalent of the reference's ``ra_server``
(reference: ``src/ra_server.erl:17-68`` — one ``handle_<role>`` per role,
each returning ``(NextRole, State', Effects)``). The core performs **no
I/O and no messaging**: it reads/writes its log only through the
``LogApi`` facade, persists term/vote through ``MetaApi``, and returns
``Effect`` values for the runtime to realise. That makes it:

- exhaustively testable message-by-message (tests/test_server_*.py),
- the *oracle* for the vectorized TPU kernels in ``ra_tpu.ops.consensus``
  (both implement the decision math in ``ra_tpu.ops.decisions``).

Roles: follower, pre_vote, candidate, leader, receive_snapshot,
await_condition (reference: src/ra_server_proc.erl:20-32).

Implementation style note: unlike the Erlang original this core mutates a
``Server`` object in place — the purity that matters (no I/O, no time, no
randomness, effects-as-data) is kept, while Python object churn is not,
because the batch coordinator reads its state out as arrays anyway.
"""

from __future__ import annotations

import dataclasses
import time
import zlib
from typing import Any, Callable, Dict, List, Optional, Sequence, Set, Tuple

from ra_tpu import counters as ra_counters
from ra_tpu.effects import (
    Aux,
    BgWork,
    Checkpoint,
    Demonitor,
    Effect,
    EffectList,
    LogRead,
    ModCall,
    Monitor,
    NextEvent,
    Notify,
    RecordLeader,
    ReleaseCursor,
    Reply,
    SendMsg,
    SendRpc,
    SendSnapshot,
    SendVoteRequests,
    StartSnapshotRetryTimer,
    StateEnter,
    StopServer as StopEffect,
    Timer,
    TryAppend,
)
from ra_tpu.log.api import LogApi
from ra_tpu.log.meta import MetaApi
from ra_tpu.machine import Machine, normalize_apply_result
from ra_tpu.ops import decisions as dec
from ra_tpu.protocol import (
    AppendEntriesReply,
    AppendEntriesRpc,
    CHUNK_INIT,
    CHUNK_LAST,
    CHUNK_NEXT,
    CHUNK_PRE,
    Command,
    DownEvent,
    ElectionTimeout,
    Entry,
    FromPeer,
    HeartbeatReply,
    HeartbeatRpc,
    InfoReply,
    InfoRpc,
    InstallSnapshotAck,
    InstallSnapshotResult,
    InstallSnapshotRpc,
    LogEvent,
    NOOP,
    REJECT_NOSPACE,
    REJECT_OVERLOADED,
    NodeEvent,
    PreVoteResult,
    PreVoteRpc,
    RA_CLUSTER_CHANGE,
    RA_JOIN,
    RA_LEAVE,
    RequestVoteResult,
    RequestVoteRpc,
    ServerId,
    SnapshotMeta,
    Tick,
    USR,
)

PROTO_VERSION = 1

FOLLOWER = "follower"
PRE_VOTE = "pre_vote"
CANDIDATE = "candidate"
LEADER = "leader"
RECEIVE_SNAPSHOT = "receive_snapshot"
AWAIT_CONDITION = "await_condition"


def status_kind(status: Any) -> str:
    """Peer status discriminator: plain statuses are strings; the
    snapshot-transfer statuses carry an attempt count as
    ("sending_snapshot", n) / ("snapshot_backoff", n) (reference peer
    status values, src/ra_server.erl:73-112)."""
    return status[0] if isinstance(status, tuple) else status


@dataclasses.dataclass
class PeerState:
    next_index: int = 1
    match_index: int = 0
    commit_index_sent: int = 0
    query_index: int = 0
    # "normal" | "suspended" | "disconnected"
    # | ("sending_snapshot", attempts) | ("snapshot_backoff", attempts)
    status: Any = "normal"
    # "voter" | ("nonvoter", target_index) — nonvoters replicate but do
    # not count for quorum/elections until promoted (reference:
    # maybe_promote_peer src/ra_server.erl:3977-3995)
    voter_status: Any = "voter"
    # highest machine version the peer supports (None = unknown; learned
    # from info/pre-vote rpcs) — gates upgrade strategies
    machine_version: Optional[int] = None

    def is_voter(self) -> bool:
        return self.voter_status == "voter"


# re-exported for existing importers; the class lives with the wire
# protocol records now (sent leader->target over transport)
from ra_tpu.protocol import TimeoutNow  # noqa: E402,F401


@dataclasses.dataclass
class ConditionTimeout:
    """Fired by the runtime when the await_condition hold expires —
    distinct from ElectionTimeout, which starts a pre-vote even while a
    condition holds (reference: await_condition_timeout vs
    election_timeout, src/ra_server.erl:1922-1945).

    ``generation`` guards against stale delivery: a timeout enqueued for
    hold A must not expire a newly-entered hold B (None = wildcard, for
    message-level tests)."""

    generation: Optional[int] = None


@dataclasses.dataclass
class Condition:
    """An await_condition hold (reference condition map,
    src/ra_server.erl:90-93): ``predicate(server, msg)`` decides when a
    message releases the hold; the server then transitions to
    ``transition_to`` and re-injects the message. If the hold expires
    first (ConditionTimeout), the server transitions to
    ``timeout_transition_to`` and issues ``timeout_effects`` (e.g. the
    catch-up condition repeats its failure reply)."""

    predicate: Callable[["Server", Any], bool]
    timeout_effects: Tuple[Effect, ...] = ()
    transition_to: str = FOLLOWER
    timeout_transition_to: str = FOLLOWER
    # None -> the runtime's default await_condition timeout
    timeout_duration_ms: Optional[int] = None


def _follower_catchup_cond(reason: str) -> Callable[["Server", Any], bool]:
    """Release predicate for the follower catch-up hold (reference:
    follower_catchup_cond, src/ra_server.erl:2196-2231): a same/higher
    term AER whose prev now fits releases; a term-mismatch AER releases
    only when the original hold was for a MISSING entry (the mismatch
    needs its own rewind); an install-snapshot at/above our next index
    releases into the snapshot path."""

    def pred(srv: "Server", m: Any) -> bool:
        if isinstance(m, AppendEntriesRpc) and m.term >= srv.current_term:
            snap = srv.log.snapshot_index_term()
            local = srv.log.fetch_term(m.prev_log_index)
            code = dec.aer_decision(
                srv.current_term, m.term, m.prev_log_index, m.prev_log_term,
                -1 if local is None else local, snap[0] if snap else 0,
            )
            if code == dec.AER_OK:
                return True
            if local is not None and local != m.prev_log_term:
                return reason == "missing"
            return False
        if isinstance(m, InstallSnapshotRpc) and m.term >= srv.current_term:
            return m.meta.index >= srv.log.next_index()
        return False

    return pred


@dataclasses.dataclass
class ServerConfig:
    server_id: ServerId
    uid: str
    cluster_name: str
    machine: Machine
    initial_members: Tuple[ServerId, ...] = ()
    max_pipeline_count: int = 4096
    max_aer_batch_size: int = 128
    # client admission window: appended-but-unapplied backlog above
    # which new client commands are rejected ("reject", "overloaded")
    # or, when ack-free, dropped — bounded queueing instead of silent
    # unbounded latency (the client analog of max_pipeline_count)
    max_command_backlog: int = 4096
    counters_enabled: bool = True
    # pre_vote on by default; candidates skip straight to request_vote
    # when False.
    pre_vote: bool = True
    # check-quorum window (seconds; 0 disables): a leader that has not
    # HEARD from a quorum of voters within the window steps down and
    # answers its pending clients "maybe" instead of reigning uselessly.
    # This is the one-way-partition guard: a leader whose AppendEntries
    # still flow OUT keeps resetting follower election timers, so no
    # follower ever stands — only the leader itself can notice that no
    # ack ever comes BACK (Raft §6's check-quorum / the reference's
    # leader contact monitoring). Node construction defaults it from
    # the node's timing config (runtime/node.py).
    check_quorum_window_s: float = 0.0
    machine_config: Optional[Dict[str, Any]] = None
    # "all" (default): bump the effective machine version only once every
    # member supports it; "quorum": once a quorum does (reference:
    # src/ra_server.erl:223-233)
    machine_upgrade_strategy: str = "all"
    # injectable clock (ra_tpu/runtime/clock.py): every behavioral time
    # read (check-quorum windows, peer-contact stamps) goes through it;
    # None = the real wall clock. The sim plane injects a VirtualClock.
    clock: Optional[Any] = None
    # clock-bound leader lease (docs/INTERNALS.md §20). OFF by default:
    # leader stickiness changes election behavior (a follower with
    # recent leader contact disregards (pre-)votes), which existing
    # churn tests trigger at will; kv_harness/bench/sim opt in
    # explicitly. Requires pre_vote — stickiness on the pre-vote round
    # is what makes the quorum-intersection safety argument hold for
    # ordinary (non-forced) elections.
    lease: bool = False
    # the follower promise window: minimum leader silence before a
    # follower will help elect a replacement. Must equal the BASE of
    # the randomized election timer (runtime/timers.py randomizes
    # upward only), so the promise is never shorter than the lease
    # math assumes.
    election_timeout_s: float = 0.15
    lease_safety_factor: float = 0.8
    lease_drift_epsilon_s: float = 0.002
    # node-scope storage-pressure plane (ra_tpu.pressure.StoragePressure
    # or None): when blocked() — WAL space-degraded or hard watermark —
    # client commands reject ("reject", "nospace") through the same
    # gate-waiter path as overload, and snapshot-chunk acks grant 0
    # credits so inbound transfers pause (docs/INTERNALS.md §21).
    pressure: Optional[Any] = None
    # receiver-paced snapshot chunk credit window granted per ack while
    # storage is healthy (SystemConfig.snapshot_credit_window)
    snapshot_credit_window: int = 4


class Server:
    """One Raft group member. See module docstring for the contract."""

    def __init__(self, cfg: ServerConfig, log: LogApi, meta: MetaApi):
        self.cfg = cfg
        self.id: ServerId = cfg.server_id
        self.log = log
        self.meta = meta
        from ra_tpu.runtime.clock import WALL

        self._clock = cfg.clock or WALL
        self.machine = cfg.machine
        self.role: str = FOLLOWER
        self.leader_id: Optional[ServerId] = None
        # max index the current leader has confirmed holding (via its
        # AERs); deferred written acks are anchored to it
        self._leader_cover = 0

        self.current_term: int = meta.fetch(cfg.uid, "current_term", 0)
        self.voted_for: Optional[ServerId] = meta.fetch(cfg.uid, "voted_for", None)
        self.commit_index: int = 0
        self.last_applied: int = meta.fetch(cfg.uid, "last_applied", 0)
        # admission-window release gate (docs/INTERNALS.md §16): a
        # rejected client parks on a waiter carried in the reject reply
        # and is woken the moment apply progress frees window room —
        # the actor-backend mirror of the batch coordinator's _adm_gate
        # (clients are process-local; the gate never crosses the wire)
        from ra_tpu.rings import WaitGate

        self._adm_gate = WaitGate()

        # machine versioning (reference: src/ra_server.erl:223-233)
        self.machine_version: int = self.machine.version()
        self.effective_machine_version: int = 0

        # cluster membership
        self.cluster: Dict[ServerId, PeerState] = {}
        self.cluster_index_term: Tuple[int, int] = (0, 0)
        self.previous_cluster: Optional[Tuple[int, int, Dict[ServerId, PeerState]]] = None
        self.cluster_change_permitted: bool = False
        self.pending_cluster_change: Optional[Tuple[Any, Any]] = None

        # election state
        self.votes: Set[ServerId] = set()
        self.pre_votes: Set[ServerId] = set()
        self.pre_vote_token: int = 0
        self._token_counter: int = 0
        # check-quorum bookkeeping: monotonic stamp of the last message
        # RECEIVED from each peer while we lead (any inbound message is
        # contact — AER replies, heartbeat replies, snapshot results,
        # votes); evaluated against cfg.check_quorum_window_s per tick
        self._peer_contact: Dict[ServerId, float] = {}

        # clock-bound leader lease (§20). All lease state lives on the
        # core (not the proc shell) so the sim plane, which drives
        # Server directly, exercises every path.
        if cfg.lease and not cfg.pre_vote:
            raise ValueError(
                "lease requires pre_vote: leader stickiness rides the "
                "pre-vote round (docs/INTERNALS.md §20)"
            )
        from ra_tpu.lease import LeaseConfig, LeaseTracker

        self._lease = LeaseTracker(LeaseConfig(
            enabled=cfg.lease,
            election_timeout_s=cfg.election_timeout_s,
            safety_factor=cfg.lease_safety_factor,
            drift_epsilon_s=cfg.lease_drift_epsilon_s,
        ))
        self._lease_renew_t = 0.0  # last demand-driven renewal round
        # follower side: monotonic stamp of last contact from a live
        # leader — the stickiness promise is measured against it
        self._leader_contact = 0.0
        # TimeoutNow/force_shrink candidacies send force=True votes that
        # bypass stickiness (the old leader revoked its lease first)
        self._forced_candidacy = False
        # lease-admitted reads waiting for applied >= read_index:
        # (read_index, from_ref, fn) — drained in _apply_to, answered
        # "redirect" if leadership is lost first (see _become)
        self.pending_lease_reads: List[Tuple[int, Any, Callable]] = []
        # True once commit_index provably includes an entry of the
        # current term (Raft read-index precondition; set by
        # _evaluate_quorum's current-term gate)
        self._term_commit_ok = False
        # staleness-bounded local reads: newest not-yet-applied
        # (commit_index, leader wall ts) anchor + the applied freshness
        # floor (read_staleness_s)
        self._fresh_anchor: Tuple[int, float] = (0, 0.0)
        self._fresh_ts = 0.0

        # consistent-query state (leader side)
        self.query_index: int = 0
        self.pending_queries: List[Tuple[int, Any, Callable]] = []
        # idx -> client reply handle for await_consensus commands. Reply
        # handles are process-ephemeral and never persisted (entries are
        # stripped of from_ref on durable write), so the leader keeps
        # them here until the entry applies or leadership is lost.
        self.pending_replies: Dict[int, Any] = {}

        # receive_snapshot state
        self._snap_accept: Optional[Dict[str, Any]] = None

        self.condition: Optional[Condition] = None
        self.condition_generation = 0  # stale-ConditionTimeout guard
        self._held_from_leader = False  # hold entered from leadership
        # a release cursor stashed behind unmet conditions:
        # (index, machine_state, conditions) — re-evaluated on written
        # events, AER acks, and snapshot-sender exits (reference:
        # pending_release_cursor, src/ra_server.erl:2455-2514)
        self.pending_release_cursor: Optional[Tuple[int, Any, Tuple[Any, ...]]] = None

        self.counter = (
            ra_counters.new((cfg.cluster_name, cfg.server_id)) if cfg.counters_enabled else None
        )
        # commit-latency stage histograms (per NODE, shared with any
        # batch coordinator on it) + flight recorder; one in-flight
        # sample per server: [idx, t_submit, t_append, t_durable,
        # t_commit, t_apply] in monotonic ns (obs.COMMIT_STAGES)
        from ra_tpu import obs as _obs

        self._commit_h = _obs.commit_hists(self.id[1])
        self._obs_rec = _obs.flight_recorder()
        self._lat: Optional[list] = None

        # machine state: from snapshot if present, else init
        snap = log.read_snapshot()
        if snap is not None:
            meta_s, mac_state = snap
            self.machine_state = mac_state
            self.effective_machine_version = meta_s.machine_version
            self._set_cluster(
                {sid: PeerState() for sid in meta_s.cluster}, meta_s.index, meta_s.term
            )
            self.commit_index = meta_s.index
            self.last_applied = max(self.last_applied, meta_s.index)
        else:
            self.machine_state = self.machine.init(
                dict(cfg.machine_config or {}, name=cfg.cluster_name)
            )
            members = cfg.initial_members or (cfg.server_id,)
            self._set_cluster({sid: PeerState() for sid in members}, 0, 0)

    # ------------------------------------------------------------------
    # helpers

    def _c(self, field: str, n: int = 1) -> None:
        if self.counter is not None:
            self.counter.incr(field, n)

    def _g(self, field: str, v: int) -> None:
        if self.counter is not None:
            self.counter.put(field, v)

    def _set_cluster(self, cluster: Dict[ServerId, PeerState], idx: int, term: int) -> None:
        if self.role == LEADER and self._lease.cfg.enabled:
            # the quorum-intersection safety argument holds only for
            # the voter set the ack bases were collected against: ANY
            # membership adoption drops the lease (the next read's
            # renewal round rebuilds it against the new set)
            if self._lease.revoke():
                self._c("read_lease_revocations")
        self.cluster = cluster
        self.cluster_index_term = (idx, term)
        if self.id not in self.cluster:
            # we may have been removed; keep a self entry for
            # bookkeeping — as a NON-voter, so quorum math reflects the
            # new config (a removed leader must not count itself) and a
            # removed member never stands for election
            self.cluster = dict(cluster)
            self.cluster[self.id] = PeerState(voter_status=None)

    def members(self) -> List[ServerId]:
        return list(self.cluster.keys())

    def peers(self) -> Dict[ServerId, PeerState]:
        return {sid: p for sid, p in self.cluster.items() if sid != self.id}

    def voters(self) -> List[ServerId]:
        return [sid for sid, p in self.cluster.items() if p.is_voter()]

    def required_quorum(self) -> int:
        return len(self.voters()) // 2 + 1

    def is_voter_self(self) -> bool:
        p = self.cluster.get(self.id)
        return p is not None and p.is_voter()

    def _new_token(self) -> int:
        self._token_counter += 1
        return self._token_counter

    def _persist_term_vote(self) -> None:
        self.meta.store_sync(self.cfg.uid, "current_term", self.current_term)
        self.meta.store_sync(self.cfg.uid, "voted_for", self.voted_for)
        self._g("term", self.current_term)

    def _update_term(self, term: int, voted_for: Optional[ServerId] = None) -> None:
        if term > self.current_term:
            self.current_term = term
            self.voted_for = voted_for
            self._persist_term_vote()

    # role -> ra_tpu.health role code (AWAIT_CONDITION/RECEIVE_SNAPSHOT
    # report as "held": not a device role, but a health-relevant fact)
    _HEALTH_ROLE = {FOLLOWER: 0, PRE_VOTE: 1, CANDIDATE: 2, LEADER: 3}

    def health_row(self) -> Tuple:
        """One row of the node's per-group health scan (the actor-
        backend mirror of the coordinator's vectorized device fetch;
        ra_tpu/health.py). Read by the detector thread between actor
        turns: plain scalar reads, best-effort like the counters.
        Returns (cluster, role_code, term, applied, commit, last_index,
        match_gap, leader_key)."""
        li, _ = self.log.last_index_term()
        gap = 0
        if self.role == LEADER:
            pm = [
                p.match_index for sid, p in self.cluster.items()
                if sid != self.id and p.is_voter()
            ]
            if pm:
                gap = max(0, li - min(pm))
        leader = self.id if self.role == LEADER else self.leader_id
        key = (
            zlib.crc32(repr(leader).encode()) if leader is not None else None
        )
        return (
            self.cfg.cluster_name, self._HEALTH_ROLE.get(self.role, 4),
            self.current_term, self.last_applied, self.commit_index, li,
            gap, key,
        )

    def overview(self) -> Dict[str, Any]:
        li, lt = self.log.last_index_term()
        return {
            "id": self.id,
            "role": self.role,
            "leader": self.leader_id,
            "current_term": self.current_term,
            "commit_index": self.commit_index,
            "last_applied": self.last_applied,
            "last_index": li,
            "last_term": lt,
            "cluster": {sid: dataclasses.asdict(p) for sid, p in self.cluster.items()},
            "cluster_change_permitted": self.cluster_change_permitted,
            "machine_version": self.machine_version,
            "effective_machine_version": self.effective_machine_version,
            "machine": self.machine.overview(self.machine_state),
            "log": self.log.overview(),
        }

    # ------------------------------------------------------------------
    # recovery

    def recover(self) -> None:
        """Replay the log up to the persisted last_applied, discarding
        effects (reference: ra_server:recover/1 src/ra_server.erl:469-528;
        effects are not re-issued after restart, INTERNALS.md:91-106).
        An orderly-shutdown recovery checkpoint, when present and valid,
        replaces the replay prefix (reference:
        maybe_recover_from_recovery_checkpoint :2769-2840)."""
        snap = self.log.snapshot_index_term()
        snap_idx = snap[0] if snap else 0
        self._scan_cluster_changes(snap_idx + 1)
        last_idx = self.log.last_index_term()[0]
        target = min(max(self.commit_index, self.last_applied), last_idx)
        # machine_state was recovered from the snapshot (or init): replay
        # starts right above it regardless of the persisted watermark
        self.last_applied = snap_idx
        rc = self.log.read_recovery_checkpoint()
        if rc is not None:
            meta, state = rc
            # single-use: a stale capture must never be replayed after a
            # non-orderly restart, so consume it now regardless
            self.log.discard_recovery_checkpoint()
            # the orderly-shutdown capture itself proves entries up to
            # meta.index were applied (hence committed) — it may be
            # ahead of the async-persisted last_applied watermark
            if (
                snap_idx <= meta.index <= last_idx
                and self.log.fetch_term(meta.index) == meta.term
            ):
                self.machine_state = state
                self.effective_machine_version = meta.machine_version
                self.last_applied = meta.index
                target = max(target, meta.index)
                self._c("recovery_checkpoint_used")
        self.commit_index = max(target, snap_idx)
        self._apply_to(self.commit_index, discard_effects=True)

    def _scan_cluster_changes(self, from_idx: int) -> None:
        last_idx, _ = self.log.last_index_term()

        def scan(entry: Entry, acc: None) -> None:
            cmd = entry.cmd
            if isinstance(cmd, Command) and cmd.kind in (RA_JOIN, RA_LEAVE, RA_CLUSTER_CHANGE):
                self._apply_cluster_entry(entry)
            return acc

        if from_idx <= last_idx:
            try:
                self.log.fold(from_idx, last_idx, scan, None)
            except KeyError:
                pass  # sparse/compacted region: snapshot cluster stands

    # ------------------------------------------------------------------
    # dispatch

    def handle(self, msg: Any, from_peer: Optional[ServerId] = None) -> EffectList:
        if isinstance(msg, FromPeer):
            return self.handle(msg.msg, from_peer=msg.peer)
        if isinstance(msg, tuple) and msg and msg[0] == "force_shrink":
            return self._force_shrink(msg[1] if len(msg) > 1 else None)
        if (
            isinstance(msg, LogEvent)
            and isinstance(msg.evt, tuple)
            and msg.evt
            and msg.evt[0] == "wal_down"
            and self.role != AWAIT_CONDITION
        ):
            return self._on_wal_down()
        if isinstance(msg, InfoRpc):
            # capability probe: answer from any role
            if from_peer is None:
                return []
            return [SendRpc(from_peer, InfoReply(self.current_term, self.machine.version()))]
        if isinstance(msg, InfoReply):
            effects: EffectList = []
            peer = self.cluster.get(from_peer)
            if self.role == LEADER and peer is not None:
                peer.machine_version = msg.machine_version
                self._maybe_upgrade_machine(effects)
                self._pipeline(effects)
            return effects
        handler = {
            FOLLOWER: self._handle_follower,
            PRE_VOTE: self._handle_pre_vote,
            CANDIDATE: self._handle_candidate,
            LEADER: self._handle_leader,
            RECEIVE_SNAPSHOT: self._handle_receive_snapshot,
            AWAIT_CONDITION: self._handle_await_condition,
        }[self.role]
        effects = handler(msg, from_peer)
        self._g("commit_index", self.commit_index)
        self._g("last_applied", self.last_applied)
        return effects

    def _force_shrink(self, from_ref: Any) -> EffectList:
        """Escape hatch: rewrite the cluster to just this member and
        elect (used when a majority is permanently lost — reference:
        force_shrink_members_to_current_member,
        src/ra_server_proc.erl:270-272). DANGEROUS: discards the other
        members' votes; only for operator-driven disaster recovery."""
        effects: EffectList = []
        idx = self.log.next_index()
        cmd = Command(kind=RA_CLUSTER_CHANGE, data=("replace", ((self.id, "voter"),)))
        self._set_cluster({self.id: PeerState()}, idx, self.current_term)
        self.log.append(Entry(index=idx, term=self.current_term, cmd=cmd))
        self.cluster_change_permitted = False
        # disaster recovery must not stall on stickiness windows
        self._forced_candidacy = True
        self._call_for_election(effects)
        if from_ref is not None:
            effects.append(Reply(from_ref, ("ok", None)))
        return effects

    # ------------------------------------------------------------------
    # role transitions

    def _become(self, role: str, effects: EffectList) -> None:
        prev = self.role
        self.role = role
        if prev != role:
            self._obs_rec.record(
                "role_change", node=self.id[1], group=self.id[0],
                term=self.current_term, detail=f"{prev}->{role}",
            )
        if role == FOLLOWER:
            self.votes = set()
            self.pre_votes = set()
        if prev == LEADER and role == AWAIT_CONDITION:
            # a leader's hold (transfer / wal_down) may RESUME
            # leadership: replies for commands that still commit are
            # retained until the hold resolves to a real step-down
            self._held_from_leader = True
        if prev == LEADER and role != LEADER:
            # leaving leadership in ANY direction — including a hold
            # that may later resume: a transfer target can win a
            # TimeoutNow election that (by design) bypasses stickiness,
            # so the lease dies NOW, held reads redirect immediately,
            # and in-flight acks must not resurrect the old window
            # (LeaseTracker.revoke clears the stamps too)
            if self._lease.revoke():
                self._c("read_lease_revocations")
                self._obs_rec.record(
                    "lease_lost", node=self.id[1], group=self.id[0],
                    term=self.current_term, detail=f"left leader for {role}",
                )
            self._term_commit_ok = False
            if self.pending_lease_reads:
                lhint = self.leader_id if self.leader_id != self.id else None
                for _ri, ref, _fn in self.pending_lease_reads:
                    effects.append(Reply(ref, ("redirect", lhint)))
                self.pending_lease_reads = []
        if role in (FOLLOWER, LEADER):
            self._forced_candidacy = False
        stepping_down = (prev == LEADER and role not in (LEADER, AWAIT_CONDITION)) or (
            prev == AWAIT_CONDITION
            and role != LEADER
            and getattr(self, "_held_from_leader", False)
        )
        if role == LEADER or stepping_down:
            self._held_from_leader = False
        if stepping_down:
            # stepping down for real: outstanding client replies will
            # never be issued by us — redirect the callers to the new
            # leader (hint may be None) so they retry immediately
            # instead of hanging out their full timeout, and clear
            # snapshot-transfer statuses so a later election does not
            # find peers stranded in sending/backoff with no sender or
            # timer behind them. The command MAY still commit if the
            # entry survives on the new leader, so the verdict is
            # "maybe": an immediate error to plain callers, a retry
            # target only for callers that opted into at-least-once.
            hint = self.leader_id if self.leader_id != self.id else None
            if self.pending_replies:
                self._obs_rec.record(
                    "deposition", node=self.id[1], group=self.id[0],
                    term=self.current_term,
                    detail=f"{len(self.pending_replies)} pending futures "
                           "answered 'maybe'",
                )
            for fut in self.pending_replies.values():
                effects.append(Reply(fut, ("maybe", hint)))
            self.pending_replies = {}
            self.pending_queries = []
            for p in self.cluster.values():
                if status_kind(p.status) in ("sending_snapshot", "snapshot_backoff"):
                    p.status = "normal"
        if prev != role:
            effects.append(StateEnter(role))
            effects.extend(self.machine.state_enter(role, self.machine_state))

    def _become_leader(self, effects: EffectList) -> None:
        self.leader_id = self.id
        last_idx, _ = self.log.last_index_term()
        now = self._clock.monotonic()
        for sid, p in self.cluster.items():
            if sid != self.id:
                p.next_index = last_idx + 1
                p.match_index = 0
                p.commit_index_sent = 0
                p.status = "normal"
                # check-quorum grace: a fresh leader owes every peer a
                # full window before their silence can depose it
                self._peer_contact[sid] = now
        self.cluster_change_permitted = False
        self.pending_cluster_change = None
        self.query_index = 0
        self.pending_queries = []
        for p in self.cluster.values():
            p.query_index = 0
        # fresh leadership starts bare: no lease (earned by the first
        # quorum of acks), no read-index proof until our noop commits
        self._lease.revoke()
        self._lease_renew_t = 0.0
        self._term_commit_ok = False
        self._become(LEADER, effects)
        effects.append(
            RecordLeader(self.cfg.cluster_name, self.id, tuple(self.members()))
        )
        # Append a noop for the new term; its commit re-enables cluster
        # changes and (upgrade strategy permitting) bumps the machine
        # version (reference: post_election_effects src/ra_server.erl:
        # 4028-4064).
        noop = Command(kind=NOOP, machine_version=self._required_machine_version())
        self._append_leader(noop, effects)
        self._pipeline(effects)

    def _become_follower(self, effects: EffectList, leader: Optional[ServerId] = None) -> None:
        if leader is not None and leader != self.leader_id:
            self.leader_id = leader
            effects.append(
                RecordLeader(self.cfg.cluster_name, leader, tuple(self.members()))
            )
        self._become(FOLLOWER, effects)

    # ------------------------------------------------------------------
    # leader

    def _handle_leader(self, msg: Any, from_peer: Optional[ServerId]) -> EffectList:
        effects: EffectList = []
        if from_peer is not None and from_peer in self.cluster:
            # ANY inbound message from a member is check-quorum contact
            self._peer_contact[from_peer] = self._clock.monotonic()
        if isinstance(msg, Command):
            self._c("commands")
            self._append_leader(msg, effects)
            self._pipeline(effects)
            return effects
        if isinstance(msg, list):  # batched commands
            self._c("commands", len(msg))
            for cmd in msg:
                self._append_leader(cmd, effects)
            self._pipeline(effects)
            return effects
        if isinstance(msg, AppendEntriesReply):
            return self._leader_aer_reply(msg, from_peer, effects)
        if isinstance(msg, InstallSnapshotResult):
            if msg.term > self.current_term:
                self._update_term(msg.term)
                self._become_follower(effects, leader=None)
                return effects
            peer = self.cluster.get(from_peer)
            if peer is not None:
                peer.status = "normal"
                peer.match_index = max(peer.match_index, msg.last_index)
                peer.next_index = max(peer.next_index, msg.last_index + 1)
                self._maybe_emit_pending_release_cursor()  # no_snapshot_sends
                # a snapshot can carry a nonvoter past its promotion
                # target just like an AER ack (reference: leader_received_
                # install_snapshot_result_and_promotes_voter)
                self._maybe_promote_peer(from_peer, peer, effects)
                self._evaluate_quorum(effects)
                self._pipeline(effects)
            return effects
        if isinstance(msg, RequestVoteRpc):
            if msg.candidate_id not in self.cluster:
                # a removed (or never-known) member's stale election must
                # not depose a working leader (reference:
                # leader_does_not_abdicate_to_unknown_peer)
                effects.append(
                    SendRpc(from_peer, RequestVoteResult(self.current_term, False))
                )
                return effects
            if msg.term > self.current_term:
                self._update_term(msg.term)
                self._become_follower(effects)
                effects.append(NextEvent(FromPeer(from_peer, msg)))
                return effects
            effects.append(SendRpc(from_peer, RequestVoteResult(self.current_term, False)))
            return effects
        if isinstance(msg, PreVoteRpc):
            # a backing-off peer that starts pre-voting is alive and
            # still behind: re-engage it with the snapshot immediately
            # instead of waiting out the retry backoff (reference:
            # leader_pre_vote_sends_snapshot_to_backoff_peer)
            peer = self.cluster.get(msg.candidate_id)
            if peer is not None and status_kind(peer.status) == "snapshot_backoff":
                effects.append(SendSnapshot(msg.candidate_id,
                                            meta=self.log.snapshot_meta()))
            return self._process_pre_vote(msg, from_peer, effects)
        if isinstance(msg, AppendEntriesRpc):
            if msg.term > self.current_term:
                self._update_term(msg.term)
                self._become_follower(effects, leader=from_peer)
                effects.append(NextEvent(FromPeer(from_peer, msg)))
            else:
                # two leaders in one term must not happen; tell them ours
                effects.append(
                    SendRpc(
                        from_peer,
                        AppendEntriesReply(
                            self.current_term, False,
                            next_index=self.log.next_index(),
                            last_index=self.log.last_index_term()[0],
                            last_term=self.log.last_index_term()[1],
                        ),
                    )
                )
            return effects
        if isinstance(msg, HeartbeatReply):
            peer = self.cluster.get(from_peer)
            if peer is not None and msg.term == self.current_term:
                self._lease_credit(from_peer)
                peer.query_index = max(peer.query_index, msg.query_index)
                self._evaluate_queries(effects)
            elif msg.term > self.current_term:
                self._update_term(msg.term)
                self._become_follower(effects)
            return effects
        if isinstance(msg, LogEvent):
            self.log.handle_event(msg.evt)
            self._maybe_emit_pending_release_cursor()  # ("written", idx)
            self._evaluate_quorum(effects)
            self._pipeline(effects)
            return effects
        if isinstance(msg, Tick):
            return self._leader_tick(msg, effects)
        if isinstance(msg, ElectionTimeout):
            return effects  # leaders ignore election timeouts
        if isinstance(msg, (NodeEvent, DownEvent)):
            return self._leader_node_event(msg, effects)
        if isinstance(msg, TimeoutNow):
            return effects
        # membership / control commands arrive as plain tuples
        if isinstance(msg, tuple) and msg:
            return self._leader_control(msg, effects)
        return effects

    def _append_leader(self, cmd: Command, effects: EffectList,
                       exempt: bool = False) -> None:
        """Append a command to the leader's log, handling membership
        commands and reply-after-append modes (reference:
        append_log_leader src/ra_server.erl:3485-3550). ``exempt``
        bypasses the admission window for internal must-deliver appends
        (fired exactly once with no retry path, e.g. monitor
        down/nodedown events)."""
        if cmd.kind != NOOP and not exempt and not cmd.internal:
            # storage-degraded pre-emption (docs/INTERNALS.md §21):
            # space-class WAL failure or hard disk watermark. Checked
            # before the backlog window — a degraded node must not let
            # clients consume backlog it cannot durably append. The
            # waiter opens when the probe write succeeds.
            pressure = self.cfg.pressure
            if pressure is not None and pressure.blocked():
                if cmd.from_ref is not None:
                    self._c("commands_rejected_nospace")
                    effects.append(Reply(
                        cmd.from_ref,
                        REJECT_NOSPACE + (pressure.waiter(),),
                    ))
                else:
                    self._c("commands_dropped_overload")
                self._obs_rec.record(
                    "admission_reject", node=self.id[1], group=self.id[0],
                    term=self.current_term, detail="nospace",
                )
                return
            # admission window: bound the appended-but-unapplied backlog
            # (noops and machine-internal commands bypass — the commit
            # gate must never be starved, and timer fires / Append
            # effects fire exactly once with no retry path). Rejected
            # callers back off and retry; noreply commands owe no ack;
            # notify-mode pipelined commands are at-most-once by
            # contract (clients resend on a missing applied
            # notification, reference pipeline_command semantics) —
            # drops are counted either way
            backlog = self.log.next_index() - 1 - self.last_applied
            if backlog >= self.cfg.max_command_backlog:
                if cmd.from_ref is not None:
                    self._c("commands_rejected")
                    # the third element is the window-release waiter:
                    # api.process_command parks on it instead of a
                    # fixed sleep poll (docs/INTERNALS.md §16)
                    effects.append(Reply(
                        cmd.from_ref,
                        REJECT_OVERLOADED + (self._adm_gate.waiter(),),
                    ))
                else:
                    self._c("commands_dropped_overload")
                self._obs_rec.record(
                    "admission_reject", node=self.id[1], group=self.id[0],
                    term=self.current_term, detail=f"backlog={backlog}",
                )
                return
        if cmd.kind in (RA_JOIN, RA_LEAVE, RA_CLUSTER_CHANGE):
            if not self._append_cluster_cmd(cmd, effects):
                return
        idx = self.log.next_index()
        entry = Entry(index=idx, term=self.current_term, cmd=cmd)
        self.log.append(entry)
        self._g("last_index", idx)
        if cmd.ts is not None:
            now_ns = time.monotonic_ns()
            lat = self._lat
            if lat is None or now_ns - lat[1] > 10_000_000_000:
                # one in-flight commit-latency sample; a sample stranded
                # >10s (leadership churn) is abandoned and replaced
                self._lat = [idx, cmd.ts, now_ns, 0, 0, 0]
                self._commit_h["submit_append"].record(now_ns - cmd.ts)
        if cmd.reply_mode == "after_log_append" and cmd.from_ref is not None:
            effects.append(Reply(cmd.from_ref, ("ok", (idx, self.current_term), self.id)))
        elif cmd.reply_mode == "await_consensus" and cmd.from_ref is not None:
            self.pending_replies[idx] = cmd.from_ref

    def _append_cluster_cmd(self, cmd: Command, effects: EffectList) -> bool:
        """Returns False when the change must be rejected. Only one
        in-flight cluster change is allowed (Raft one-at-a-time member
        changes; reference: src/ra_server.erl:3491-3542)."""
        if not self.cluster_change_permitted:
            if cmd.from_ref is not None:
                effects.append(
                    Reply(cmd.from_ref, ("error", "cluster_change_not_permitted"))
                )
            return False
        idx = self.log.next_index()
        new_cluster = {sid: dataclasses.replace(p) for sid, p in self.cluster.items()}
        if cmd.kind == RA_JOIN:
            member, voter = cmd.data
            if member in new_cluster:
                if cmd.from_ref is not None:
                    effects.append(Reply(cmd.from_ref, ("ok", "already_member")))
                return False
            ps = PeerState(next_index=self.log.next_index() + 1)
            if not voter:
                ps.voter_status = ("nonvoter", self.log.last_index_term()[0])
            new_cluster[member] = ps
        elif cmd.kind == RA_LEAVE:
            member = cmd.data
            if member not in new_cluster:
                if cmd.from_ref is not None:
                    effects.append(Reply(cmd.from_ref, ("ok", "not_member")))
                return False
            del new_cluster[member]
        else:  # RA_CLUSTER_CHANGE: explicit voter-status updates
            for member, voter_status in cmd.data:
                if member in new_cluster:
                    new_cluster[member].voter_status = voter_status
        self.previous_cluster = (
            self.cluster_index_term[0],
            self.cluster_index_term[1],
            self.cluster,
        )
        self._set_cluster(new_cluster, idx, self.current_term)
        self.cluster_change_permitted = False
        return True

    def _leader_aer_reply(
        self, msg: AppendEntriesReply, from_peer: Optional[ServerId], effects: EffectList
    ) -> EffectList:
        if msg.term > self.current_term:
            self._update_term(msg.term)
            self._become_follower(effects)
            return effects
        peer = self.cluster.get(from_peer)
        if peer is None or msg.term < self.current_term:
            return effects
        # any same-term reply — success or rejection — proves the
        # follower processed an AER of ours at this term (its election
        # timer reset), so it credits the lease basis
        self._lease_credit(from_peer)
        if msg.success:
            peer.match_index = max(peer.match_index, msg.last_index)
            peer.next_index = max(peer.next_index, msg.last_index + 1)
            if peer.status == "suspended":
                peer.status = "normal"
            self._maybe_emit_pending_release_cursor()
            self._maybe_promote_peer(from_peer, peer, effects)
            self._evaluate_quorum(effects)
        else:
            self._c("aer_replies_failed")
            # Stale-reply detection via last_index/last_term (reference
            # relies on these reply fields, src/ra.hrl:131-143).
            hint = max(1, msg.next_index)
            peer.next_index = max(min(hint, msg.last_index + 1), peer.match_index + 1)
        self._pipeline(effects)
        return effects

    def _maybe_promote_peer(self, sid: ServerId, peer: PeerState, effects: EffectList) -> None:
        if (
            isinstance(peer.voter_status, tuple)
            and peer.voter_status[0] == "nonvoter"
            and peer.match_index >= peer.voter_status[1]
            and self.cluster_change_permitted
        ):
            cmd = Command(kind=RA_CLUSTER_CHANGE, data=((sid, "voter"),))
            self._append_leader(cmd, effects)

    def _evaluate_quorum(self, effects: EffectList) -> None:
        """match_index -> commit_index quorum scan. The leader counts its
        own durable (written) watermark, not its in-memory tail
        (reference: evaluate_quorum/agreed_commit src/ra_server.erl:
        3633-3688)."""
        written_idx, _ = self.log.last_written()
        self._g("last_written_index", written_idx)
        lat = self._lat
        if lat is not None and lat[3] == 0 and written_idx >= lat[0]:
            lat[3] = time.monotonic_ns()
            self._commit_h["append_durable"].record(lat[3] - lat[2])
        match = []
        for sid, p in self.cluster.items():
            if not p.is_voter():
                continue
            match.append(written_idx if sid == self.id else p.match_index)
        if not match:
            return
        agreed = dec.agreed_commit(match)
        if agreed > self.commit_index:
            # current-term gate (Raft 5.4.2): same math as
            # dec.new_commit_index, with the sort done once
            if self.log.fetch_term(agreed) == self.current_term:
                self.commit_index = agreed
                # read-index precondition met: commit_index now covers
                # an entry of our own term (the noop at the latest)
                self._term_commit_ok = True
                if (
                    lat is not None and lat[3] and lat[4] == 0
                    and agreed >= lat[0]
                ):
                    lat[4] = time.monotonic_ns()
                    self._commit_h["durable_commit"].record(lat[4] - lat[3])
                self._apply_to(agreed, effects=effects)

    def _evaluate_queries(self, effects: EffectList) -> None:
        if not self.pending_queries:
            return
        qis = []
        for sid, p in self.cluster.items():
            if not p.is_voter():
                continue
            qis.append(self.query_index if sid == self.id else p.query_index)
        agreed_qi = dec.agreed_commit(qis)
        still = []
        for qi, from_ref, fn in self.pending_queries:
            if qi <= agreed_qi:
                self._c("consistent_queries")
                effects.append(Reply(from_ref, ("ok", fn(self.machine_state), self.id)))
            else:
                still.append((qi, from_ref, fn))
        self.pending_queries = still

    # ------------------------------------------------------------------
    # clock-bound leader lease (docs/INTERNALS.md §20)

    def _lease_credit(self, from_peer: Optional[ServerId]) -> None:
        """Fold a same-term response from ``from_peer`` into the lease
        (no-op when leases are off or the response is unsolicited)."""
        lt = self._lease
        if not lt.cfg.enabled or from_peer is None:
            return
        if not lt.record_ack(from_peer):
            return
        now = self._clock.monotonic()
        had = lt.valid(now)
        if lt.refresh(self.voters(), self.id, now) and not had and lt.valid(now):
            self._obs_rec.record(
                "lease_acquired", node=self.id[1], group=self.id[0],
                term=self.current_term,
                detail=f"expires in {lt.remaining(now):.3f}s",
            )

    def _lease_renewal_round(self, now: float, effects: EffectList) -> None:
        """One throttled heartbeat fan-out whose acks extend the lease.
        There are no idle leader heartbeats in this design, so renewal
        is DEMAND-DRIVEN: reads landing in the back half of the window
        fund the quorum round that extends it — one round per lease
        window amortized over every read inside it. No pending query
        rides on the round; at most one per quarter-window."""
        lt = self._lease
        if now - self._lease_renew_t < lt.cfg.window_s / 4.0:
            return
        self._lease_renew_t = now
        hb = HeartbeatRpc(self.current_term, self.id, self.query_index)
        for sid, p in self.peers().items():
            if p.is_voter():
                lt.record_send(sid, now)
                effects.append(SendRpc(sid, hb))

    def _stickiness_lapsed(self) -> bool:
        """False while the leader-stickiness promise window holds: a
        live leader heard within one election timeout (leaders count
        themselves as in perpetual contact). Callers gate on cfg.lease."""
        if self.leader_id is None:
            return True
        if self.role == LEADER:
            return False
        return (
            self._clock.monotonic() - self._leader_contact
            >= self.cfg.election_timeout_s
        )

    def read_staleness_s(self) -> float:
        """Upper bound on how stale a local read of ``machine_state``
        is, in seconds of leader wall-clock time (staleness-bounded
        follower reads). inf until a leader-stamped freshness anchor
        has been applied — lease-off senders never stamp one, so
        bounded reads stay conservative there by construction."""
        if self._fresh_ts <= 0.0:
            return float("inf")
        return (
            max(0.0, self._clock.time() - self._fresh_ts)
            + self._lease.cfg.drift_epsilon_s
        )

    def _leader_control(self, msg: tuple, effects: EffectList) -> EffectList:
        kind = msg[0]
        if kind == "snapshot_sender_down":
            # routed by the runtime's monitor plumbing when a transfer
            # thread exits (reference: handle_down snapshot_sender,
            # src/ra_server.erl:2640-2660)
            _, sid, reason = msg
            peer = self.cluster.get(sid)
            if peer is None or status_kind(peer.status) != "sending_snapshot":
                return effects
            if reason == "normal":
                peer.status = "normal"
                self._maybe_emit_pending_release_cursor()
            else:
                # exponential backoff: 5000 * 2^(n-1) ms capped at 60 s
                attempts = peer.status[1] + 1
                peer.status = ("snapshot_backoff", attempts)
                delay = min(5000 * (1 << (attempts - 1)), 60000)
                self._c("snapshot_send_failures")
                effects.append(StartSnapshotRetryTimer(sid, delay))
            return effects
        if kind == "snapshot_retry_timeout":
            _, sid = msg
            peer = self.cluster.get(sid)
            if peer is not None and status_kind(peer.status) == "snapshot_backoff":
                # keep the backoff status: the send-effect handler reads
                # the attempt count from it (reference:
                # snapshot_backoff_prevents_immediate_retry)
                effects.append(SendSnapshot(sid, meta=self.log.snapshot_meta()))
            return effects
        if kind == "consistent_query":
            _, fn, from_ref = msg
            lt = self._lease
            if lt.cfg.enabled:
                now = self._clock.monotonic()
                if self._term_commit_ok and lt.valid(now):
                    # lease fast path (§20): linearizable at
                    # read_index = commit_index with ZERO quorum
                    # traffic — the lease quorum's stickiness promise
                    # stands in for the heartbeat round
                    read_idx = self.commit_index
                    if self.last_applied >= read_idx:
                        self._c("read_lease_served")
                        self._c("consistent_queries")
                        effects.append(
                            Reply(from_ref, ("ok", fn(self.machine_state), self.id))
                        )
                    else:
                        self.pending_lease_reads.append((read_idx, from_ref, fn))
                    if lt.remaining(now) < lt.cfg.window_s / 2.0:
                        self._lease_renewal_round(now, effects)
                    return effects
                if lt.expiry > 0.0:
                    # count each lapse once, at detection
                    self._c("read_lease_expirations")
                    self._obs_rec.record(
                        "lease_lost", node=self.id[1], group=self.id[0],
                        term=self.current_term, detail="expired",
                    )
                    lt.expiry = 0.0
                self._c("read_quorum_fallback")
            self.query_index += 1
            self.pending_queries.append((self.query_index, from_ref, fn))
            hb = HeartbeatRpc(self.current_term, self.id, self.query_index)
            if lt.cfg.enabled:
                now = self._clock.monotonic()
            for sid, p in self.peers().items():
                if p.is_voter():
                    if lt.cfg.enabled:
                        # the fallback round's own acks re-earn the
                        # lease: subsequent reads go local again
                        lt.record_send(sid, now)
                    effects.append(SendRpc(sid, hb))
            self._evaluate_queries(effects)  # single-node clusters
            return effects
        if kind == "transfer_leadership":
            _, target, from_ref = msg
            if target == self.id:
                if from_ref is not None:
                    effects.append(Reply(from_ref, ("ok", "already_leader")))
                return effects
            if target not in self.cluster:
                if from_ref is not None:
                    effects.append(Reply(from_ref, ("error", "unknown_member")))
                return effects
            peer = self.cluster[target]
            if not peer.is_voter():
                if from_ref is not None:
                    effects.append(Reply(from_ref, ("error", "non_voter")))
                return effects
            if peer.match_index + 1 != self.log.next_index():
                # only a CONFIRMED-caught-up voter may take over
                # (match_index, not the optimistically-advanced
                # next_index — a peer that was pipelined to but never
                # acked must not pass)
                if from_ref is not None:
                    effects.append(Reply(from_ref, ("error", "not_up_to_date")))
                return effects
            if from_ref is not None:
                effects.append(Reply(from_ref, ("ok", None)))
            effects.append(SendRpc(target, TimeoutNow()))
            # hold while the hand-off is in flight: the target's
            # higher-term vote/AER releases the hold into follower; if
            # nothing arrives, fall back to leading (reference:
            # transfer_leadership_condition, src/ra_server.erl:1015-1035,
            # 2233-2243)

            def transfer_cond(srv: "Server", m: Any) -> bool:
                return (
                    isinstance(m, (AppendEntriesRpc, InstallSnapshotRpc))
                    and m.term > srv.current_term
                )

            self.await_condition(
                Condition(
                    predicate=transfer_cond,
                    timeout_transition_to=LEADER,
                    # short hold: if the TimeoutNow was lost, resume
                    # leading after 5 s rather than the 30 s default
                    # (the held leader is alive, so no peer elects)
                    timeout_duration_ms=5000,
                ),
                effects,
            )
            return effects
        if kind == "aux":
            _, aux_kind, cmd, from_ref = msg
            return self._handle_aux(aux_kind, cmd, from_ref, effects)
        return effects

    def _leader_tick(self, msg: Tick, effects: EffectList) -> EffectList:
        if self._check_quorum_lost():
            # check-quorum: no quorum of voters has been HEARD within
            # the window — one-way partitions leave our AERs flowing
            # out (so no follower ever times out) while nothing comes
            # back. Step down: _become answers every pending client
            # "maybe" immediately (no wedged clients) and the now-
            # silent followers elect a connected leader.
            self._c("check_quorum_stepdowns")
            self._obs_rec.record(
                "check_quorum_stepdown", node=self.id[1], group=self.id[0],
                term=self.current_term,
                detail=f"quorum silent > {self.cfg.check_quorum_window_s}s",
            )
            self.leader_id = None
            self._become_follower(effects, leader=None)
            return effects
        # persist last_applied so effects are not re-issued on recovery
        # (reference: persist_last_applied src/ra_server.erl:2540-2567)
        self.meta.store(self.cfg.uid, "last_applied", self.last_applied)
        effects.extend(self.machine.tick(msg.now_ms, self.machine_state))
        # probe peers whose supported machine version is unknown or
        # below ours (rolling upgrades: a peer restarted with a newer
        # machine must be re-discovered), and bump once the upgrade
        # strategy's requirement is met. Probing stops once every peer
        # reports >= our version.
        own = self.machine.version()
        for sid, p in self.peers().items():
            if p.machine_version is None or (
                p.machine_version < own
                and self.effective_machine_version < own
            ):
                # re-probe lagging peers only while an upgrade is still
                # pending locally (quorum-strategy clusters stop probing
                # a legitimately-old minority once the bump lands)
                effects.append(SendRpc(sid, InfoRpc(self.current_term, self.id)))
        # stale-peer re-send: a peer a full pipeline window ahead of its
        # confirmed match that made NO progress across two ticks cannot
        # accept anything we would pipeline; rewind next_index to
        # match + 1 so replication resumes from a point it can append
        # (reference: stale peer handling around the pipeline window,
        # src/ra_server.erl:2308-2329)
        prev = getattr(self, "_stale_match", None)
        if prev is None:
            prev = self._stale_match = {}
        for sid, p in self.peers().items():
            if (
                status_kind(p.status) == "normal"
                and p.next_index - p.match_index > self.cfg.max_pipeline_count
            ):
                # match 0 means nothing confirmed THIS term (fresh
                # leader): never rewind to 1 — that would re-send the
                # whole log (or stream snapshots) to caught-up peers;
                # the tick's empty probe elicits the reject hint that
                # rewinds next_index to the peer's true position
                if prev.get(sid) == p.match_index and p.match_index > 0:
                    p.next_index = p.match_index + 1
                    self._c("stale_peer_resends")
                prev[sid] = p.match_index
            else:
                prev.pop(sid, None)
        self._maybe_upgrade_machine(effects)
        self._pipeline(effects, force_commit_sync=True)
        return effects

    def _check_quorum_lost(self) -> bool:
        """True when check-quorum is enabled and no quorum of voters
        (self included) has been heard within the window. Peers never
        seen before (fresh joins) count as just-contacted so a
        membership change cannot depose a healthy leader."""
        win = self.cfg.check_quorum_window_s
        if win <= 0:
            return False
        now = self._clock.monotonic()
        live = 1 if self.is_voter_self() else 0
        for sid, p in self.cluster.items():
            if sid == self.id or not p.is_voter():
                continue
            if now - self._peer_contact.setdefault(sid, now) <= win:
                live += 1
        return live < self.required_quorum()

    def _required_machine_version(self) -> int:
        """The version the upgrade strategy currently allows (never below
        the effective version). Unknown peer versions count as
        unsupporting (reference: src/ra_server.erl:223-233)."""
        vers = []
        for sid, p in self.cluster.items():
            if sid == self.id:
                vers.append(self.machine.version())
            elif p.is_voter() or isinstance(p.voter_status, tuple):
                vers.append(p.machine_version if p.machine_version is not None else -1)
        if not vers:
            return max(self.machine.version(), self.effective_machine_version)
        if self.cfg.machine_upgrade_strategy == "quorum":
            vers.sort(reverse=True)
            need = len(vers) // 2 + 1
            v = vers[need - 1]
        else:  # "all"
            v = min(vers)
        return max(v, self.effective_machine_version)

    def _maybe_upgrade_machine(self, effects: EffectList) -> None:
        req = self._required_machine_version()
        if req <= self.effective_machine_version or not self.cluster_change_permitted:
            return
        pending = getattr(self, "_upgrade_noop_idx", None)
        if pending is not None and pending > self.last_applied:
            return  # a bump noop is already in flight
        idx = self.log.next_index()
        self._append_leader(Command(kind=NOOP, machine_version=req), effects)
        self._upgrade_noop_idx = idx

    def _leader_node_event(self, msg: Any, effects: EffectList) -> EffectList:
        if isinstance(msg, NodeEvent):
            for sid, p in self.peers().items():
                if sid[1] == msg.node:
                    # neither direction may clobber a LIVE transfer —
                    # that would let a no_snapshot_sends cursor fire
                    # mid-send and lose the attempt count (the sender's
                    # own death routes through snapshot_sender_down,
                    # which arms the backoff); nodeup resets
                    # disconnected/backoff (reference:
                    # snapshot_backoff_reset_on_nodeup)
                    if status_kind(p.status) == "sending_snapshot":
                        continue
                    p.status = "disconnected" if msg.status == "down" else "normal"
            data = ("nodeup", msg.node) if msg.status == "up" else ("nodedown", msg.node)
            # node/monitor events fire exactly once with no retry path:
            # they must never be shed by the admission window
            self._append_leader(Command(kind=USR, data=data), effects,
                                exempt=True)
        else:  # DownEvent
            self._append_leader(
                Command(kind=USR, data=("down", msg.target, msg.info)), effects,
                exempt=True,
            )
        self._pipeline(effects)
        return effects

    def _pipeline(self, effects: EffectList, force_commit_sync: bool = False) -> None:
        """Build pipelined AppendEntries for every peer (reference:
        make_pipelined_rpc_effects src/ra_server.erl:2285-2434)."""
        last_idx, _ = self.log.last_index_term()
        for sid, peer in self.peers().items():
            if status_kind(peer.status) in (
                "sending_snapshot", "snapshot_backoff", "suspended",
                "disconnected",
            ):
                continue
            sent_any = False
            while (
                peer.next_index <= last_idx
                and (peer.next_index - peer.match_index) <= self.cfg.max_pipeline_count
            ):
                if not self._send_aer(sid, peer, effects):
                    break
                sent_any = True
            if not sent_any and (
                peer.commit_index_sent < self.commit_index or force_commit_sync
            ):
                self._send_aer(sid, peer, effects, empty=True)

    def _send_aer(
        self, sid: ServerId, peer: PeerState, effects: EffectList, empty: bool = False
    ) -> bool:
        prev_idx = peer.next_index - 1
        prev_term = self.log.fetch_term(prev_idx)
        snap = self.log.snapshot_index_term()
        if prev_term is None or (snap is not None and prev_idx < snap[0]):
            # prev entry compacted away: peer needs a snapshot
            # (reference: make_rpc_effect snapshot branch
            # src/ra_server.erl:2392-2415). Carry the attempt count
            # across retries so repeated sender deaths keep backing off.
            attempts = (
                peer.status[1] if status_kind(peer.status) == "snapshot_backoff"
                else 0
            )
            peer.status = ("sending_snapshot", attempts)
            effects.append(SendSnapshot(sid, meta=self.log.snapshot_meta()))
            return False
        entries: Tuple[Entry, ...] = ()
        if not empty:
            last_idx, _ = self.log.last_index_term()
            hi = min(last_idx, prev_idx + self.cfg.max_aer_batch_size)
            if hi > prev_idx:
                acc: List[Entry] = []
                self.log.fold(prev_idx + 1, hi, lambda e, a: (a.append(e), a)[1], acc)
                entries = tuple(acc)
        commit_ts = 0.0
        if self._lease.cfg.enabled:
            # lease basis stamp (oldest outstanding send wins) + the
            # wall-clock freshness stamp followers anchor bounded local
            # reads to; both gated on cfg.lease so the default path
            # pays no clock reads
            self._lease.record_send(sid, self._clock.monotonic())
            commit_ts = self._clock.time()
        rpc = AppendEntriesRpc(
            term=self.current_term,
            leader_id=self.id,
            prev_log_index=prev_idx,
            prev_log_term=prev_term,
            leader_commit=self.commit_index,
            entries=entries,
            commit_ts=commit_ts,
        )
        effects.append(SendRpc(sid, rpc))
        self._c("msgs_sent")
        peer.commit_index_sent = max(peer.commit_index_sent, self.commit_index)
        if entries:
            peer.next_index = entries[-1].index + 1
        return bool(entries)

    # ------------------------------------------------------------------
    # apply loop

    def _apply_to(
        self, idx: int, effects: Optional[EffectList] = None, discard_effects: bool = False
    ) -> None:
        """Apply committed entries to the machine (reference: apply_to /
        apply_with src/ra_server.erl:3244-3335)."""
        sink: EffectList = [] if effects is None else effects
        last_idx, _ = self.log.last_index_term()
        hi = min(idx, last_idx)
        if hi <= self.last_applied:
            return
        lo = self.last_applied + 1
        notify: Dict[Any, List[Any]] = {}

        def apply_one(entry: Entry, acc: None) -> None:
            self._apply_entry(entry, sink if not discard_effects else [], notify,
                              discard_effects)
            return acc

        self.log.fold(lo, hi, apply_one, None)
        self.last_applied = hi
        # apply progress released admission-window room: wake parked
        # rejected clients (one attribute check when none are parked)
        self._adm_gate.open()
        self._c("applied", hi - lo + 1)
        if self.pending_lease_reads and not discard_effects:
            # lease-admitted reads whose read_index is now applied:
            # linearizable as of admission time (state at >= read_index)
            still_reads = []
            for ridx, ref, fn in self.pending_lease_reads:
                if ridx <= hi:
                    self._c("read_lease_served")
                    self._c("consistent_queries")
                    sink.append(Reply(ref, ("ok", fn(self.machine_state), self.id)))
                else:
                    still_reads.append((ridx, ref, fn))
            self.pending_lease_reads = still_reads
        if self._lease.cfg.enabled:
            # freshness floor for staleness-bounded local reads: a
            # leader fully caught up to its commit is fresh as of now;
            # a follower promotes the leader-stamped anchor once the
            # anchored index is applied
            if self.role == LEADER and hi >= self.commit_index:
                self._fresh_ts = self._clock.time()
            elif self._fresh_anchor[1] > 0.0 and self._fresh_anchor[0] <= hi:
                self._fresh_ts = max(self._fresh_ts, self._fresh_anchor[1])
                self._fresh_anchor = (0, 0.0)
        if not discard_effects:
            for who, corrs in notify.items():
                sink.append(Notify(who, tuple(corrs)))
            # machine-driven snapshot/checkpoint decisions ride on the
            # release_cursor effects the machine returned (collected in
            # _apply_entry); cluster-change commits unlock further changes
        if self.commit_index >= self.cluster_index_term[0]:
            self.cluster_change_permitted = self.role == LEADER
        # promote pending nonvoters once changes are permitted again
        if self.role == LEADER and self.cluster_change_permitted and not discard_effects:
            for sid, p in list(self.peers().items()):
                self._maybe_promote_peer(sid, p, sink)

    def _apply_entry(
        self,
        entry: Entry,
        effects: EffectList,
        notify: Dict[Any, List[Any]],
        discard: bool,
    ) -> None:
        cmd = entry.cmd
        if not isinstance(cmd, Command):
            return
        is_leader = self.role == LEADER
        if cmd.kind == USR:
            meta = {
                "index": entry.index,
                "term": entry.term,
                "machine_version": self.effective_machine_version,
                "reply_mode": cmd.reply_mode,
            }
            mac = self.machine.which_module(self.effective_machine_version)
            state, reply, mac_effects = normalize_apply_result(
                mac.apply(meta, cmd.data, self.machine_state)
            )
            self.machine_state = state
            lat = self._lat
            if lat is not None and entry.index == lat[0] and lat[4]:
                lat[5] = time.monotonic_ns()
                self._commit_h["commit_apply"].record(lat[5] - lat[4])
            mac_effects = self._realise_log_effects(entry, mac_effects)
            if not discard:
                # Client replies/notifications and most machine side
                # effects are issued by the leader only; followers keep
                # local-option sends (reference: effect filtering in
                # ra_server_proc, "local" send_msg option).
                if is_leader:
                    effects.extend(mac_effects)
                    self._reply_applied(entry, cmd, reply, effects, notify)
                else:
                    # try_append runs in any raft state (reference:
                    # src/ra_server_proc.erl:1610-1615); local-option
                    # sends are evaluated wherever the local member is
                    effects.extend(
                        e for e in mac_effects
                        if (isinstance(e, SendMsg) and "local" in e.options)
                        or isinstance(e, TryAppend)
                    )
        elif cmd.kind == NOOP:
            if cmd.machine_version > self.effective_machine_version:
                old_v = self.effective_machine_version
                self.effective_machine_version = cmd.machine_version
                mac = self.machine.which_module(cmd.machine_version)
                meta = {
                    "index": entry.index,
                    "term": entry.term,
                    "machine_version": cmd.machine_version,
                }
                state, _reply, mac_effects = normalize_apply_result(
                    mac.apply(meta, ("machine_version", old_v, cmd.machine_version),
                              self.machine_state)
                )
                self.machine_state = state
                if not discard and is_leader:
                    effects.extend(mac_effects)
            if not discard and is_leader:
                self._reply_applied(entry, cmd, None, effects, notify)
        elif cmd.kind in (RA_JOIN, RA_LEAVE, RA_CLUSTER_CHANGE):
            if not discard and is_leader:
                self._reply_applied(entry, cmd, None, effects, notify)
                ps = self.cluster.get(self.id)
                if (
                    self.role == LEADER
                    and ps is not None
                    and ps.voter_status is None
                ):
                    # our own removal committed: relinquish leadership
                    # AND stop — the proc-down broadcast is what tells
                    # the remaining members to elect (reference:
                    # leader_is_removed returns {stop,...},
                    # test/ra_server_SUITE.erl:2121-2142)
                    self._become_follower(effects)
                    effects.append(StopEffect())

    def _realise_log_effects(self, entry: Entry, mac_effects: List[Effect]) -> List[Effect]:
        """Machines steer snapshotting via release_cursor / checkpoint
        effects; the core realises those against its own log (reference:
        update_release_cursor src/ra_server.erl:2455-2479) and passes the
        rest through to the runtime."""
        out: List[Effect] = []
        for eff in mac_effects:
            if isinstance(eff, ReleaseCursor):
                conds = tuple(getattr(eff, "conditions", ()) or ())
                if conds and not self._release_cursor_conditions_met(conds):
                    # stash until the conditions hold (reference:
                    # update_release_cursor_with_written_condition /
                    # _no_snapshot_sends_condition)
                    self.pending_release_cursor = (
                        eff.index, eff.machine_state, conds
                    )
                    continue
                self._do_release_cursor(eff.index, eff.machine_state)
            elif isinstance(eff, Checkpoint):
                mac = self.machine.which_module(self.effective_machine_version)
                self.log.checkpoint(
                    eff.index,
                    tuple(self.members()),
                    self.effective_machine_version,
                    eff.machine_state,
                    live_indexes=tuple(mac.live_indexes(eff.machine_state)),
                )
                self._c("checkpoints_written")
            else:
                out.append(eff)
        return out

    def _do_release_cursor(self, index: int, machine_state: Any) -> None:
        mac = self.machine.which_module(self.effective_machine_version)
        self.log.update_release_cursor(
            index,
            tuple(self.members()),
            self.effective_machine_version,
            machine_state,
            live_indexes=tuple(mac.live_indexes(machine_state)),
        )
        self._c("releases")

    def _release_cursor_conditions_met(self, conds: Tuple[Any, ...]) -> bool:
        for c in conds:
            if c == "no_snapshot_sends":
                if any(
                    status_kind(p.status) == "sending_snapshot"
                    for p in self.cluster.values()
                ):
                    return False
            elif isinstance(c, tuple) and c and c[0] == "written":
                if self.log.last_written()[0] < c[1]:
                    return False
        return True

    def _maybe_emit_pending_release_cursor(self) -> None:
        pend = self.pending_release_cursor
        if pend is not None and self._release_cursor_conditions_met(pend[2]):
            self.pending_release_cursor = None
            self._do_release_cursor(pend[0], pend[1])

    def _reply_applied(
        self,
        entry: Entry,
        cmd: Command,
        reply: Any,
        effects: EffectList,
        notify: Dict[Any, List[Any]],
    ) -> None:
        mode = cmd.reply_mode
        if mode == "await_consensus":
            # pop unconditionally: the table must not leak one future per
            # command on the normal in-memory-entry path
            from_ref = self.pending_replies.pop(entry.index, None) or cmd.from_ref
            if from_ref is not None:
                effects.append(Reply(from_ref, ("ok", reply, self.id)))
        elif isinstance(mode, tuple) and mode and mode[0] == "notify":
            _, corr, who = mode
            notify.setdefault(who, []).append((corr, reply))
        lat = self._lat
        if lat is not None and entry.index == lat[0] and lat[5]:
            # reply stage closes at reply/notify emission (the proc
            # executes the effect immediately after this handler)
            self._commit_h["apply_reply"].record(
                time.monotonic_ns() - lat[5]
            )
            self._lat = None

    # ------------------------------------------------------------------
    # follower

    def _handle_follower(self, msg: Any, from_peer: Optional[ServerId]) -> EffectList:
        effects: EffectList = []
        if isinstance(msg, AppendEntriesRpc):
            return self._follower_aer(msg, from_peer, effects)
        if isinstance(msg, RequestVoteRpc):
            return self._follower_request_vote(msg, from_peer, effects)
        if isinstance(msg, PreVoteRpc):
            return self._process_pre_vote(msg, from_peer, effects)
        if isinstance(msg, InstallSnapshotRpc):
            return self._follower_install_snapshot(msg, from_peer, effects)
        if isinstance(msg, HeartbeatRpc):
            if msg.term >= self.current_term:
                self._update_term(msg.term)
                self.leader_id = msg.leader_id
                if self.cfg.lease:
                    self._leader_contact = self._clock.monotonic()
                effects.append(
                    SendRpc(from_peer, HeartbeatReply(self.current_term, msg.query_index))
                )
            else:
                effects.append(
                    SendRpc(from_peer, HeartbeatReply(self.current_term, 0))
                )
            return effects
        if isinstance(msg, LogEvent):
            self.log.handle_event(msg.evt)
            self._maybe_emit_pending_release_cursor()  # ("written", idx)
            self._follower_send_written_reply(effects)
            self._apply_to(self.commit_index, effects=effects)
            return effects
        if isinstance(msg, ElectionTimeout):
            return self._call_for_election_or_pre_vote(effects)
        if isinstance(msg, TimeoutNow):
            if self.is_voter_self():
                self._c("force_elections")
                # transfer-driven candidacy: votes carry force=True so
                # peers skip stickiness (the transferring leader
                # revoked its lease before sending TimeoutNow)
                self._forced_candidacy = True
                self._call_for_election(effects)
            return effects
        if isinstance(msg, Tick):
            self.meta.store(self.cfg.uid, "last_applied", self.last_applied)
            effects.extend(self.machine.tick(msg.now_ms, self.machine_state))
            return effects
        if isinstance(msg, Command):
            if msg.from_ref is not None:
                effects.append(Reply(msg.from_ref, ("redirect", self.leader_id)))
            return effects
        if isinstance(msg, (RequestVoteResult, PreVoteResult, AppendEntriesReply)):
            if msg.term > self.current_term:
                self._update_term(msg.term)
            return effects
        if isinstance(msg, NodeEvent):
            return effects
        if isinstance(msg, tuple) and msg and msg[0] == "aux":
            _, aux_kind, cmd, from_ref = msg
            return self._handle_aux(aux_kind, cmd, from_ref, effects)
        return effects

    def _follower_aer(
        self, msg: AppendEntriesRpc, from_peer: Optional[ServerId], effects: EffectList
    ) -> EffectList:
        self._c("aer_received")
        snap = self.log.snapshot_index_term()
        snap_idx = snap[0] if snap else 0
        local_prev_term = self.log.fetch_term(msg.prev_log_index)
        code = dec.aer_decision(
            self.current_term,
            msg.term,
            msg.prev_log_index,
            msg.prev_log_term,
            -1 if local_prev_term is None else local_prev_term,
            snap_idx,
        )
        li, lt = self.log.last_index_term()
        if code == dec.AER_STALE:
            effects.append(
                SendRpc(
                    from_peer,
                    AppendEntriesReply(self.current_term, False, li + 1, li, lt),
                )
            )
            return effects
        self._update_term(msg.term)
        if self.cfg.lease:
            # stickiness stamp: any same-or-higher-term AER is leader
            # contact (the stale case returned above)
            self._leader_contact = self._clock.monotonic()
            if msg.commit_ts > self._fresh_anchor[1]:
                # freshness anchor: at leader wall time commit_ts the
                # commit index was >= leader_commit; the local floor
                # advances once apply catches up (read_staleness_s)
                if self.last_applied >= msg.leader_commit:
                    self._fresh_ts = max(self._fresh_ts, msg.commit_ts)
                else:
                    self._fresh_anchor = (msg.leader_commit, msg.commit_ts)
        if self.leader_id != msg.leader_id:
            self.leader_id = msg.leader_id
            # acks to a NEW leader may only cover what it has confirmed
            self._leader_cover = 0
            effects.append(
                RecordLeader(self.cfg.cluster_name, self.leader_id, tuple(self.members()))
            )
        if code in (dec.AER_MISMATCH, dec.AER_BEHIND_SNAPSHOT):
            self._c("aer_replies_failed")
            nid = dec.aer_failure_next_index(self.commit_index, li, msg.prev_log_index, snap_idx)
            reply = SendRpc(
                from_peer,
                AppendEntriesReply(self.current_term, False, nid, li, lt),
            )
            effects.append(reply)
            # hold in await_condition while the requested resend is in
            # flight: repeated failing AERs must not trigger one rewind
            # each (reference: follower_catchup_cond,
            # src/ra_server.erl:1390-1428, 2196-2231). The failure reply
            # above still goes out now; the condition timeout repeats it.
            reason = "missing" if local_prev_term is None else "term_mismatch"
            self.await_condition(
                Condition(
                    predicate=_follower_catchup_cond(reason),
                    timeout_effects=(reply,),
                ),
                effects,
            )
            return effects
        # AER_OK: drop already-matching entries, truncate on divergence,
        # write the rest (reference: drop_existing src/ra_server.erl:3700)
        to_write: List[Entry] = []
        for e in msg.entries:
            if e.index <= li:
                our_term = self.log.fetch_term(e.index)
                if our_term == e.term:
                    continue  # duplicate
                to_write = [x for x in msg.entries if x.index >= e.index]
                break
            to_write.append(e)
        last_entry_idx = msg.entries[-1].index if msg.entries else msg.prev_log_index
        if to_write:
            if to_write[0].index <= li:
                # overwriting a divergent suffix: an uncommitted cluster
                # change adopted from that suffix must be rolled back
                # before the replacement entries are scanned (reference:
                # follower_cluster_change_overwrite_updates_membership;
                # one-at-a-time changes mean depth-1 history suffices —
                # committed changes can never be overwritten)
                ci = self.cluster_index_term[0]
                if ci >= to_write[0].index and self.previous_cluster is not None:
                    pidx, pterm, pcluster = self.previous_cluster
                    if pidx < to_write[0].index:
                        self._set_cluster(pcluster, pidx, pterm)
                        self.previous_cluster = None
            self.log.write(to_write)
            li, lt = self.log.last_index_term()
        self.commit_index = max(self.commit_index, min(msg.leader_commit, last_entry_idx))
        # Reply only with the durable watermark, anchored to what THIS
        # AER covered: a new leader with a shorter log must not receive
        # an ack above its own prev (reference follower_aer_5/6 — reply
        # next_index = prev+n+1 even when our tail is longer). Deferred
        # until the written event when writes are pending
        # (src/ra_server.erl:1457-1474 — replies carry fsynced indexes).
        self._leader_cover = max(getattr(self, "_leader_cover", 0), last_entry_idx)
        wi, wt = self.log.last_written()
        if wi >= last_entry_idx or not to_write:
            ack = min(wi, last_entry_idx)
            at = self.log.fetch_term(ack)
            self._c("aer_replies_success")
            effects.append(
                SendRpc(
                    from_peer,
                    AppendEntriesReply(
                        self.current_term, True, ack + 1, ack,
                        at if at is not None else wt,
                    ),
                )
            )
        # cluster changes take effect at append time
        for e in to_write:
            if isinstance(e.cmd, Command) and e.cmd.kind in (RA_JOIN, RA_LEAVE, RA_CLUSTER_CHANGE):
                self._apply_cluster_entry(e)
        self._apply_to(self.commit_index, effects=effects)
        return effects

    def _apply_cluster_entry(self, entry: Entry) -> None:
        cmd = entry.cmd
        new_cluster = {sid: dataclasses.replace(p) for sid, p in self.cluster.items()}
        if cmd.kind == RA_JOIN:
            member, voter = cmd.data
            if member not in new_cluster:
                ps = PeerState()
                if not voter:
                    ps.voter_status = ("nonvoter", entry.index)
                new_cluster[member] = ps
        elif cmd.kind == RA_LEAVE:
            new_cluster.pop(cmd.data, None)
        elif (
            isinstance(cmd.data, tuple) and cmd.data and cmd.data[0] == "replace"
        ):
            # full-cluster replacement (force_shrink recovery marker)
            new_cluster = {
                member: PeerState(voter_status=vs) for member, vs in cmd.data[1]
            }
        else:
            for member, voter_status in cmd.data:
                if member in new_cluster:
                    new_cluster[member].voter_status = voter_status
        self.previous_cluster = (
            self.cluster_index_term[0],
            self.cluster_index_term[1],
            self.cluster,
        )
        self._set_cluster(new_cluster, entry.index, entry.term)

    def _follower_send_written_reply(self, effects: EffectList) -> None:
        if self.leader_id is None or self.leader_id == self.id:
            return
        # anchor to what the CURRENT leader has confirmed holding: a
        # durable tail inherited from a previous leader must not inflate
        # the new leader's match_index past its own log
        cover = getattr(self, "_leader_cover", 0)
        if cover <= 0:
            return
        wi, wt = self.log.last_written()
        ack = min(wi, cover)
        at = self.log.fetch_term(ack)
        self._c("aer_replies_success")
        effects.append(
            SendRpc(
                self.leader_id,
                AppendEntriesReply(
                    self.current_term, True, ack + 1, ack,
                    at if at is not None else wt,
                ),
            )
        )

    def _follower_request_vote(
        self, msg: RequestVoteRpc, from_peer: Optional[ServerId], effects: EffectList
    ) -> EffectList:
        if (
            self.cfg.lease
            and not msg.force
            and msg.candidate_id != self.leader_id
            and not self._stickiness_lapsed()
        ):
            # leader stickiness (§20 / Raft §9.6): within one election
            # timeout of leader contact the RPC is DISREGARDED entirely
            # — answering false at OUR term is fine, but adopting the
            # higher term would depose the live leader through the term
            # echo. Forced votes (leadership transfer / force_shrink —
            # the old leader revoked its lease first) bypass.
            effects.append(
                SendRpc(from_peer, RequestVoteResult(self.current_term, False))
            )
            return effects
        li, lt = self.log.last_index_term()
        voted_slot = -1
        if self.voted_for is not None and msg.term == self.current_term:
            voted_slot = 0 if self.voted_for == msg.candidate_id else 1
        grant, new_term = dec.vote_decision(
            self.current_term,
            voted_slot if voted_slot >= 0 else -1,
            0,
            msg.term,
            msg.last_log_index,
            msg.last_log_term,
            li,
            lt,
        )
        if new_term > self.current_term:
            self.current_term = new_term
            self.voted_for = None
        if grant:
            self.voted_for = msg.candidate_id
            self.leader_id = None
        if new_term != self.meta.fetch(self.cfg.uid, "current_term", 0) or grant:
            self._persist_term_vote()
        effects.append(SendRpc(from_peer, RequestVoteResult(self.current_term, grant)))
        return effects

    def _follower_install_snapshot(
        self, msg: InstallSnapshotRpc, from_peer: Optional[ServerId], effects: EffectList
    ) -> EffectList:
        if msg.term < self.current_term:
            li, lt = self.log.last_index_term()
            effects.append(
                SendRpc(from_peer, InstallSnapshotResult(self.current_term, li, lt))
            )
            return effects
        if msg.meta.machine_version > self.machine.version():
            # this member cannot interpret state from a machine version
            # it does not have: ignore the transfer until the operator
            # upgrades the module (reference:
            # follower_ignores_installs_snapshot_with_higher_machine_version,
            # test/ra_server_SUITE.erl)
            return effects
        self._update_term(msg.term)
        self.leader_id = msg.leader_id
        if self.cfg.lease:
            self._leader_contact = self._clock.monotonic()
        self._snap_accept = {
            "meta": msg.meta,
            "chunks": [],
            "next_chunk": 0,
            "from": from_peer,
        }
        self._become(RECEIVE_SNAPSHOT, effects)
        effects.append(NextEvent(FromPeer(from_peer, msg)))
        return effects

    def _process_pre_vote(
        self, msg: PreVoteRpc, from_peer: Optional[ServerId], effects: EffectList
    ) -> EffectList:
        """Pre-vote grant, identical in every role (reference keeps one
        process_pre_vote for all roles too: src/ra_server.erl:2926-2984).
        Pre-vote is non-disruptive: no term change, no abdication — a
        genuinely ahead candidate dethrones us with its request_vote."""
        # free capability discovery: the rpc carries the candidate's
        # supported machine version
        peer = self.cluster.get(from_peer)
        if peer is not None:
            peer.machine_version = max(peer.machine_version or 0, msg.machine_version)
        li, lt = self.log.last_index_term()
        granted = dec.pre_vote_decision(
            self.current_term,
            msg.term,
            msg.machine_version,
            self.effective_machine_version,
            msg.last_log_index,
            msg.last_log_term,
            li,
            lt,
        )
        if (
            granted
            and self.cfg.lease
            and msg.candidate_id != self.leader_id
            and not self._stickiness_lapsed()
        ):
            # leader stickiness (§20): within one election timeout of
            # leader contact this voter refuses to help elect a
            # replacement — the promise the leader's lease is bound by
            granted = False
        effects.append(
            SendRpc(from_peer, PreVoteResult(self.current_term, msg.token, granted))
        )
        return effects

    def _call_for_election_or_pre_vote(self, effects: EffectList) -> EffectList:
        if not self.is_voter_self():
            return effects  # nonvoters never start elections
        if self.cfg.lease and not self._stickiness_lapsed():
            # stickiness also gates STANDING: a candidate grants itself,
            # so an early or injected timeout must not let it complete
            # a (pre-)vote quorum inside some leader's lease window —
            # the candidate could be the one intersection voter the
            # safety argument counts on. TimeoutNow bypasses via
            # _call_for_election directly.
            return effects
        if self.cfg.pre_vote:
            return self._call_for_pre_vote(effects)
        return self._call_for_election(effects)

    def _call_for_pre_vote(self, effects: EffectList) -> EffectList:
        self._c("pre_vote_elections")
        self.pre_vote_token = self._new_token()
        self.pre_votes = {self.id}
        self.leader_id = None
        self._become(PRE_VOTE, effects)
        if len(self.voters()) == 1 and self.is_voter_self():
            return self._call_for_election(effects)
        li, lt = self.log.last_index_term()
        rpc = PreVoteRpc(
            term=self.current_term,
            token=self.pre_vote_token,
            candidate_id=self.id,
            version=PROTO_VERSION,
            machine_version=self.machine_version,
            last_log_index=li,
            last_log_term=lt,
        )
        reqs = tuple(
            (sid, rpc) for sid, p in self.peers().items() if p.is_voter()
        )
        effects.append(SendVoteRequests(reqs))
        return effects

    def _call_for_election(self, effects: EffectList) -> EffectList:
        self._c("elections")
        self._obs_rec.record(
            "election", node=self.id[1], group=self.id[0],
            term=self.current_term + 1, detail="candidate round started",
        )
        self.current_term += 1
        self.voted_for = self.id
        self._persist_term_vote()
        self.votes = {self.id}
        self.leader_id = None
        self._become(CANDIDATE, effects)
        if len(self.voters()) == 1 and self.is_voter_self():
            self._become_leader(effects)
            return effects
        li, lt = self.log.last_index_term()
        rpc = RequestVoteRpc(
            term=self.current_term, candidate_id=self.id, last_log_index=li,
            last_log_term=lt, force=self._forced_candidacy,
        )
        reqs = tuple((sid, rpc) for sid, p in self.peers().items() if p.is_voter())
        effects.append(SendVoteRequests(reqs))
        return effects

    # ------------------------------------------------------------------
    # pre_vote role

    def _handle_pre_vote(self, msg: Any, from_peer: Optional[ServerId]) -> EffectList:
        effects: EffectList = []
        if isinstance(msg, PreVoteResult):
            if msg.term > self.current_term:
                self._update_term(msg.term)
                self._become_follower(effects)
                return effects
            if msg.token != self.pre_vote_token or not msg.vote_granted:
                return effects
            if from_peer is not None:
                self.pre_votes.add(from_peer)
            if len(self.pre_votes) >= self.required_quorum():
                self._call_for_election(effects)
            return effects
        if isinstance(msg, AppendEntriesRpc):
            if msg.term >= self.current_term:
                self._become_follower(effects, leader=msg.leader_id)
                effects.append(NextEvent(FromPeer(from_peer, msg)))
            else:
                li, lt = self.log.last_index_term()
                effects.append(
                    SendRpc(
                        from_peer,
                        AppendEntriesReply(self.current_term, False, li + 1, li, lt),
                    )
                )
            return effects
        if isinstance(msg, (RequestVoteRpc, InstallSnapshotRpc)):
            self._become_follower(effects)
            effects.append(NextEvent(FromPeer(from_peer, msg)))
            return effects
        if isinstance(msg, PreVoteRpc):
            return self._process_pre_vote(msg, from_peer, effects)
        if isinstance(msg, HeartbeatRpc):
            return self._nonfollower_heartbeat(msg, from_peer, effects)
        if isinstance(msg, ElectionTimeout):
            return self._call_for_pre_vote(effects)
        if isinstance(msg, LogEvent):
            self.log.handle_event(msg.evt)
            return effects
        if isinstance(msg, Command):
            if msg.from_ref is not None:
                effects.append(Reply(msg.from_ref, ("redirect", self.leader_id)))
            return effects
        return effects

    # ------------------------------------------------------------------
    # candidate role

    def _handle_candidate(self, msg: Any, from_peer: Optional[ServerId]) -> EffectList:
        effects: EffectList = []
        if isinstance(msg, RequestVoteResult):
            if msg.term > self.current_term:
                self._update_term(msg.term)
                self._become_follower(effects)
                return effects
            if msg.term < self.current_term or not msg.vote_granted:
                return effects
            if from_peer is not None:
                self.votes.add(from_peer)
            if len(self.votes) >= self.required_quorum():
                self._become_leader(effects)
            return effects
        if isinstance(msg, AppendEntriesRpc):
            if msg.term >= self.current_term:
                self._update_term(msg.term)
                self._become_follower(effects, leader=msg.leader_id)
                effects.append(NextEvent(FromPeer(from_peer, msg)))
            else:
                li, lt = self.log.last_index_term()
                effects.append(
                    SendRpc(
                        from_peer,
                        AppendEntriesReply(self.current_term, False, li + 1, li, lt),
                    )
                )
            return effects
        if isinstance(msg, RequestVoteRpc):
            if msg.term > self.current_term:
                self._update_term(msg.term)
                self._become_follower(effects)
                effects.append(NextEvent(FromPeer(from_peer, msg)))
            else:
                effects.append(SendRpc(from_peer, RequestVoteResult(self.current_term, False)))
            return effects
        if isinstance(msg, PreVoteRpc):
            return self._process_pre_vote(msg, from_peer, effects)
        if isinstance(msg, InstallSnapshotRpc):
            if msg.term >= self.current_term:
                # a leader exists and we are behind its snapshot: step
                # down and take the transfer as a follower
                self._update_term(msg.term)
                self._become_follower(effects, leader=msg.leader_id)
                effects.append(NextEvent(FromPeer(from_peer, msg)))
            else:
                li, lt = self.log.last_index_term()
                effects.append(
                    SendRpc(from_peer, InstallSnapshotResult(self.current_term, li, lt))
                )
            return effects
        if isinstance(msg, HeartbeatRpc):
            return self._nonfollower_heartbeat(msg, from_peer, effects)
        if isinstance(msg, ElectionTimeout):
            return self._call_for_election(effects)
        if isinstance(msg, LogEvent):
            self.log.handle_event(msg.evt)
            return effects
        if isinstance(msg, Command):
            if msg.from_ref is not None:
                effects.append(Reply(msg.from_ref, ("redirect", self.leader_id)))
            return effects
        return effects

    def _nonfollower_heartbeat(
        self, msg: HeartbeatRpc, from_peer: Optional[ServerId], effects: EffectList
    ) -> EffectList:
        """Heartbeats reaching a pre-vote/candidate server: a current-or-
        higher term proves an elected leader (revert and re-dispatch); a
        stale one gets our term back so the deposed leader steps down
        (reference: pre_vote_heartbeat / candidate_heartbeat)."""
        if msg.term >= self.current_term:
            self._update_term(msg.term)
            self._become_follower(effects, leader=msg.leader_id)
            effects.append(NextEvent(FromPeer(from_peer, msg)))
        else:
            effects.append(
                SendRpc(from_peer, HeartbeatReply(self.current_term, 0))
            )
        return effects

    # ------------------------------------------------------------------
    # receive_snapshot role

    def _snap_ack(self, chunk_no: int) -> InstallSnapshotAck:
        """Chunk ack with receiver-paced credits (docs/INTERNALS.md
        §21): how many further chunks this receiver will accept. A
        storage-blocked receiver grants 0 — the sender parks instead of
        spooling chunks onto a disk that cannot hold them."""
        pressure = self.cfg.pressure
        window = max(1, self.cfg.snapshot_credit_window)
        credits = (window if pressure is None
                   else pressure.snapshot_credits(window))
        if credits:
            self._c("snapshot_credits_granted", credits)
        else:
            self._c("snapshot_credit_waits")
        self._g("snapshot_credit_window", credits)
        return InstallSnapshotAck(self.current_term, chunk_no, credits)

    def _handle_receive_snapshot(self, msg: Any, from_peer: Optional[ServerId]) -> EffectList:
        """Four-phase chunked snapshot install: init -> pre (sparse live
        entries) -> next* -> last (reference: handle_receive_snapshot
        src/ra_server.erl:1659-1807)."""
        effects: EffectList = []
        if isinstance(msg, InstallSnapshotRpc):
            if msg.term < self.current_term:
                li, lt = self.log.last_index_term()
                effects.append(
                    SendRpc(from_peer, InstallSnapshotResult(self.current_term, li, lt))
                )
                return effects
            if msg.chunk_phase == CHUNK_INIT:
                # INIT always starts a fresh accumulator — a retried
                # transfer at the same index must not extend stale
                # chunks. Chunk bodies spool straight to disk when the
                # log's snapshot store supports it (reference:
                # begin_accept, src/ra_snapshot.erl:742-860); "accept"
                # is None on memory-backed logs (in-RAM fallback).
                self._abort_snap_accept()
                self._snap_accept = {
                    "meta": msg.meta, "chunks": [], "next_chunk": 1,
                    "from": from_peer,
                    "accept": self.log.begin_accept_snapshot(msg.meta),
                }
                effects.append(
                    SendRpc(from_peer, self._snap_ack(msg.chunk_no))
                )
                return effects
            acc = self._snap_accept
            if acc is None or acc["meta"].index != msg.meta.index:
                return effects  # no transfer in progress for this snapshot
            if msg.chunk_phase == CHUNK_PRE:
                # sparse live entries preceding the snapshot body; writes
                # are idempotent so pre chunks just advance the cursor
                acc["next_chunk"] = max(acc["next_chunk"], msg.chunk_no + 1)
                entries = msg.data
                for e in entries:
                    if self.log.fetch_term(e.index) is None:
                        self.log.write_sparse(e)
                effects.append(
                    SendRpc(from_peer, self._snap_ack(msg.chunk_no))
                )
                return effects
            # next / last: validate chunk ordering — duplicates (sender
            # retry after a lost ack) are re-acked without appending;
            # future chunks are ignored so the sender retries in order
            if msg.chunk_no < acc["next_chunk"]:
                effects.append(
                    SendRpc(from_peer, self._snap_ack(msg.chunk_no))
                )
                return effects
            if msg.chunk_no > acc["next_chunk"]:
                return effects
            a = acc.get("accept")
            if a is not None and isinstance(msg.data, (bytes, bytearray)):
                a.accept_chunk(msg.data)  # straight to the disk spool
            else:
                if a is not None:
                    # a non-byte chunk (in-proc direct-object transfer)
                    # cannot spool to disk: fall back to in-RAM — always
                    # the transfer's first chunk, so nothing is lost
                    a.abort()
                    acc["accept"] = None
                acc["chunks"].append(msg.data)
            acc["next_chunk"] += 1
            if msg.chunk_phase == CHUNK_LAST:
                return self._complete_snapshot(msg, from_peer, effects)
            effects.append(
                SendRpc(from_peer, self._snap_ack(msg.chunk_no))
            )
            return effects
        if isinstance(msg, ElectionTimeout):
            self._abort_snap_accept()
            self._become_follower(effects)
            return effects
        if isinstance(msg, AppendEntriesRpc) and msg.term >= self.current_term:
            # leader moved on; abandon the transfer
            self._update_term(msg.term)
            self._abort_snap_accept()
            self._become_follower(effects, leader=msg.leader_id)
            effects.append(NextEvent(FromPeer(from_peer, msg)))
            return effects
        if isinstance(msg, RequestVoteRpc):
            # a higher-term election aborts the transfer (reference:
            # receive_snapshot_request_vote_higher_term); stale votes
            # must not (reference: ..._lower_term)
            if msg.term > self.current_term:
                self._update_term(msg.term)
                self._abort_snap_accept()
                self._become_follower(effects)
                effects.append(NextEvent(FromPeer(from_peer, msg)))
            return effects
        if isinstance(msg, LogEvent):
            self.log.handle_event(msg.evt)
            return effects
        if isinstance(msg, Command):
            if msg.from_ref is not None:
                effects.append(Reply(msg.from_ref, ("redirect", self.leader_id)))
            return effects
        return effects

    def _abort_snap_accept(self) -> None:
        """Drop an in-progress transfer, cleaning any disk spool."""
        acc = self._snap_accept
        self._snap_accept = None
        if acc is not None:
            a = acc.get("accept")
            if a is not None and not a.done:
                a.abort()

    def _complete_snapshot(
        self, msg: InstallSnapshotRpc, from_peer: Optional[ServerId], effects: EffectList
    ) -> EffectList:
        acc = self._snap_accept
        assert acc is not None
        old_meta = self.log.snapshot_meta()
        old_state = self.machine_state
        a = acc.get("accept")
        if a is not None:
            # disk-spooled accept: seal + streaming-decode + promote in
            # one step (the capture directory IS the new snapshot — no
            # second serialization of the state)
            machine_state = self.log.complete_accept_snapshot(a)
        else:
            machine_state = self._decode_snapshot(acc["chunks"])
            self.log.install_snapshot(msg.meta, machine_state)
        self.machine_state = machine_state
        self.effective_machine_version = msg.meta.machine_version
        self._obs_rec.record(
            "snapshot_install", node=self.id[1], group=self.id[0],
            term=self.current_term,
            detail=f"installed at index {msg.meta.index} "
                   f"(term {msg.meta.term})",
        )
        self.commit_index = max(self.commit_index, msg.meta.index)
        self.last_applied = max(self.last_applied, msg.meta.index)
        self._set_cluster(
            {sid: PeerState() for sid in msg.meta.cluster}, msg.meta.index, msg.meta.term
        )
        self._c("snapshot_installed")
        self._g("snapshot_index", msg.meta.index)
        effects.extend(
            self.machine.snapshot_installed(msg.meta, machine_state, old_meta, old_state)
        )
        self._snap_accept = None
        self._become_follower(effects, leader=msg.leader_id)
        effects.append(
            SendRpc(
                from_peer,
                InstallSnapshotResult(self.current_term, msg.meta.index, msg.meta.term),
            )
        )
        return effects

    @staticmethod
    def _decode_snapshot(chunks: List[Any]) -> Any:
        from ra_tpu.log.snapshot import decode_snapshot_chunks

        return decode_snapshot_chunks(chunks)

    # ------------------------------------------------------------------
    # await_condition role

    def _handle_await_condition(self, msg: Any, from_peer: Optional[ServerId]) -> EffectList:
        effects: EffectList = []
        cond = self.condition
        if isinstance(msg, RequestVoteRpc):
            # an election is under way: leave the hold and process the
            # vote as a follower (reference: src/ra_server.erl:1918)
            self.condition = None
            self._become_follower(effects)
            effects.append(NextEvent(FromPeer(from_peer, msg) if from_peer else msg))
            return effects
        if isinstance(msg, PreVoteRpc):
            # liveness: a waiting server must still answer pre-vote
            # probes (reference: await_condition_receives_pre_vote)
            return self._process_pre_vote(msg, from_peer, effects)
        if isinstance(msg, ElectionTimeout):
            # a held server still suspects dead leaders: full pre-vote
            # round, NOT the condition's timeout path (reference:
            # src/ra_server.erl:1922-1931; nonvoters never elect)
            if not self.is_voter_self():
                return effects
            self.condition = None
            return self._call_for_election_or_pre_vote(effects)
        if isinstance(msg, ConditionTimeout):
            if (
                msg.generation is not None
                and msg.generation != self.condition_generation
            ):
                return effects  # stale: armed for an earlier hold
            self.condition = None
            if cond is not None and cond.predicate(self, msg):
                self._exit_condition(cond.transition_to, effects)
                return effects
            self._exit_condition(
                cond.timeout_transition_to if cond else FOLLOWER, effects
            )
            if cond is not None:
                effects.extend(cond.timeout_effects)
            return effects
        if cond is not None and cond.predicate(self, msg):
            self.condition = None
            self._exit_condition(cond.transition_to, effects)
            effects.append(NextEvent(FromPeer(from_peer, msg) if from_peer else msg))
            return effects
        if (
            isinstance(msg, (AppendEntriesRpc, InstallSnapshotRpc))
            and msg.term > self.current_term
        ):
            # a higher-term leader is probing while we hold: adopt the
            # term and (for AERs) answer with a prompt failure so the
            # NEW leader rewinds next_index now, instead of hearing
            # nothing until ConditionTimeout repeats a stale reply
            # addressed to the old leader. The hold itself is kept —
            # the condition (wal_up / catch-up resend) still gates what
            # this server may accept.
            self._update_term(msg.term)
            if isinstance(msg, AppendEntriesRpc) and from_peer is not None:
                self.leader_id = msg.leader_id
                snap = self.log.snapshot_index_term()
                li, lt = self.log.last_index_term()
                nid = dec.aer_failure_next_index(
                    self.commit_index, li, msg.prev_log_index,
                    snap[0] if snap else 0,
                )
                effects.append(
                    SendRpc(
                        from_peer,
                        AppendEntriesReply(self.current_term, False, nid, li, lt),
                    )
                )
            return effects
        if isinstance(msg, LogEvent):
            self.log.handle_event(msg.evt)
            self._maybe_emit_pending_release_cursor()  # ("written", idx)
            return effects
        if isinstance(msg, InstallSnapshotResult):
            if msg.term > self.current_term:
                # stale-term rejection: the cluster moved on while we
                # held — step down now rather than resuming a stale
                # leadership on the condition timeout
                self._update_term(msg.term)
                self.condition = None
                self._become_follower(effects)
                return effects
            # a transfer that COMPLETES during a hold: record the
            # peer's progress so a resumed leader pipelines from the
            # snapshot index instead of finding a stranded status
            peer = self.cluster.get(from_peer)
            if peer is not None:
                peer.status = "normal"
                peer.match_index = max(peer.match_index, msg.last_index)
                peer.next_index = max(peer.next_index, msg.last_index + 1)
                self._maybe_emit_pending_release_cursor()
            return effects
        if isinstance(msg, tuple) and msg and msg[0] == "snapshot_sender_down":
            # a transfer that dies during a hold must not strand the
            # peer in sending status: reset so a resumed leader's
            # pipeline re-engages (no retry timer while held)
            peer = self.cluster.get(msg[1])
            if peer is not None and status_kind(peer.status) in (
                "sending_snapshot", "snapshot_backoff",
            ):
                peer.status = "normal"
                self._maybe_emit_pending_release_cursor()
            return effects
        if isinstance(msg, tuple) and msg and msg[0] == "snapshot_retry_timeout":
            peer = self.cluster.get(msg[1])
            if peer is not None and status_kind(peer.status) == "snapshot_backoff":
                peer.status = "normal"  # resumed leaders re-send directly
            return effects
        if isinstance(msg, Command) and msg.from_ref is not None:
            # never strand a caller while held: redirect so the client
            # retries against whatever leader emerges
            effects.append(Reply(msg.from_ref, ("redirect", None)))
            return effects
        return effects

    def _exit_condition(self, role: str, effects: EffectList) -> None:
        if role == LEADER and getattr(self, "_hold_entry_term", None) not in (
            None, self.current_term,
        ):
            # the term advanced while we held (a higher-term probe was
            # adopted mid-hold): resuming leadership would be a stale-
            # term leader — fall back to follower instead
            role = FOLLOWER
        if role == LEADER:
            # returning to leadership after a hold (transfer timed out /
            # WAL recovered) re-enters WITHOUT the fresh-election reset:
            # peer bookkeeping, cluster_change_permitted, and the
            # noop gate are retained, and no new noop is appended
            # (reference: leader_enters_from_await_condition)
            self._become(LEADER, effects)
            self._pipeline(effects)
        else:
            self._become_follower(effects)

    def await_condition(self, cond: Condition, effects: EffectList) -> None:
        self.condition = cond
        self.condition_generation += 1
        # release-time guard: a hold that would resume leadership may
        # only do so in the term it was entered (see _exit_condition)
        self._hold_entry_term = self.current_term
        self._become(AWAIT_CONDITION, effects)

    def _on_wal_down(self) -> EffectList:
        """The shared WAL failed. A leader that cannot persist must
        abdicate (transfer to the most caught-up voter); every role then
        holds in await_condition until the WAL is back, at which point
        the re-injected wal_up event drives the unwritten-tail resend
        (reference: src/ra_server.erl:653-693, 1918-1961)."""
        effects: EffectList = []
        if self.role == LEADER:
            target = None
            best = -1
            for sid, p in self.peers().items():
                if p.is_voter() and p.match_index > best:
                    target, best = sid, p.match_index
            if target is not None:
                effects.append(SendRpc(target, TimeoutNow()))

        def wal_is_up(_srv: "Server", m: Any) -> bool:
            return (
                isinstance(m, LogEvent)
                and isinstance(m.evt, tuple)
                and bool(m.evt)
                and m.evt[0] == "wal_up"
            )

        # a leader whose WAL comes back in the SAME term resumes
        # leadership directly (the abdication TimeoutNow may have been
        # lost; a successful transfer shows up as a higher-term probe
        # during the hold, and the _exit_condition term guard then
        # forces follower). A hold that times out with the WAL still
        # dead always falls back to follower.
        self.await_condition(
            Condition(
                predicate=wal_is_up,
                transition_to=LEADER if self.role == LEADER else FOLLOWER,
            ),
            effects,
        )
        return effects

    # ------------------------------------------------------------------
    # aux machine plumbing

    def _handle_aux(self, kind: str, cmd: Any, from_ref: Any, effects: EffectList) -> EffectList:
        from ra_tpu.aux import AuxContext

        if not hasattr(self, "aux_state"):
            self.aux_state = self.machine.init_aux(self.cfg.cluster_name)
        from ra_tpu.machine import normalize_aux_result

        res = self.machine.handle_aux(
            self.role, kind, cmd, self.aux_state, AuxContext(self)
        )
        reply, self.aux_state, aux_effects = normalize_aux_result(res, self.aux_state)
        if res is None:
            return effects
        effects.extend(aux_effects)
        if kind == "call" and from_ref is not None:
            effects.append(Reply(from_ref, ("ok", reply, self.id)))
        return effects
