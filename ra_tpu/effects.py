"""Effects returned by the pure consensus core and by user machines.

The core never performs I/O: every transition returns
``(next_role, state, effects)`` and the runtime realises the effects —
the same contract as the reference (reference: ``src/ra_machine.erl:
131-159`` for the machine-effect vocabulary and ``src/ra_server_proc.erl:
1530-1861`` for the executor). Effects here are plain dataclasses so the
batch coordinator can serialize them out of a device step cheaply.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, List, Optional, Sequence, Tuple

from ra_tpu.protocol import ServerId


class Effect:
    __slots__ = ()


@dataclasses.dataclass(frozen=True)
class SendRpc(Effect):
    to: ServerId
    msg: Any


@dataclasses.dataclass(frozen=True)
class SendVoteRequests(Effect):
    # [(peer, RequestVoteRpc | PreVoteRpc)] — realised as parallel calls
    requests: Tuple[Tuple[ServerId, Any], ...]


@dataclasses.dataclass(frozen=True)
class SendSnapshot(Effect):
    to: ServerId
    # runtime spawns a chunked sender for this peer
    meta: Any = None


@dataclasses.dataclass(frozen=True)
class Reply(Effect):
    from_ref: Any
    reply: Any


@dataclasses.dataclass(frozen=True)
class Notify(Effect):
    """Deliver applied-notifications: who -> list of correlations."""

    who: Any
    correlations: Tuple[Any, ...]


@dataclasses.dataclass(frozen=True)
class SendMsg(Effect):
    """Machine effect: send an arbitrary message to a pid/actor.

    options: subset of {"ra_event", "cast", "local"} (reference:
    src/ra_machine.erl send_msg options).
    """

    to: Any
    msg: Any
    options: Tuple[str, ...] = ()


@dataclasses.dataclass(frozen=True)
class ModCall(Effect):
    fn: Callable
    args: Tuple[Any, ...] = ()


@dataclasses.dataclass(frozen=True)
class Monitor(Effect):
    kind: str  # "process" | "node"
    target: Any
    component: str = "machine"  # machine | snapshot_sender | aux


@dataclasses.dataclass(frozen=True)
class Demonitor(Effect):
    kind: str
    target: Any
    component: str = "machine"


@dataclasses.dataclass(frozen=True)
class Append(Effect):
    """Machine effect: append ``cmd`` as a NEW user command to the raft
    log — leader-only, silently dropped elsewhere (reference:
    ``{append, Cmd}`` / ``{append, Cmd, ReplyMode}``,
    src/ra_machine.erl:131-159, realised as a next_event command,
    src/ra_server_proc.erl:1604-1609)."""

    cmd: Any
    reply_mode: Any = "noreply"
    from_ref: Any = None


@dataclasses.dataclass(frozen=True)
class TryAppend(Effect):
    """Like :class:`Append` but attempted in ANY raft state — a
    non-leader routes it like any client command (redirect/drop)
    (reference: ``{try_append, Cmd, ReplyMode}``,
    src/ra_server_proc.erl:1610-1615)."""

    cmd: Any
    reply_mode: Any = "noreply"
    from_ref: Any = None


@dataclasses.dataclass(frozen=True)
class Timer(Effect):
    """Machine timer: deliver {timeout, name} to apply after ms (None
    cancels)."""

    name: Any
    ms: Optional[int]


@dataclasses.dataclass(frozen=True)
class LogRead(Effect):
    """Machine effect: read log indexes and feed them back via fn."""

    indexes: Tuple[int, ...]
    fn: Callable[[Sequence[Any]], Any]


@dataclasses.dataclass(frozen=True)
class ReleaseCursor(Effect):
    index: int
    machine_state: Any
    # optional gating conditions (reference: conditional release
    # cursors, src/ra_server.erl:2455-2479): ("written", idx) defers
    # until the log's durable watermark covers idx; "no_snapshot_sends"
    # defers while any peer is mid-snapshot-transfer. Unmet conditions
    # stash the cursor; it re-fires when they become true.
    conditions: Tuple[Any, ...] = ()


@dataclasses.dataclass(frozen=True)
class StartSnapshotRetryTimer(Effect):
    """Arm a retry for a peer whose snapshot sender died (reference:
    start_snapshot_retry_timer, src/ra_server.erl:204, exponential
    5000*2^(n-1) ms capped at 60 s)."""

    to: Any
    delay_ms: int


@dataclasses.dataclass(frozen=True)
class Checkpoint(Effect):
    index: int
    machine_state: Any


@dataclasses.dataclass(frozen=True)
class Aux(Effect):
    cmd: Any


@dataclasses.dataclass(frozen=True)
class NextEvent(Effect):
    """Re-inject a message into the server's own event loop."""

    msg: Any


@dataclasses.dataclass(frozen=True)
class RecordLeader(Effect):
    """Leader identity changed — update leaderboard/registry."""

    cluster_name: str
    leader: Optional[ServerId]
    members: Tuple[ServerId, ...]


@dataclasses.dataclass(frozen=True)
class BgWork(Effect):
    """Run fn on the server's background worker (snapshot write,
    compaction...); err_fn is called with the exception on failure."""

    fn: Callable[[], Any]
    err_fn: Optional[Callable[[BaseException], None]] = None


@dataclasses.dataclass(frozen=True)
class StateEnter(Effect):
    """Marker: role changed (runtime triggers machine state_enter)."""

    role: str


@dataclasses.dataclass(frozen=True)
class StopServer(Effect):
    """The server asked to be terminated (its own removal committed —
    reference: handle_leader returning {stop,...}). The runtime stops
    the proc; the resulting proc-down signal is what lets the remaining
    members arm elections."""


@dataclasses.dataclass(frozen=True)
class GarbageCollection(Effect):
    pass


EffectList = List[Effect]
