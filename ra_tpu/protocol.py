"""Wire protocol records.

Capability parity with the reference's protocol record definitions
(reference: ``src/ra.hrl:122-211``): AppendEntries carries full prev-idx/
term matching info; the AppendEntries *reply* carries the follower's
``next_index`` hint plus its ``last_index``/``last_term`` (a deliberate
deviation from vanilla Raft the reference relies on for stale-reply
detection); pre-vote carries a token and version info; install-snapshot is
chunked with an ``(num, phase)`` chunk state.

These records double as the schema for the TPU batch backend: every fixed-
width field here becomes a column in the device-resident RPC batch arrays
(see ra_tpu.ops.consensus).
"""

from __future__ import annotations

import dataclasses
from typing import Any, List, NamedTuple, Optional, Tuple

ServerId = Tuple[str, str]  # (cluster-unique server name, node name)


class Entry(NamedTuple):
    # NamedTuple, not dataclass: entries/commands are created on the
    # per-command hot path (frozen-dataclass __init__ costs ~4x more).
    # NOTE: this changed the pickle format of durable records pre-1.0 —
    # WAL/segment files written by earlier revisions do not unpickle
    index: int
    term: int
    cmd: Any  # Command


# -- commands stored in the log -------------------------------------------

USR = "usr"  # user machine command
NOOP = "noop"  # leader-election noop (carries machine version)
RA_JOIN = "ra_join"
RA_LEAVE = "ra_leave"
RA_CLUSTER_CHANGE = "ra_cluster_change"


class Command(NamedTuple):
    kind: str  # one of the constants above
    data: Any = None
    # reply mode: "after_log_append" | "await_consensus" | "noreply"
    # | ("notify", corr, caller)
    reply_mode: Any = "noreply"
    # caller ref for synchronous replies (opaque to the core)
    from_ref: Any = None
    machine_version: int = 0  # only meaningful for NOOP
    # "normal" | "low": low-priority commands are buffered behind normal
    # traffic and drained in bounded slices (reference: ra_ets_queue +
    # FLUSH_COMMANDS_SIZE, src/ra_server_proc.erl:160,507-530)
    priority: str = "normal"
    # machine-internal must-deliver commands (timer fires, Append/
    # TryAppend effects): fired exactly once with no retry path, so the
    # admission window must never shed them (client commands are
    # rejected/dropped instead — they have a caller or owe no ack)
    internal: bool = False
    # optional submit timestamp (time.monotonic_ns at client submit).
    # Commands carrying one are eligible for commit-latency stage
    # sampling (obs.COMMIT_STAGES); None opts out — internal commands
    # and bare constructions never pay the sampling cost
    ts: Any = None


# -- snapshot metadata -----------------------------------------------------


def strip_entry_refs(entries: "Tuple[Entry, ...]") -> "Tuple[Entry, ...]":
    """Drop process-ephemeral fields from entries about to cross a
    process boundary (replication / snapshot pre-chunks): reply handles
    (the leader keeps them in its pending-reply table; remote copies
    never need them) and the volatile submit timestamp (``ts`` is a
    LOCAL monotonic stamp — another machine's clock base makes it
    meaningless, and latency sampling must never compare across)."""
    out = []
    changed = False
    for e in entries:
        cmd = e.cmd
        if isinstance(cmd, Command) and (
            cmd.from_ref is not None or cmd.ts is not None
        ):
            out.append(
                Entry(e.index, e.term, cmd._replace(from_ref=None, ts=None))
            )
            changed = True
        else:
            out.append(e)
    return tuple(out) if changed else entries


def sanitize_for_wire(msg: Any) -> Any:
    """Make a protocol message safe to serialize across processes."""
    if isinstance(msg, Command) and msg.ts is not None:
        # the submit stamp is time.monotonic_ns() on the SENDING
        # machine; a remote leader comparing it against its own clock
        # base would record garbage submit_append samples — remote
        # commands simply opt out of commit-stage sampling
        return msg._replace(ts=None)
    if isinstance(msg, AppendEntriesRpc) and msg.entries:
        stripped = strip_entry_refs(msg.entries)
        if stripped is not msg.entries:
            return dataclasses.replace(msg, entries=stripped)
    if isinstance(msg, InstallSnapshotRpc) and msg.chunk_phase == CHUNK_PRE:
        data = msg.data
        if isinstance(data, (list, tuple)):
            return dataclasses.replace(
                msg, data=list(strip_entry_refs(tuple(data)))
            )
    return msg


# encode memo for the fan-out hot shape: ONE Command object rides to
# thousands of groups (the pipelined wave), and every group's log would
# re-pickle it. Keyed by id() and validated by identity — safe because
# the memo holds a strong reference, so a live entry's id cannot be
# reused by another object. Bounded FIFO; commands are immutable once
# submitted (NamedTuple), which is what makes the cache sound.
_ENC_MEMO: dict = {}
_ENC_ORDER: list = []


def encode_cmd(cmd: Any) -> bytes:
    """Serialize a log command for durable storage. Client reply handles
    (``from_ref``) are process-ephemeral — replies are never re-issued
    after a restart (same rule as the reference, INTERNALS.md:91-106) —
    so they are stripped before pickling, as is the volatile submit
    timestamp (``ts``): a monotonic stamp is meaningless across a
    restart, and stripping keeps identical payloads byte-identical on
    disk regardless of when they were submitted."""
    import pickle

    if isinstance(cmd, Command):
        if cmd.from_ref is not None or cmd.ts is not None:
            # never memoize stamped/reply-carrying commands: the memo
            # holds its key object strongly (that is what makes id()
            # keying sound), and pinning retired reply handles would
            # extend "process-ephemeral" arbitrarily. The fan-out hot
            # shape this cache exists for is a bare noreply Command;
            # per-run dedup of stamped ones is Log._bulk_insert's memo.
            return pickle.dumps(cmd._replace(from_ref=None, ts=None))
        key = id(cmd)
        hit = _ENC_MEMO.get(key)
        if hit is not None and hit[0] is cmd:
            return hit[1]
        out = pickle.dumps(cmd)
        _ENC_MEMO[key] = (cmd, out)
        _ENC_ORDER.append(key)
        if len(_ENC_ORDER) > 128:
            try:
                _ENC_MEMO.pop(_ENC_ORDER.pop(0), None)
            except IndexError:
                pass  # concurrent eviction: bound is approximate
        return out
    return pickle.dumps(cmd)


@dataclasses.dataclass(frozen=True)
class SnapshotMeta:
    index: int
    term: int
    cluster: Tuple[ServerId, ...]
    machine_version: int
    # sparse live indexes above `index` that must be retained in the log
    live_indexes: Tuple[int, ...] = ()


# -- RPCs ------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class AppendEntriesRpc:
    term: int
    leader_id: ServerId
    prev_log_index: int
    prev_log_term: int
    leader_commit: int
    entries: Tuple[Entry, ...] = ()
    # leader-computed hint: every entry in this batch is a plain USR
    # command (no noops/cluster changes). Lets the receiver skip the
    # per-entry specials/cluster scan on the write hot path; False is
    # always safe (receiver scans).
    plain_usr: bool = False
    # leader wall-clock stamp taken while leader_commit was current
    # (staleness-bounded follower reads, docs/INTERNALS.md §20). 0.0
    # when the sender runs lease-off — receivers then never advance
    # their freshness floor and bounded local reads stay conservative.
    commit_ts: float = 0.0


@dataclasses.dataclass(frozen=True)
class AppendEntriesReply:
    term: int
    success: bool
    # follower's expectation/bookkeeping (reference: src/ra.hrl:131-143)
    next_index: int
    last_index: int
    last_term: int


@dataclasses.dataclass(frozen=True)
class RequestVoteRpc:
    term: int
    candidate_id: ServerId
    last_log_index: int
    last_log_term: int
    # leadership-transfer (TimeoutNow) and force_shrink candidacies set
    # this so voters skip leader stickiness (§20): the old leader
    # revoked its lease before soliciting the vote, so deposing it
    # early is safe. Ordinary elections leave it False.
    force: bool = False


@dataclasses.dataclass(frozen=True)
class RequestVoteResult:
    term: int
    vote_granted: bool


@dataclasses.dataclass(frozen=True)
class PreVoteRpc:
    term: int
    token: Any
    candidate_id: ServerId
    version: int  # protocol version
    machine_version: int
    last_log_index: int
    last_log_term: int


@dataclasses.dataclass(frozen=True)
class PreVoteResult:
    term: int
    token: Any
    vote_granted: bool


# chunk phases for snapshot transfer
CHUNK_INIT = "init"  # first chunk of meta negotiation
CHUNK_PRE = "pre"  # sparse live entries preceding the snapshot body
CHUNK_NEXT = "next"
CHUNK_LAST = "last"


@dataclasses.dataclass(frozen=True)
class InstallSnapshotRpc:
    term: int
    leader_id: ServerId
    meta: SnapshotMeta
    chunk_no: int
    chunk_phase: str  # CHUNK_*
    data: Any = b""


@dataclasses.dataclass(frozen=True)
class InstallSnapshotResult:
    """Terminal reply: transfer complete (or stale-term rejection)."""

    term: int
    last_index: int
    last_term: int


@dataclasses.dataclass(frozen=True)
class InstallSnapshotAck:
    """Mid-transfer chunk ack consumed by the sender, not the consensus
    core."""

    term: int
    chunk_no: int
    # receiver-paced flow control (docs/INTERNALS.md §21): how many
    # further chunks the receiver is prepared to accept beyond
    # ``chunk_no``. Storage-blocked receivers grant 0 (the sender backs
    # off and retries instead of spooling onto a full disk). Default 1
    # keeps old-format acks (and pickled peers) on stop-and-wait.
    credits: int = 1


@dataclasses.dataclass(frozen=True)
class HeartbeatRpc:
    term: int
    leader_id: ServerId
    query_index: int


@dataclasses.dataclass(frozen=True)
class HeartbeatReply:
    term: int
    query_index: int


@dataclasses.dataclass(frozen=True)
class InfoRpc:
    """Peer-capability probe (reference: #info_rpc{} src/ra.hrl:202) —
    the leader discovers followers' supported machine versions to gate
    upgrade strategies."""

    term: int
    leader_id: ServerId


@dataclasses.dataclass(frozen=True)
class InfoReply:
    term: int
    machine_version: int


# Peer protocol traffic the transport contract allows to drop: every
# type here is periodically retried/resent by its sender (AER resend
# windows, election retry timers, heartbeat ticks), so a full ingress
# lane sheds it with a counter instead of blocking the producer
# (docs/INTERNALS.md §16 backpressure table). Everything NOT listed —
# client commands (they reject through the admission path), log
# events, snapshot chunks, queries — must never be silently dropped.
LOSSY_PROTOCOL_TYPES = frozenset((
    AppendEntriesRpc, AppendEntriesReply,
    RequestVoteRpc, RequestVoteResult,
    PreVoteRpc, PreVoteResult,
    HeartbeatRpc, HeartbeatReply,
))

# Client-visible admission reject reply: ``("reject", "overloaded")``,
# optionally extended with a third element — a ``threading.Event`` the
# server sets when the admission window (or a full ingress lane)
# releases, so ``api.process_command`` parks on the release instead of
# sleeping a fixed backoff. The gate is process-local (never pickled:
# rejects are generated by the node the client called).
REJECT_OVERLOADED = ("reject", "overloaded")

# Storage-degraded admission reject (docs/INTERNALS.md §21): the node's
# WAL hit a space-class failure (ENOSPC/EDQUOT) or the hard disk
# watermark pre-empted admission. Same shape and gate semantics as
# REJECT_OVERLOADED — the third element's Event opens when the probe
# write succeeds (or the watermark clears), so parked clients resume
# the moment storage recovers.
REJECT_NOSPACE = ("reject", "nospace")


# -- events delivered to the server core (non-peer messages) ---------------


@dataclasses.dataclass(frozen=True)
class ElectionTimeout:
    # detector-fired timeouts stamp the monotonic time the suspicion
    # was CONFIRMED; the handler drops the trigger when the group has
    # seen contact (or restarted its election window) since — a delayed
    # delivery (e.g. behind a long jit compile in the pipelined loop)
    # must not act on a stale observation and depose a fresh leader.
    # 0.0 (explicit operator/test triggers) always acts.
    armed_at: float = 0.0


@dataclasses.dataclass(frozen=True)
class TimeoutNow:
    """Leadership-transfer trigger: the target starts an election
    immediately, skipping pre-vote (Raft §3.10). Sent leader->target
    over the wire, so it lives with the protocol records."""


@dataclasses.dataclass(frozen=True)
class Tick:
    now_ms: int = 0


@dataclasses.dataclass(frozen=True)
class LogEvent:
    """Event from the log/WAL subsystem (written confirmations etc.)."""

    evt: Any


@dataclasses.dataclass(frozen=True)
class NodeEvent:
    node: str
    status: str  # "up" | "down"


@dataclasses.dataclass(frozen=True)
class DownEvent:
    """A monitored process/actor went down."""

    target: Any
    info: Any = None


@dataclasses.dataclass(frozen=True)
class FromPeer:
    """Envelope: message `msg` received from peer `peer`."""

    peer: ServerId
    msg: Any


# Ring item class codes — the flat tagged-item layout (docs/INTERNALS.md
# §18). Producers stamp one per published ring item so the native
# drain-classify pass (ra_tpu.native.classify) can partition a drained
# burst with the GIL released; the Python routing half walks the
# partitions. RC_CMD_LOW / RC_CMDS_LOW carry the producer-side priority
# split that the classify loop would otherwise compute per item.
RC_MSG, RC_CMD, RC_CMD_LOW, RC_CMDS, RC_CMDS_LOW, RC_BATCH = range(6)
