"""Composable nemesis plane: fault dimensions as first-class objects.

ROADMAP item 5 ("scenario diversity as a product surface"): every fault
dimension the harnesses know — symmetric and one-way partitions, disk
failpoints, node/coordinator crash-restarts, membership churn, overload
bursts, active-set mode flips — is a ``Dimension`` object, and a seeded
``Planner`` interleaves them so they can run ALL AT ONCE (the regime
BlackWater-style fleets of cheap unreliable nodes actually see, and the
coverage the LNT model-checking work shows single-fault tests miss).

Contracts:

- **Replayable**: the planner draws from its OWN ``random.Random`` (in
  combined mode), so the nemesis schedule is a pure function of the
  seed; every action is appended to ``planner.schedule`` and the whole
  schedule is dumped in the repro bundle when a run fails.
- **Heal on every exit path**: the planner is a context manager whose
  ``__exit__`` unblocks every transport, restores flipped modes, and
  ``faults.disarm_all()`` — including exception/assertion teardown, so
  a failed soak cannot leak armed failpoints or blocked transports into
  the next test in the process (``nemesis_heals_forced`` counts when
  that safety net actually had faults to clean).
- **Observable**: every inject/heal lands in the ``FlightRecorder`` as
  a ``"nemesis"`` event (post-mortems interleave faults with elections)
  and bumps the per-dimension ``NEMESIS_FIELDS`` counters, so a soak
  can prove each enabled dimension actually fired.

The kv/fifo harness (``ra_tpu.kv_harness``) builds a ``NemesisContext``
of backend closures (how to block, restart, churn on THAT backend) and
either fires single dimensions from its legacy dice (flag-compatible
``planner.fire``) or lets ``planner.step`` drive everything at once.
"""

from __future__ import annotations

import dataclasses
import random
import sys
from typing import Any, Callable, Dict, List, Optional, Tuple

from ra_tpu import counters as ra_counters
from ra_tpu import faults, obs
from ra_tpu.counters import NEMESIS_FIELDS

# seeded disk-fault menu: every entry self-heals (one-shots disarm on
# fire; node supervision / the harness infra check recovers the rest).
# Entries are (site, action, trigger, weight): weights skew the draw
# per site so space-class faults (ENOSPC/EDQUOT — the storage-pressure
# survival plane, docs/INTERNALS.md §21) fire often enough per soak to
# exercise degraded-mode entry/exit without drowning out the integrity
# class (EIO / torn / thread-crash) the restart paths need.
DISK_FAULT_MENU: List[Tuple[str, Tuple, Tuple, int]] = [
    ("wal.fsync", ("raise", "eio"), ("one_shot",), 2),
    ("wal.write", ("torn", 0.5), ("one_shot",), 2),
    ("wal.write", ("raise", "enospc"), ("one_shot",), 3),
    ("wal.write", ("raise", "edquot"), ("one_shot",), 1),
    ("wal.fsync", ("raise", "enospc"), ("one_shot",), 1),
    ("wal.thread", ("crash",), ("one_shot",), 2),
    ("segment_writer.thread", ("crash",), ("one_shot",), 2),
    ("segment_writer.flush", ("raise", "eio"), ("one_shot",), 2),
    ("meta.append", ("raise", "eio"), ("one_shot",), 2),
    ("wal.fsync", ("latency", 0.02), ("one_shot", 2), 2),
]
_DISK_MENU_WEIGHTS = [w for _, _, _, w in DISK_FAULT_MENU]


def pick_disk_fault(rng: random.Random) -> Tuple[str, Tuple, Tuple]:
    """One weighted menu draw (single rng consumption: random())."""
    site, action, trigger, _w = rng.choices(
        DISK_FAULT_MENU, weights=_DISK_MENU_WEIGHTS, k=1
    )[0]
    return site, action, trigger


@dataclasses.dataclass
class NemesisContext:
    """Backend adapter: how to execute each fault on one backend.

    ``peers``/``members`` return node names; ``block`` is DIRECTIONAL
    (only ``frm``'s sends to ``to`` drop) — the transports are already
    directional (``InProcTransport``/``TcpTransport`` ``blocked`` sets),
    which is what makes one-way partitions a first-class dimension.
    Optional callbacks gate their dimensions: a backend that cannot
    flip step modes simply leaves ``set_mode`` as ``None``.
    """

    peers: Callable[[], List[str]]            # every transport peer
    members: Callable[[], List[str]]          # current member node names
    block: Callable[[str, str], None]         # drop frm -> to sends
    unblock_all: Callable[[], None]
    restart: Optional[Callable[[str], None]] = None
    membership_step: Optional[Callable[[], Optional[str]]] = None
    fault_scopes: Optional[Callable[[], List[str]]] = None
    overload_burst: Optional[Callable[[], int]] = None
    set_mode: Optional[Callable[[str], None]] = None
    get_mode: Optional[Callable[[], str]] = None


class Dimension:
    """One composable fault axis. ``inject`` draws ONLY from the rng it
    is handed (the caller decides whether that is the workload stream —
    legacy flag parity — or the planner's own stream) and returns
    ``(verb, detail)`` with verb in {"inject", "heal", "skip"}.
    ``heal`` must be idempotent: the planner calls it on every exit
    path, including after an explicit mid-run heal."""

    name = "?"

    def __init__(self) -> None:
        self.planner: Optional["Planner"] = None

    def inject(self, ctx: NemesisContext, rng: random.Random):
        raise NotImplementedError

    def heal(self, ctx: NemesisContext) -> Optional[str]:
        return None

    def active(self) -> bool:
        return False


class PartitionDimension(Dimension):
    """Symmetric isolation of one member (both directions blocked to
    every peer) — the classic kv_harness partition, dice-compatible."""

    name = "partition"

    def inject(self, ctx, rng):
        p = self.planner
        if p.sym_victim is None and rng.random() < 0.7:
            victim = rng.choice(ctx.members())
            for n in ctx.peers():
                if n != victim:
                    ctx.block(victim, n)
                    ctx.block(n, victim)
            p.sym_victim = victim
            return "inject", f"isolate {victim}"
        return "heal", None

    def heal(self, ctx):
        p = self.planner
        if p.sym_victim is not None:
            detail = f"rejoin {p.sym_victim}"
            p.sym_victim = None
            return detail
        return None

    def active(self):
        return self.planner.sym_victim is not None


class OneWayPartitionDimension(Dimension):
    """Asymmetric partition: ``a`` can no longer reach ``b`` while every
    other direction (including ``b -> a``) stays up. Blocking each
    follower's path BACK to the leader yields the classic stale-leader
    shape: AppendEntries still flow out, acks never return — the
    check-quorum step-down (server.py) is what keeps clients unwedged."""

    name = "oneway"

    def inject(self, ctx, rng):
        p = self.planner
        mem = ctx.members()
        if p.oneway_pair is None and len(mem) >= 2:
            a, b = rng.sample(mem, 2)
            ctx.block(a, b)
            p.oneway_pair = (a, b)
            return "inject", f"{a} -/-> {b}"
        return "heal", None

    def heal(self, ctx):
        p = self.planner
        if p.oneway_pair is not None:
            a, b = p.oneway_pair
            detail = f"restore {a} -> {b}"
            p.oneway_pair = None
            return detail
        return None

    def active(self):
        return self.planner.oneway_pair is not None


class DiskFaultDimension(Dimension):
    """Arm one seeded failpoint from the menu against a random node's
    storage stack; supervision (or the batch infra sweep) heals the
    damage, ``disarm_all`` clears anything still armed-but-unfired."""

    name = "disk"

    def __init__(self) -> None:
        super().__init__()
        self.armed = 0

    def inject(self, ctx, rng):
        site, action, trigger = pick_disk_fault(rng)
        faults.arm(site, action, trigger,
                   seed=rng.randrange(1 << 30),
                   scope=rng.choice(ctx.fault_scopes()))
        self.armed += 1
        return "inject", f"{site}:{action[0]}"

    def heal(self, ctx):
        if self.armed:
            self.armed = 0
            faults.disarm_all()
            return "disarm_all"
        return None

    def active(self):
        return self.armed > 0


class DiskFullDimension(Dimension):
    """ENOSPC storm: a PERSISTENT space-class failure against one
    node's WAL (``("always",)`` trigger — every write, and every reopen
    probe, keeps failing until heal). This is the storage-pressure
    survival drill (docs/INTERNALS.md §21): the victim must flip to
    ``storage_degraded`` (typed RA_NOSPACE rejects, elections/reads
    still served), NOT restart-from-disk, and its probe loop must
    auto-resume when the heal clears the storm. EDQUOT is drawn
    occasionally: same class, different errno."""

    name = "disk_full"

    def __init__(self) -> None:
        super().__init__()
        self.storming = False

    def inject(self, ctx, rng):
        if self.storming:
            return "heal", None
        scope = rng.choice(ctx.fault_scopes())
        which = "edquot" if rng.random() < 0.25 else "enospc"
        faults.arm("wal.write", ("raise", which), ("always",),
                   seed=rng.randrange(1 << 30), scope=scope)
        self.storming = True
        return "inject", f"{which} storm @ {scope or 'all'}"

    def heal(self, ctx):
        if self.storming:
            self.storming = False
            faults.disarm("wal.write")
            return "storm cleared"
        return None

    def active(self):
        return self.storming


class SlowDiskDimension(Dimension):
    """Slow-disk brownout: persistent fsync latency against one node's
    WAL. The victim's li-smoothed fsync gauge must cross the brownout
    threshold, shed its leaderships via transfer_leadership, and
    un-mark once the latency clears (docs/INTERNALS.md §21)."""

    name = "slow_disk"

    # brownout detection needs a streak of slow ticks: a storm that
    # heals on the very next roll lasts tens of milliseconds at harness
    # op rates — below any sane detector window. Hold the storm for at
    # least this many subsequent fires before a roll may heal it
    # (deterministic: hold state is a pure function of the fire
    # sequence, so schedules stay seed-replayable).
    MIN_HOLD_FIRES = 8

    def __init__(self) -> None:
        super().__init__()
        self.slowed = False
        self._held = 0

    def inject(self, ctx, rng):
        if self.slowed:
            self._held += 1
            if self._held < self.MIN_HOLD_FIRES:
                return "skip", None
            return "heal", None
        scope = rng.choice(ctx.fault_scopes())
        delay = rng.choice((0.02, 0.05))
        faults.arm("wal.fsync", ("latency", delay), ("always",),
                   seed=rng.randrange(1 << 30), scope=scope)
        self.slowed = True
        self._held = 0
        return "inject", f"fsync +{delay * 1000:.0f}ms @ {scope or 'all'}"

    def heal(self, ctx):
        if self.slowed:
            self.slowed = False
            faults.disarm("wal.fsync")
            return "latency cleared"
        return None

    def active(self):
        return self.slowed


class CrashRestartDimension(Dimension):
    """Node/coordinator crash-restart. The restart callback is expected
    to recover synchronously from durable state (server restart on the
    actor backend, coordinator rebuild from WAL/meta/segments on the
    batch backend), so inject counts as both injected and healed. A
    symmetrically-partitioned victim is skipped: restarting it would
    half-dissolve the partition on backends whose transport state dies
    with the process."""

    name = "crash"

    def inject(self, ctx, rng):
        victim = rng.choice(ctx.members())
        if victim != self.planner.sym_victim:
            ctx.restart(victim)
            return "inject", f"crash-restart {victim}"
        return "skip", None


class MembershipDimension(Dimension):
    """One churn step (remove the spare if joined, else join it). Only
    on a fully-connected cluster: removing an alive member while
    another is partitioned away can drop below quorum and wedge until
    the next heal."""

    name = "membership"

    def inject(self, ctx, rng):
        p = self.planner
        if p.sym_victim is None and p.oneway_pair is None:
            what = ctx.membership_step()
            return "inject", what or "churn"
        return "skip", None


class OverloadDimension(Dimension):
    """A bounded ack-free burst straight past the admission window
    (cluster + current leader, so the flood cannot miss the one node
    whose window matters). Bursts are self-draining; the heal hook
    marks the flood over for the counter pair."""

    name = "overload"

    def __init__(self) -> None:
        super().__init__()
        self.bursting = False

    def inject(self, ctx, rng):
        n = ctx.overload_burst()
        self.bursting = True
        return "inject", f"burst {n} ack-free cmds"

    def heal(self, ctx):
        if self.bursting:
            self.bursting = False
            return "flood drained"
        return None

    def active(self):
        return self.bursting


class ModeFlipDimension(Dimension):
    """Live active-set step-mode flip (batch backend: the coordinator
    reads ``active_set`` per step, so auto/always/never can change
    under load); heal restores the pre-fault mode."""

    name = "modeflip"

    def __init__(self) -> None:
        super().__init__()
        self.orig: Optional[str] = None

    def inject(self, ctx, rng):
        mode = rng.choice(("auto", "always", "never"))
        if self.orig is None:
            self.orig = ctx.get_mode()
        ctx.set_mode(mode)
        return "inject", f"active_set={mode}"

    def heal(self, ctx):
        if self.orig is not None:
            ctx.set_mode(self.orig)
            detail = f"active_set={self.orig}"
            self.orig = None
            return detail
        return None

    def active(self):
        return self.orig is not None


# network dimensions heal together (one unblock_all clears every block)
_NET_DIMS = ("partition", "oneway")
# dimensions cleared by the periodic transient heal (the legacy
# ``kv_harness.heal()`` scope: network blocks + armed failpoints).
# disk_full/slow_disk ride it too: their storms are persistent
# ("always" triggers), so the periodic heal is what bounds each
# degraded/brownout episode's length.
_TRANSIENT_DIMS = _NET_DIMS + ("disk", "disk_full", "slow_disk")


class Planner:
    """Seeded fault scheduler over a set of dimensions.

    Two driving modes, usable together:

    - ``fire(name, rng)`` — the legacy path: the HARNESS dice decide
      when a dimension fires and pass their own rng, so existing
      flag-gated runs keep their exact seed-deterministic op sequence;
    - ``step(op_i)`` — the combined path: the planner's own rng decides
      per op whether to fire and which dimension, so the schedule
      replays from the nemesis seed alone regardless of workload
      timing.

    Use as a context manager: ``__exit__`` ALWAYS heals everything and
    disarms every failpoint, whatever path left the block.
    """

    def __init__(self, ctx: NemesisContext, seed: int, label: str,
                 dimensions: List[Dimension],
                 fault_rate: float = 0.22) -> None:
        self.ctx = ctx
        self.seed = seed
        self.label = label
        # planner stream is decorrelated from the workload stream (which
        # uses Random(seed) directly)
        self.rng = random.Random((seed << 16) ^ 0x4E454D)  # "NEM"
        self.dims: Dict[str, Dimension] = {}
        for d in dimensions:
            d.planner = self
            self.dims[d.name] = d
        self._order = [d.name for d in dimensions]
        self.fault_rate = fault_rate
        self.schedule: List[Tuple[Any, str, str, Any]] = []
        self.sym_victim: Optional[str] = None
        self.oneway_pair: Optional[Tuple[str, str]] = None
        self.ctr = ra_counters.registry().new(("nemesis", label),
                                              NEMESIS_FIELDS)

    # -- driving -------------------------------------------------------

    def fire(self, name: str, rng: random.Random, op_i: Any = None) -> None:
        """Fire one dimension now, drawing from the CALLER's rng (legacy
        dice parity). A "heal" verdict from the dimension triggers the
        transient heal — the legacy dice healed everything transient on
        a failed partition roll."""
        dim = self.dims[name]
        out = dim.inject(self.ctx, rng)
        verb, detail = out if out is not None else ("skip", None)
        if verb == "inject":
            self._record(op_i, name, "inject", detail)
            self.ctr.incr(f"nemesis_{name}_injected")
            if name == "crash":
                # restart callbacks recover synchronously
                self.ctr.incr("nemesis_crash_healed")
            if name == "membership" and detail == "add":
                self.ctr.incr("nemesis_membership_healed")
        elif verb == "heal":
            self.heal_transient(op_i)

    def step(self, op_i: Any) -> None:
        """Combined mode: one planner-rng draw decides whether any fault
        fires this op, a second picks the dimension uniformly."""
        r = self.rng
        if r.random() >= self.fault_rate:
            return
        self.fire(r.choice(self._order), r, op_i)

    # -- healing -------------------------------------------------------

    @property
    def net_active(self) -> bool:
        return self.sym_victim is not None or self.oneway_pair is not None

    def heal_transient(self, op_i: Any = None) -> None:
        """The legacy ``heal()`` scope: drop every transport block and
        disarm failpoints (when the disk dimension is in play). Safe and
        cheap to call even when nothing is active."""
        for name in _TRANSIENT_DIMS:
            dim = self.dims.get(name)
            if dim is None:
                continue
            detail = dim.heal(self.ctx)
            if detail is not None:
                self._record(op_i, name, "heal", detail)
                self.ctr.incr(f"nemesis_{name}_healed")
        self.ctx.unblock_all()

    def heal_all(self, op_i: Any = None) -> None:
        """Heal every dimension (transients + mode flips + overload)."""
        self.heal_transient(op_i)
        for name, dim in self.dims.items():
            if name in _TRANSIENT_DIMS:
                continue
            detail = dim.heal(self.ctx)
            if detail is not None:
                self._record(op_i, name, "heal", detail)
                self.ctr.incr(f"nemesis_{name}_healed")

    # -- teardown guarantee -------------------------------------------

    def __enter__(self) -> "Planner":
        return self

    def __exit__(self, et, ev, tb) -> bool:
        # the guarantee: EVERY exit path — normal return, consistency
        # failure, infra-check abort, arbitrary exception — leaves the
        # process with no blocks and no armed failpoints
        leaked = any(d.active() for d in self.dims.values())
        if leaked:
            self.ctr.incr("nemesis_heals_forced")
        try:
            self.heal_all("teardown")
        finally:
            faults.disarm_all()
        return False  # never swallow the original exception

    # -- replay / post-mortem -----------------------------------------

    def _record(self, op_i: Any, name: str, verb: str, detail: Any) -> None:
        self.schedule.append((op_i, name, verb, detail))
        obs.record_event("nemesis", node=self.sym_victim,
                         detail=f"{name} {verb}: {detail}"
                                f"{'' if op_i is None else f' (op {op_i})'}")

    def counters(self) -> Dict[str, int]:
        return self.ctr.to_dict()

    def dump_schedule(self, file=None, header: str = "") -> None:
        """The repro half of the bundle: replaying the run is
        ``run(seed=..., ...)`` with the same flags — this dump is the
        evidence of what that seed DID, aligned on workload op index so
        it can be read against the flight recorder."""
        f = file or sys.stderr
        print(f"-- nemesis schedule ({len(self.schedule)} actions, "
              f"seed={self.seed}){header} --", file=f)
        for op_i, name, verb, detail in self.schedule:
            print(f"   op={op_i!r:>10} {name:<10} {verb:<6} {detail}",
                  file=f)


def standard_dimensions(
    *,
    partitions: bool = True,
    oneway: bool = False,
    disk_faults: bool = False,
    disk_full: bool = False,
    slow_disk: bool = False,
    restarts: bool = False,
    membership: bool = False,
    overload: bool = False,
    mode_flips: bool = False,
) -> List[Dimension]:
    """The harness dimension set, flag-gated (a context lacking a
    callback must not enable the dimension that needs it)."""
    dims: List[Dimension] = []
    if partitions:
        dims.append(PartitionDimension())
    if oneway:
        dims.append(OneWayPartitionDimension())
    if disk_faults:
        dims.append(DiskFaultDimension())
    if disk_full:
        dims.append(DiskFullDimension())
    if slow_disk:
        dims.append(SlowDiskDimension())
    if restarts:
        dims.append(CrashRestartDimension())
    if membership:
        dims.append(MembershipDimension())
    if overload:
        dims.append(OverloadDimension())
    if mode_flips:
        dims.append(ModeFlipDimension())
    return dims
