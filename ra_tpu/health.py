"""Cluster health plane: vectorized per-group lag/churn introspection.

The per-group diagnosis layer the placement/rebalancing work (ROADMAP
item 1) consumes: *which* of the thousands of groups on a node are
stuck, lagging, or flapping — computed off the mirrors the node already
holds (the coordinator's device arrays + ``_applied_np``; the actor
backend's per-server scalars), with **no per-group Python loop** on the
batch backend and **one fetch per tick** (the ``scans == fetches``
counter invariant in ``HEALTH_FIELDS`` proves it). The reference's
``ra:overview/1`` + per-server metrics ETS is the capability anchor:
per-group introspection cheap enough to leave on in production;
BlackWater Raft (arxiv 2203.07920) rebalances on exactly this feed.

Per-group gauges, all numpy-vectorized per scan:

- ``commit_gap``   — commit_index - last_applied (commit→apply lag);
- ``match_gap``    — leader's last_index minus the slowest active
  peer's confirmed match (follower replication lag, leaders only);
- ``backlog``      — last_index - last_applied (the appended-but-
  unapplied admission backlog the flow-control window bounds);
- ``commit_rate``  — li-smoothed per-group applied/sec
  (:class:`ra_tpu.li.VectorLeakyIntegrator`);
- ``churn``        — EWMA of the per-scan term-bump indicator in
  [0, 1] (0.3 after one election, →1 under sustained churn) plus a
  raw ``churn_rate`` in bumps/sec;
- ``leader_age_s`` — leader stickiness: seconds since the group's
  leader identity last changed.

On top, a per-group anomaly state machine with hysteresis::

    quiet ──────────────► stuck     backlog/commit_gap pending AND
      ▲   (stuck_ticks       │      applied frozen for stuck_ticks
      │    consecutive       │      consecutive scans
      │    scans)            │
      ├─────────────► flapping      churn EWMA ≥ churn_enter
      │               (exit: churn ≤ churn_exit
      │                for clear_ticks scans)
      └─────────────► lagging       any gap ≥ lag_enter
                      (exit: all gaps ≤ lag_exit
                       for clear_ticks scans)

Severity order stuck > flapping > lagging: a group qualifying for
several states reports the worst. Entering/leaving a state emits a
``health_transition`` flight-recorder event, so anomaly onsets line up
with the election/deposition/WAL-failure trace on the same timeline.

``api.cluster_health()`` merges every registered scanner with the
leaderboard into one machine-readable feed; ``scripts/ra_top.py``
renders it as a periodic terminal top-K view, and the per-node
aggregate gauges (``HEALTH_FIELDS``) ride the normal Prometheus
exposition.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Dict, List, Optional, Tuple

import numpy as np

from ra_tpu import counters as ra_counters
from ra_tpu.li import VectorLeakyIntegrator

# anomaly states (int8 codes; severity == code, higher is worse)
QUIET = 0
LAGGING = 1
FLAPPING = 2
STUCK = 3
STATE_NAMES = {QUIET: "quiet", LAGGING: "lagging", FLAPPING: "flapping",
               STUCK: "stuck"}

# role codes shared with ra_tpu.ops.consensus (0..3) plus the actor
# backend's non-device holds
ROLE_FOLLOWER = 0
ROLE_PRE_VOTE = 1
ROLE_CANDIDATE = 2
ROLE_LEADER = 3
ROLE_HELD = 4
ROLE_NAMES = {ROLE_FOLLOWER: "follower", ROLE_PRE_VOTE: "pre_vote",
              ROLE_CANDIDATE: "candidate", ROLE_LEADER: "leader",
              ROLE_HELD: "held"}

NO_LEADER_KEY = np.int64(-(1 << 40))  # distinct from any real identity


class HealthConfig:
    """Anomaly thresholds. Enter thresholds are strictly above exit
    thresholds (hysteresis): a group flickering around one boundary
    does not flicker between states."""

    __slots__ = ("stuck_ticks", "clear_ticks", "lag_enter", "lag_exit",
                 "churn_enter", "churn_exit", "alpha")

    def __init__(self, stuck_ticks: int = 3, clear_ticks: int = 2,
                 lag_enter: int = 64, lag_exit: int = 16,
                 churn_enter: float = 0.5, churn_exit: float = 0.1,
                 alpha: float = 0.3):
        if lag_exit >= lag_enter or churn_exit >= churn_enter:
            raise ValueError("hysteresis requires exit < enter thresholds")
        self.stuck_ticks = stuck_ticks
        self.clear_ticks = clear_ticks
        self.lag_enter = lag_enter
        self.lag_exit = lag_exit
        self.churn_enter = churn_enter
        self.churn_exit = churn_exit
        self.alpha = alpha


class HealthScanner:
    """Per-node scanner: persistent per-group EWMA/hysteresis state in
    flat numpy arrays addressed by slot, updated by one vectorized
    ``scan`` per tick. Slots are allocated per group name (``ensure``)
    and recycled on ``release`` — the batch coordinator allocates once
    at add_groups (slot == gid order), the actor node re-ensures its
    live procs each sweep.

    Thread model: ``scan`` runs only on the owner's detector/tick
    thread (single writer). ``rows``/``summary`` read best-effort
    snapshots from any thread, same contract as counters."""

    def __init__(self, node_name: str, backend: str = "",
                 capacity: int = 64,
                 config: Optional[HealthConfig] = None):
        self.node = node_name
        self.backend = backend
        self.cfg = config or HealthConfig()
        self.counters = ra_counters.new(
            ("health", node_name), ra_counters.HEALTH_FIELDS
        )
        self._lock = threading.Lock()  # slot table only; scan is 1-writer
        self._slot_of: Dict[str, int] = {}
        self._free: List[int] = list(range(capacity - 1, -1, -1))
        self._names: List[Optional[str]] = [None] * capacity
        self._clusters: List[Optional[str]] = [None] * capacity
        # node-scope disk-pressure anomaly (0=clear 1=soft 2=hard); the
        # hysteresis lives in pressure.DiskWatermark — this is the
        # published, transition-evented mirror (docs/INTERNALS.md §21)
        self.disk_pressure = 0
        self._alloc(capacity)

    def _alloc(self, capacity: int) -> None:
        z_i = lambda: np.zeros(capacity, np.int64)  # noqa: E731
        z_f = lambda: np.zeros(capacity, np.float64)  # noqa: E731
        self.capacity = capacity
        self.seen = np.zeros(capacity, bool)
        self.state = np.zeros(capacity, np.int8)
        self.prev_term = z_i()
        self.prev_applied = z_i()
        self.leader_key = np.full(capacity, NO_LEADER_KEY, np.int64)
        self.leader_since = z_f()
        self.churn = z_f()  # term-bump-indicator EWMA in [0, 1]
        self.churn_rate = z_f()  # raw bumps/sec EWMA (display gauge)
        self.stuck_streak = z_i()
        self.clear_streak = z_i()
        self.li = VectorLeakyIntegrator(capacity, alpha=self.cfg.alpha)
        # last-scan snapshot (what rows() renders)
        self.role = np.zeros(capacity, np.int8)
        self.term = z_i()
        self.applied = z_i()
        self.commit_gap = z_i()
        self.match_gap = z_i()
        self.backlog = z_i()
        self.last_scan_t = 0.0

    def _grow(self, need: int) -> None:
        cap = self.capacity
        new_cap = cap
        while new_cap < need:
            new_cap *= 2
        old = self.__dict__.copy()
        old_li = self.li
        self._alloc(new_cap)
        for k in ("seen", "state", "prev_term", "prev_applied",
                  "leader_key", "leader_since", "churn", "churn_rate",
                  "stuck_streak", "clear_streak", "role", "term",
                  "applied", "commit_gap", "match_gap", "backlog"):
            getattr(self, k)[:cap] = old[k]
        old_li.grow(new_cap)
        self.li = old_li
        self.last_scan_t = old["last_scan_t"]
        self._free.extend(range(new_cap - 1, cap - 1, -1))
        self._names.extend([None] * (new_cap - cap))
        self._clusters.extend([None] * (new_cap - cap))

    # -- slot table --------------------------------------------------------

    def _reset_slot(self, slot: int) -> None:
        """Zero EVERY per-slot statistic: a recycled slot must not leak
        the previous occupant's EWMAs/streaks into a new group (a fresh
        group inheriting a dead flapper's churn would classify flapping
        on its first scan)."""
        self.seen[slot] = False
        self.state[slot] = QUIET
        self.churn[slot] = 0.0
        self.churn_rate[slot] = 0.0
        self.stuck_streak[slot] = 0
        self.clear_streak[slot] = 0
        self.li.rate[slot] = 0.0
        self.leader_since[slot] = 0.0
        self.leader_key[slot] = NO_LEADER_KEY
        for arr in (self.prev_term, self.prev_applied, self.role,
                    self.term, self.applied, self.commit_gap,
                    self.match_gap, self.backlog):
            arr[slot] = 0

    def ensure(self, name: str, cluster: str) -> int:
        with self._lock:
            slot = self._slot_of.get(name)
            if slot is not None:
                return slot
            if not self._free:
                self._grow(self.capacity + 1)
            slot = self._free.pop()
            self._slot_of[name] = slot
            self._names[slot] = name
            self._clusters[slot] = cluster
            self._reset_slot(slot)  # fresh state on (re)allocation
            return slot

    def release(self, name: str) -> None:
        with self._lock:
            slot = self._slot_of.pop(name, None)
            if slot is None:
                return
            self._names[slot] = None
            self._clusters[slot] = None
            self._reset_slot(slot)
            self._free.append(slot)

    # -- the scan ----------------------------------------------------------

    def scan(self, now: float, slots: np.ndarray, role: np.ndarray,
             term: np.ndarray, applied: np.ndarray, commit: np.ndarray,
             last_index: np.ndarray, match_gap: np.ndarray,
             leader_key: np.ndarray) -> None:
        """One vectorized health pass over the groups at ``slots``.
        All arrays are aligned with ``slots``; ``match_gap`` is the
        caller-computed follower replication gap (0 for non-leaders),
        ``leader_key`` any int identity that changes when the group's
        leader does (NO_LEADER_KEY when unknown). The caller fetched
        its mirrors in ONE operation and bumps the ``health_fetches`` counter
        itself."""
        cfg = self.cfg
        n = len(slots)
        if n == 0:
            return
        dt = now - self.last_scan_t if self.last_scan_t else 0.0
        self.last_scan_t = now

        term = term.astype(np.int64, copy=False)
        applied = applied.astype(np.int64, copy=False)
        commit = commit.astype(np.int64, copy=False)
        last_index = last_index.astype(np.int64, copy=False)
        leader_key = leader_key.astype(np.int64, copy=False)

        fresh = ~self.seen[slots]
        if fresh.any():
            fi = slots[fresh]
            self.prev_term[fi] = term[fresh]
            self.prev_applied[fi] = applied[fresh]
            self.leader_key[fi] = leader_key[fresh]
            self.leader_since[fi] = now
            self.seen[fi] = True

        commit_gap = np.maximum(commit - applied, 0)
        backlog = np.maximum(last_index - applied, 0)
        gap = np.maximum(np.maximum(commit_gap, backlog), match_gap)

        d_applied = np.maximum(applied - self.prev_applied[slots], 0)
        progress = d_applied > 0
        bumped = term > self.prev_term[slots]
        a = cfg.alpha
        churn = a * bumped + (1 - a) * self.churn[slots]
        if dt > 0:
            self.churn_rate[slots] = (
                a * (term - self.prev_term[slots]) / dt
                + (1 - a) * self.churn_rate[slots]
            )
            self.li.sample(slots, d_applied, dt)
        moved = leader_key != self.leader_key[slots]
        if moved.any():
            mi = slots[moved]
            self.leader_key[mi] = leader_key[moved]
            self.leader_since[mi] = now

        # -- anomaly state machine (vectorized, with hysteresis) ----------
        prev_state = self.state[slots]
        pending = (backlog > 0) | (commit_gap > 0)
        stuck_streak = np.where(
            pending & ~progress, self.stuck_streak[slots] + 1, 0
        )
        is_stuck = stuck_streak >= cfg.stuck_ticks
        enter_flap = churn >= cfg.churn_enter
        enter_lag = gap >= cfg.lag_enter
        # exit only after clear_ticks consecutive below-exit scans; a
        # group with in-flight work still counts as calm while it makes
        # progress (steady load always has a nonzero instantaneous
        # backlog — only a FROZEN backlog blocks clearing)
        calm = (
            (churn <= cfg.churn_exit) & (gap <= cfg.lag_exit)
            & (progress | ~pending)
        )
        clear_streak = np.where(calm, self.clear_streak[slots] + 1, 0)
        cleared = clear_streak >= cfg.clear_ticks

        target = np.zeros(n, np.int8)
        target[enter_lag] = LAGGING
        target[enter_flap] = FLAPPING
        target[is_stuck] = STUCK
        # hold the previous anomaly unless a WORSE one fires or the
        # group has been provably calm for clear_ticks scans
        hold = (prev_state > target) & ~cleared
        state = np.where(hold, prev_state, target).astype(np.int8)

        self.stuck_streak[slots] = stuck_streak
        self.clear_streak[slots] = clear_streak
        self.churn[slots] = churn
        self.prev_term[slots] = term
        self.prev_applied[slots] = applied
        self.state[slots] = state
        self.role[slots] = role.astype(np.int8, copy=False)
        self.term[slots] = term
        self.applied[slots] = applied
        self.commit_gap[slots] = commit_gap
        self.match_gap[slots] = match_gap.astype(np.int64, copy=False)
        self.backlog[slots] = backlog

        # transitions: Python cost only for groups that actually flipped
        changed = np.flatnonzero(state != prev_state)
        if len(changed):
            from ra_tpu import obs as _obs

            self.counters.incr("health_transitions", len(changed))
            for k in changed.tolist():
                slot = int(slots[k])
                _obs.record_event(
                    "health_transition", node=self.node,
                    group=self._names[slot], term=int(term[k]),
                    detail=(
                        f"{STATE_NAMES[int(prev_state[k])]}->"
                        f"{STATE_NAMES[int(state[k])]} "
                        f"commit_gap={int(commit_gap[k])} "
                        f"backlog={int(backlog[k])} "
                        f"match_gap={int(match_gap[k])} "
                        f"churn={churn[k]:.2f}"
                    ),
                )

        c = self.counters
        c.incr("health_scans")
        c.put("health_stuck", int((state == STUCK).sum()))
        c.put("health_flapping", int((state == FLAPPING).sum()))
        c.put("health_lagging", int((state == LAGGING).sum()))
        c.put("health_quiet", int((state == QUIET).sum()))
        c.put("health_max_commit_gap", int(commit_gap.max(initial=0)))
        c.put("health_max_match_gap", int(match_gap.max(initial=0)))
        c.put("health_max_backlog", int(backlog.max(initial=0)))

    # -- node-scope anomalies ----------------------------------------------

    DISK_STATE_NAMES = {0: "clear", 1: "soft", 2: "hard"}

    def note_disk_pressure(self, state: int) -> None:
        """Publish the node's disk-pressure tri-state (computed with
        hysteresis by :class:`ra_tpu.pressure.DiskWatermark`). Unlike
        the per-group states this is node-scope: one value, driven by
        the owner's detector thread alongside ``scan``. Transitions
        emit a ``health_transition`` flight-recorder event so pressure
        onsets line up with WAL failures / elections on one timeline."""
        state = int(state)
        prev = self.disk_pressure
        if state == prev:
            return
        self.disk_pressure = state
        self.counters.put("health_disk_pressure", state)
        self.counters.incr("health_disk_transitions")
        from ra_tpu import obs as _obs

        _obs.record_event(
            "health_transition", node=self.node, group="",
            detail=(
                f"disk_pressure {self.DISK_STATE_NAMES.get(prev, prev)}->"
                f"{self.DISK_STATE_NAMES.get(state, state)}"
            ),
        )

    # -- reads -------------------------------------------------------------

    def rows(self) -> List[Dict[str, Any]]:
        """Per-group gauge rows from the latest scan (any thread)."""
        with self._lock:
            present = [(name, slot) for name, slot in self._slot_of.items()]
        now = time.monotonic()
        out = []
        for name, i in present:
            if not self.seen[i]:
                continue
            out.append({
                "group": name,
                "cluster": self._clusters[i],
                "node": self.node,
                "state": STATE_NAMES[int(self.state[i])],
                "severity": int(self.state[i]),  # == state code, higher worse
                "role": ROLE_NAMES.get(int(self.role[i]), "?"),
                "term": int(self.term[i]),
                "applied": int(self.applied[i]),
                "commit_gap": int(self.commit_gap[i]),
                "match_gap": int(self.match_gap[i]),
                "backlog": int(self.backlog[i]),
                "commit_rate": round(float(self.li.rate[i]), 2),
                "churn": round(float(self.churn[i]), 3),
                "churn_rate": round(float(self.churn_rate[i]), 3),
                "leader_age_s": round(
                    max(0.0, now - float(self.leader_since[i])), 2
                ),
            })
        return out

    def summary(self) -> Dict[str, Any]:
        c = self.counters
        return {
            "node": self.node,
            "backend": self.backend,
            "groups": len(self._slot_of),
            "scans": c.get("health_scans"),
            "fetches": c.get("health_fetches"),
            "transitions": c.get("health_transitions"),
            "states": {
                "stuck": c.get("health_stuck"),
                "flapping": c.get("health_flapping"),
                "lagging": c.get("health_lagging"),
                "quiet": c.get("health_quiet"),
            },
            "disk_pressure": self.DISK_STATE_NAMES.get(
                self.disk_pressure, self.disk_pressure
            ),
            "reads": self._read_totals(),
        }

    # read-path totals (docs/INTERNALS.md §20) summed over this node's
    # server/coordinator counter sets; cumulative, so consumers like
    # scripts/ra_top.py can difference successive snapshots into reads/s
    _READ_FIELDS = ("read_lease_served", "read_quorum_fallback",
                    "read_local_bounded", "read_stale_rejected")

    def _read_totals(self) -> Dict[str, int]:
        tot = dict.fromkeys(self._READ_FIELDS, 0)
        reg = ra_counters.registry()
        for key in reg.names():
            mine = key == ("coordinator", self.node) or (
                isinstance(key, tuple) and len(key) == 2
                and isinstance(key[1], tuple) and len(key[1]) == 2
                and key[1][1] == self.node
            )
            if not mine:
                continue
            cs = reg.fetch(key)
            if cs is None:
                continue
            for f in self._READ_FIELDS:
                try:
                    tot[f] += cs.get(f)
                except KeyError:
                    pass  # counter set without read fields
        return tot


# ---------------------------------------------------------------------------
# process-global scanner registry (api.cluster_health joins over it)

_lock = threading.Lock()
_scanners: Dict[str, HealthScanner] = {}


def register(node_name: str, backend: str = "", capacity: int = 64,
             config: Optional[HealthConfig] = None) -> HealthScanner:
    with _lock:
        sc = _scanners.get(node_name)
        if sc is None:
            sc = HealthScanner(node_name, backend=backend,
                               capacity=capacity, config=config)
            _scanners[node_name] = sc
        return sc


def unregister(node_name: str) -> None:
    with _lock:
        _scanners.pop(node_name, None)
    ra_counters.delete(("health", node_name))


def scanners() -> Dict[str, HealthScanner]:
    with _lock:
        return dict(_scanners)


def node_health(node_name: str) -> Optional[Dict[str, Any]]:
    with _lock:
        sc = _scanners.get(node_name)
    if sc is None:
        return None
    return {"summary": sc.summary(), "groups": sc.rows()}
