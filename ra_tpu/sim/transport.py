"""Simulated network: schedule-driven delivery with seeded faults.

Implements the ``InProcTransport`` seam (``send`` returning False on
known-undeliverable, directional ``blocked`` pairs, ``node_alive`` /
``proc_alive``) over the sim run queue, so the pure ``Server`` cores and
the nemesis plane (``NemesisContext`` closures -> ``block`` /
``unblock_all``) drive it unchanged.

Every send draws a stable sequence number and one decision from the
network's OWN rng stream (decorrelated from the workload/election
streams): deliver after the base latency, drop in flight, duplicate, or
delay. Blocked directed pairs refuse at the sender (``send`` -> False,
like a closed connection: the caller marks the peer disconnected);
probabilistic drops are silent in-flight loss (``send`` -> True), like
a lossy link. Both are recorded in the world trace keyed by the send
seq, which is what makes a failing schedule replayable and shrinkable.
"""

from __future__ import annotations

import random
from typing import Any, Callable, Dict, Optional, Set, Tuple

from ra_tpu.protocol import ServerId
from ra_tpu.sim.scheduler import SimScheduler


class SimNetwork:
    def __init__(
        self,
        sched: SimScheduler,
        seed: int,
        drop_p: float = 0.0,
        dup_p: float = 0.0,
        delay_p: float = 0.0,
        delay_ms_max: int = 50,
        base_latency_ms: int = 1,
        ctr=None,
        trace: Optional[Callable[..., None]] = None,
    ) -> None:
        self.sched = sched
        self.rng = random.Random((seed << 8) ^ 0x4E4554)  # "NET"
        self.drop_p = drop_p
        self.dup_p = dup_p
        self.delay_p = delay_p
        self.delay_ms_max = delay_ms_max
        self.base_latency_ms = base_latency_ms
        self.ctr = ctr
        self.trace = trace or (lambda *a: None)
        # node_name -> deliver(to_sid, msg, from_sid); None while crashed
        self._deliver: Dict[str, Optional[Callable[[ServerId, Any, Optional[ServerId]], None]]] = {}
        self.blocked: Set[Tuple[str, str]] = set()  # directed (from, to)
        self.send_seq = 0
        self.dropped = 0

    def _c(self, field: str, n: int = 1) -> None:
        if self.ctr is not None:
            self.ctr.incr(field, n)

    # -- node registry -------------------------------------------------------

    def attach(self, node_name: str, deliver) -> None:
        self._deliver[node_name] = deliver

    def detach(self, node_name: str) -> None:
        self._deliver[node_name] = None

    # -- fault injection (InProcTransport seam; NemesisContext closures) ------

    def block(self, a: str, b: str) -> None:
        self.blocked.add((a, b))

    def unblock_all(self) -> None:
        self.blocked.clear()

    # -- aliveness (InProcTransport seam) --------------------------------------

    def node_alive(self, node_name: str) -> bool:
        return self._deliver.get(node_name) is not None

    def proc_alive(self, sid: ServerId) -> bool:
        return self.node_alive(sid[1])

    def known_nodes(self):
        return list(self._deliver.keys())

    # -- sending ----------------------------------------------------------------

    def send(self, frm: ServerId, to: ServerId, msg: Any) -> bool:
        """Schedule delivery; False when known-undeliverable (dead node
        or blocked directed pair), True otherwise — including silent
        in-flight loss, which a sender cannot observe."""
        self.send_seq += 1
        seq = self.send_seq
        if (frm[1], to[1]) in self.blocked or not self.node_alive(to[1]):
            self.dropped += 1
            self._c("sim_msgs_dropped")
            return False
        # one decision per send, one rng draw shape per branch
        r = self.rng.random()
        kind = type(msg).__name__
        if r < self.drop_p:
            self.dropped += 1
            self._c("sim_msgs_dropped")
            self.trace("drop", seq, frm[1], to[1], kind)
            return True
        # the single draw partitions [0,1) into disjoint fault bands:
        # [0, drop) | [drop, drop+delay) | [.., +dup) | the rest delivers
        delay = self.base_latency_ms
        if r < self.drop_p + self.delay_p:
            delay += 1 + self.rng.randrange(self.delay_ms_max)
            self._c("sim_msgs_delayed")
            self.trace("delay", seq, frm[1], to[1], kind, delay)
        self._arm(seq, frm, to, msg, delay, kind)
        if self.drop_p + self.delay_p <= r < self.drop_p + self.delay_p + self.dup_p:
            dup_delay = delay + 1 + self.rng.randrange(self.delay_ms_max)
            self._c("sim_msgs_duplicated")
            self.trace("dup", seq, frm[1], to[1], kind, dup_delay)
            self._arm(seq, frm, to, msg, dup_delay, kind)
        return True

    def _arm(self, seq: int, frm: ServerId, to: ServerId, msg: Any,
             delay_ms: int, kind: str) -> None:
        def deliver() -> None:
            # re-checked at delivery time: a partition or crash that
            # landed while the message was in flight eats it
            if (frm[1], to[1]) in self.blocked:
                self.dropped += 1
                self._c("sim_msgs_dropped")
                return
            fn = self._deliver.get(to[1])
            if fn is None:
                self.dropped += 1
                self._c("sim_msgs_dropped")
                return
            self._c("sim_msgs_delivered")
            self.trace("deliver", seq, frm[1], to[1], kind)
            fn(to, msg, frm)

        self.sched.after_ms(delay_ms, deliver)
