"""Virtual clock: integer-millisecond simulated time.

Drop-in for ``ra_tpu.runtime.clock.WallClock`` behind the clock seam
(``ServerConfig.clock``, ``TimerService(clock=...)``): ``monotonic()``
and ``time()`` read simulated time, and ``sleep()`` REFUSES — in a
deterministic simulation nothing may block a real thread; waiting is
expressed by scheduling an event (``SimScheduler.after_ms``). Any
``sleep`` reaching the virtual clock is a bug in the caller: code that
still needs a thread does not belong under the sim plane.

Time is integer milliseconds internally so two runs can never diverge
through float accumulation; ``monotonic()``/``time()`` convert at the
edge. ``time()`` is offset by a fixed epoch so code that formats wall
timestamps (Tick.now_ms consumers, log lines) sees plausible values —
the epoch is a constant, never ``time.time()``, or determinism dies.
"""

from __future__ import annotations

# fixed, arbitrary "wall" base: 2020-09-13T12:26:40Z
SIM_EPOCH_S = 1_600_000_000


class VirtualClock:
    __slots__ = ("now_ms",)

    def __init__(self) -> None:
        self.now_ms: int = 0

    # -- WallClock interface ------------------------------------------------

    def monotonic(self) -> float:
        return self.now_ms / 1000.0

    def monotonic_ns(self) -> int:
        return self.now_ms * 1_000_000

    def time(self) -> float:
        return SIM_EPOCH_S + self.now_ms / 1000.0

    def sleep(self, seconds: float) -> None:
        raise RuntimeError(
            "sleep() on the virtual clock: simulated code must schedule "
            "an event (SimScheduler.after_ms), never block a thread"
        )

    # -- simulation driver ----------------------------------------------------

    def advance_to(self, t_ms: int) -> None:
        if t_ms < self.now_ms:
            raise ValueError(f"time moved backwards: {t_ms} < {self.now_ms}")
        self.now_ms = t_ms
