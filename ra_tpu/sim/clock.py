"""Virtual clock: integer-millisecond simulated time.

Drop-in for ``ra_tpu.runtime.clock.WallClock`` behind the clock seam
(``ServerConfig.clock``, ``TimerService(clock=...)``): ``monotonic()``
and ``time()`` read simulated time, and ``sleep()`` REFUSES — in a
deterministic simulation nothing may block a real thread; waiting is
expressed by scheduling an event (``SimScheduler.after_ms``). Any
``sleep`` reaching the virtual clock is a bug in the caller: code that
still needs a thread does not belong under the sim plane.

Time is integer milliseconds internally so two runs can never diverge
through float accumulation; ``monotonic()``/``time()`` convert at the
edge. ``time()`` is offset by a fixed epoch so code that formats wall
timestamps (Tick.now_ms consumers, log lines) sees plausible values —
the epoch is a constant, never ``time.time()``, or determinism dies.
"""

from __future__ import annotations

# fixed, arbitrary "wall" base: 2020-09-13T12:26:40Z
SIM_EPOCH_S = 1_600_000_000


class VirtualClock:
    __slots__ = ("now_ms",)

    def __init__(self) -> None:
        self.now_ms: int = 0

    # -- WallClock interface ------------------------------------------------

    def monotonic(self) -> float:
        return self.now_ms / 1000.0

    def monotonic_ns(self) -> int:
        return self.now_ms * 1_000_000

    def time(self) -> float:
        return SIM_EPOCH_S + self.now_ms / 1000.0

    def sleep(self, seconds: float) -> None:
        raise RuntimeError(
            "sleep() on the virtual clock: simulated code must schedule "
            "an event (SimScheduler.after_ms), never block a thread"
        )

    # -- simulation driver ----------------------------------------------------

    def advance_to(self, t_ms: int) -> None:
        if t_ms < self.now_ms:
            raise ValueError(f"time moved backwards: {t_ms} < {self.now_ms}")
        self.now_ms = t_ms


class SkewedClock:
    """Per-node view over a shared :class:`VirtualClock` running at a
    slightly different RATE (``1 + rate``, e.g. ``rate=0.01`` is a
    clock 1% fast).

    Rate skew — not offset — is the honest adversary for clock-bound
    leases (docs/INTERNALS.md §20): a constant offset cancels out of
    every lease comparison (basis vs now on the leader's own clock,
    contact vs now on the follower's own clock), while rate skew makes
    one node's measured election-timeout window genuinely shorter or
    longer than another's. The lease ``drift_epsilon_s`` exists to
    absorb exactly this, so the sim draws each node's rate from the
    schedule seed (bounded by ``Schedule.skew_ppm``) and the lease
    config widens epsilon to cover the bound — a run that violates
    linearizability under covered skew is a real lease-math bug."""

    __slots__ = ("_base", "rate")

    def __init__(self, base: VirtualClock, rate: float) -> None:
        self._base = base
        self.rate = rate

    def monotonic(self) -> float:
        return (self._base.now_ms / 1000.0) * (1.0 + self.rate)

    def monotonic_ns(self) -> int:
        return int(self._base.now_ms * 1_000_000 * (1.0 + self.rate))

    def time(self) -> float:
        return SIM_EPOCH_S + (self._base.now_ms / 1000.0) * (1.0 + self.rate)

    def sleep(self, seconds: float) -> None:
        raise RuntimeError(
            "sleep() on the virtual clock: simulated code must schedule "
            "an event (SimScheduler.after_ms), never block a thread"
        )
