"""SimWorld: a whole cluster under one deterministic event loop.

FoundationDB-style simulation for the pure ``Server`` cores: every
concurrency source the threaded runtime has — actor mailboxes, timer
wheels, WAL completion threads, snapshot sender threads, the network —
is replaced by ONE seeded run queue over virtual time
(``SimScheduler``). The effect executor here mirrors
``runtime/proc.py``'s ``_execute`` decision-for-decision (append
front-enqueue order, leader-only machine timers, snapshot
backoff/retry, peer-disconnected marking), so a schedule that breaks an
invariant here is evidence against the same contracts the threaded
runtime runs — minus thread interleavings, plus total reproducibility:

    execution == f(Schedule)          (the determinism invariant, §19)

Safety oracles run continuously, on every replica at every applied
index, via a ``RecordingMachine`` wrapper: cross-replica state digests
(state-machine safety: equal states at equal index) plus the workload's
own invariant (``sim/workloads.py``). Violations are collected, never
raised, so a failing run still produces its full trace for the shrinker.

What is NOT simulated, by choice: the WAL/segment disk stack (logs are
``MemoryLog(auto_written=False)`` with write->written modeled as a
scheduled event), the failure detector (election timers re-arm on
leader contact instead — classic Raft, same safety envelope), and
crash-restarts are clean (pending write completions are flushed before
the rebuild; torn-write crashes stay with the disk-fault soak lane).
"""

from __future__ import annotations

import copy
import dataclasses
import hashlib
import pickle
import random
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Set, Tuple

from ra_tpu import counters as ra_counters
from ra_tpu import effects as fx
from ra_tpu.counters import SESSION_FIELDS, SIM_FIELDS
from ra_tpu.log.memory import MemoryLog
from ra_tpu.log.meta import InMemoryMeta
from ra_tpu.machine import Machine, normalize_apply_result
from ra_tpu.protocol import (
    CHUNK_INIT,
    CHUNK_LAST,
    CHUNK_PRE,
    USR,
    AppendEntriesRpc,
    Command,
    DownEvent,
    ElectionTimeout,
    FromPeer,
    HeartbeatRpc,
    InstallSnapshotAck,
    InstallSnapshotResult,
    InstallSnapshotRpc,
    LogEvent,
    ServerId,
    Tick,
)
from ra_tpu.server import (
    AWAIT_CONDITION,
    FOLLOWER,
    LEADER,
    RECEIVE_SNAPSHOT,
    ConditionTimeout,
    Server,
    ServerConfig,
    status_kind,
)
from ra_tpu.sim.clock import SkewedClock, VirtualClock
from ra_tpu.sim.scheduler import SimScheduler
from ra_tpu.sim.schedule import Schedule
from ra_tpu.sim.transport import SimNetwork
from ra_tpu.sim.workloads import invariant_for, make_machine


# Planted misclassification bug (docs/INTERNALS.md §21, exercised by
# tests/test_sim.py): treat a space-class write failure like a torn
# frame — poison the node and let "recovery" truncate the durable
# tail. Since every replica runs the same byte accounting over the
# same log, they all truncate the same committed (acked) entry, and
# the acked-writes-survive oracle fires deterministically.
SIM_BUG_SPACE_AS_POISON = False


def _fp(state: Any) -> str:
    """Stable state fingerprint. Pickle is deterministic here because
    the sim itself is: both runs build identical structures in
    identical insertion order."""
    return hashlib.sha1(pickle.dumps(state)).hexdigest()[:16]


class RecordingMachine(Machine):
    """Delegating wrapper that feeds every apply to the world's oracles
    (digest recording + workload invariant). ``which_module`` returns
    self so recording survives versioned dispatch."""

    def __init__(self, inner: Machine, world: "SimWorld", node_name: str):
        self.inner = inner
        self.world = world
        self.node_name = node_name

    def init(self, config):
        return self.inner.init(config)

    def apply(self, meta, cmd, state):
        st, reply, effs = normalize_apply_result(
            self.inner.apply(meta, cmd, state)
        )
        self.world.record_apply(self.node_name, meta["index"], cmd,
                                state, st, effs)
        return st, reply, effs

    def state_enter(self, role, state):
        return self.inner.state_enter(role, state)

    def tick(self, time_ms, state):
        return self.inner.tick(time_ms, state)

    def snapshot_installed(self, meta, state, old_meta, old_state):
        self.world.record_install(self.node_name, meta.index, state)
        return self.inner.snapshot_installed(meta, state, old_meta, old_state)

    def overview(self, state):
        return self.inner.overview(state)

    def live_indexes(self, state):
        return self.inner.live_indexes(state)

    def version(self):
        return self.inner.version()

    def which_module(self, version):
        return self

    def snapshot_module(self):
        return self.inner.snapshot_module()


class SimNode:
    """One cluster member: durable log+meta, a rebuildable ``Server``
    core, and the deterministic effect shell (the sim counterpart of
    ``ServerProc``)."""

    def __init__(self, world: "SimWorld", idx: int) -> None:
        self.world = world
        self.name = f"n{idx}"
        self.sid: ServerId = ("srv", self.name)
        # per-node clock view: rate-skewed when the schedule asks for
        # clock skew (the adversary the lease drift epsilon absorbs)
        rate = world.clock_rates.get(self.name, 0.0)
        self.clock = (
            SkewedClock(world.clock, rate) if rate else world.clock
        )
        # durable across crash-restarts (the actor backend restarts over
        # its WAL/meta the same way: runtime/node.py restart path)
        self.log = MemoryLog(auto_written=False)
        self.meta = InMemoryMeta()
        self.server: Optional[Server] = None
        self.running = False
        self.mailbox: deque = deque()  # (msg, )
        self._draining = False
        self.election_ref: Optional[int] = None
        self.condition_ref: Optional[int] = None
        self.tick_ref: Optional[int] = None
        self.machine_timers: Dict[Any, int] = {}
        self.snap_retry: Dict[ServerId, int] = {}
        self.senders: Dict[ServerId, Dict[str, Any]] = {}
        # disk-space model (schedule.disk_budget_bytes): deterministic
        # byte accounting over durable writes; exhausted writes park
        # until disk_heal (the sim storage_degraded episode)
        self.disk_used = 0
        self.space_degraded = False
        self._parked: List[Any] = []

    # -- lifecycle -----------------------------------------------------------

    def _build_server(self) -> None:
        w = self.world
        cfg = ServerConfig(
            server_id=self.sid,
            uid=f"uid_{self.name}",
            cluster_name="sim",
            machine=RecordingMachine(w.make_machine(self.name), w, self.name),
            initial_members=w.members,
            counters_enabled=False,
            check_quorum_window_s=w.check_quorum_s,
            clock=self.clock,
            # clock-bound leases (docs/INTERNALS.md §20): the server's
            # promise window must equal the sim's election timer base
            # (arm_election randomizes upward only), and the drift
            # epsilon is widened to cover the schedule's rate-skew
            # bound — with that covered, any stale consistent read the
            # kvread oracle sees is a genuine lease-math violation
            lease=w.lease,
            election_timeout_s=w.election_ms / 1000.0,
            lease_drift_epsilon_s=w.lease_drift_eps_s,
        )
        self.server = Server(cfg, self.log, self.meta)

    def start(self) -> None:
        self._build_server()
        self.running = True
        self.world.net.attach(self.name, self._net_deliver)
        self._schedule_tick()
        if self.server.is_voter_self():
            self.arm_election()

    def crash(self) -> None:
        w = self.world
        self.running = False
        self.mailbox.clear()
        w.net.detach(self.name)
        w.sched.cancel(self.election_ref)
        self.election_ref = None
        w.sched.cancel(self.condition_ref)
        self.condition_ref = None
        w.sched.cancel(self.tick_ref)
        self.tick_ref = None
        for ref in self.machine_timers.values():
            w.sched.cancel(ref)
        self.machine_timers.clear()
        for ref in self.snap_retry.values():
            w.sched.cancel(ref)
        self.snap_retry.clear()
        self.senders.clear()
        # leader-local runtime state (monitors) dies with the proc; the
        # machine's state_enter re-issues them on the next leader
        for watchers in w.monitors.values():
            watchers.discard(self.name)

    def boot(self) -> None:
        # clean-crash model: everything appended had its write
        # completion flushed before the rebuild (torn-tail crashes are
        # the disk-fault soak lane's job, not the sim's) — unless the
        # disk is exhausted, in which case those writes stay parked:
        # they were never durable and must not confirm across a reboot
        w0 = self.world
        for evt in self.log.pending_written_events():
            if w0.disk_budget and not SIM_BUG_SPACE_AS_POISON:
                cost = self._evt_bytes(evt)
                if (self.space_degraded
                        or self.disk_used + cost > w0.disk_budget):
                    self.space_degraded = True
                    self._parked.append(evt)
                    continue
                self.disk_used += cost
            self.log.handle_event(evt)
        self._build_server()
        self.server.recover()
        self.running = True
        w = self.world
        w.net.attach(self.name, self._net_deliver)
        w.trace("boot", w.clock.now_ms, self.name, self.server.role)
        self._schedule_tick()
        if self.server.role == FOLLOWER and self.server.is_voter_self():
            self.arm_election()

    # -- event sources ----------------------------------------------------------

    def _net_deliver(self, to: ServerId, msg: Any, from_sid: ServerId) -> None:
        self.post(FromPeer(from_sid, msg))

    def _schedule_tick(self) -> None:
        w = self.world
        if not self.running or w.clock.now_ms >= w.end_ms:
            return

        def fire() -> None:
            self.tick_ref = None
            if self.running:
                self.post(Tick(now_ms=int(w.clock.time() * 1000)))
                self._schedule_tick()

        self.tick_ref = w.sched.after_ms(w.tick_ms, fire)

    def arm_election(self, immediate: bool = False) -> None:
        w = self.world
        w.sched.cancel(self.election_ref)
        self.election_ref = None
        if not self.running or w.clock.now_ms >= w.end_ms:
            return
        delay = 0 if immediate else int(
            w.election_ms * (1.0 + w.rng.random())
        )

        def fire() -> None:
            self.election_ref = None
            if self.running:
                w.trace("etimo", w.clock.now_ms, self.name)
                self.post(ElectionTimeout())
                # a losing round leaves the role unchanged (a pre-vote
                # swallowed by a partition emits no state transition),
                # so the retry must be armed here; winning cancels it
                # via state_enter(LEADER)
                self.arm_election()

        self.election_ref = w.sched.after_ms(delay, fire)

    # -- mailbox -------------------------------------------------------------------

    def post(self, msg: Any, front: bool = False) -> None:
        if not self.running:
            return
        if front:
            self.mailbox.appendleft(msg)
        else:
            self.mailbox.append(msg)
        if not self._draining:
            self._drain()

    def _drain(self) -> None:
        self._draining = True
        try:
            while self.mailbox and self.running:
                msg = self.mailbox.popleft()
                self.world.count_step()
                self._execute(self._handle(msg))
            if self.running:
                self._flush_wal()
        finally:
            self._draining = False

    def _flush_wal(self) -> None:
        """Write->written as a scheduled event: durability has latency
        and is schedulable (and therefore reorderable) like everything
        else. Under a disk budget, writes that would exceed it fail
        space-class: parked (never confirmed) until disk_heal — the
        sim's storage_degraded episode."""
        w = self.world
        for evt in self.log.pending_written_events():
            if w.disk_budget:
                cost = self._evt_bytes(evt)
                if self.space_degraded or self.disk_used + cost > w.disk_budget:
                    if self._on_disk_full(evt) == "poisoned":
                        return  # log truncated: remaining evts are stale
                    continue
                self.disk_used += cost

            def deliver(evt=evt) -> None:
                if self.running:
                    w.trace("wal", w.clock.now_ms, self.name, evt[1],
                            str(evt[2]))
                    self.post(LogEvent(evt))

            w.sched.after_ms(w.wal_ms, deliver)

    def _evt_bytes(self, evt: Any) -> int:
        """Deterministic frame cost of one ("written", term, seq) batch:
        a fixed header plus the pickled command payload per entry —
        identical across replicas because replicated logs are."""
        cost = 0
        for idx in evt[2]:
            e = self.log.fetch(idx)
            if e is not None:
                cost += 32 + len(pickle.dumps(e.cmd))
        return cost

    def _on_disk_full(self, evt: Any) -> str:
        w = self.world
        if SIM_BUG_SPACE_AS_POISON:
            # the misclassification under test: ENOSPC handled like a
            # torn frame — poison-restart, and "recovery" truncates the
            # durable tail (discarding a committed, possibly acked,
            # entry). The clean path below provably never does this.
            last, _t = self.log.last_written()
            w.trace("disk_poison", w.clock.now_ms, self.name, last)
            if last > 0:
                self.log.set_last_index(last - 1)
            self.crash()
            self.boot()
            return "poisoned"
        if not self.space_degraded:
            self.space_degraded = True
            w.trace("disk_full", w.clock.now_ms, self.name, self.disk_used)
            w.ctr.incr("sim_disk_exhaustions")
        self._parked.append(evt)
        w.ctr.incr("sim_disk_parked_writes")
        return "parked"

    def disk_heal(self) -> None:
        """Operator freed space: exit degraded, confirm every parked
        write (stale ones — overwritten since — are filtered by the
        log's term check, exactly like late WAL notifications)."""
        w = self.world
        self.space_degraded = False
        self.disk_used = 0
        parked, self._parked = self._parked, []
        if parked:
            w.trace("disk_heal", w.clock.now_ms, self.name, len(parked))
        for evt in parked:
            def deliver(evt=evt) -> None:
                if self.running:
                    w.trace("wal", w.clock.now_ms, self.name, evt[1],
                            str(evt[2]))
                    self.post(LogEvent(evt))

            w.sched.after_ms(w.wal_ms, deliver)

    # -- message routing (the sim ServerProc._on_batch) -----------------------------

    def _handle(self, msg: Any) -> List[fx.Effect]:
        server = self.server
        if isinstance(msg, FromPeer):
            inner = msg.msg
            # mid-transfer chunk acks/results are sender-plane traffic,
            # consumed by the active sender, not the consensus core
            if isinstance(inner, InstallSnapshotAck) and msg.peer in self.senders:
                self._sender_ack(msg.peer, inner)
                return []
            if (
                isinstance(inner, InstallSnapshotResult)
                and msg.peer in self.senders
            ):
                self.senders.pop(msg.peer, None)
                return server.handle(inner, from_peer=msg.peer)
            if isinstance(inner, InstallSnapshotAck):
                return []  # stale ack, no transfer in progress
            self._note_contact(msg)
            return server.handle(msg)
        if isinstance(msg, tuple) and msg and msg[0] == "__snap_fail__":
            _, to = msg
            if self.senders.pop(to, None) is None:
                return []
            return server.handle(("snapshot_sender_down", to, "failed"))
        if isinstance(msg, Tick) and server.role == LEADER:
            # reconnect probing (proc.py does the same per tick): peers
            # marked disconnected by refused sends retry once reachable
            for sid, p in server.peers().items():
                if p.status == "disconnected" and self.world.net.proc_alive(sid):
                    p.status = "normal"
        return server.handle(msg)

    def _note_contact(self, msg: FromPeer) -> None:
        """Leader contact postpones the election timer (classic Raft
        re-arm; the threaded runtime cancels and leans on its failure
        detector instead — same safety envelope, no detector thread).
        Stale traffic from a dead sender is not liveness evidence."""
        if not isinstance(
            msg.msg, (AppendEntriesRpc, InstallSnapshotRpc, HeartbeatRpc)
        ):
            return
        if self.server.role in (
            FOLLOWER, AWAIT_CONDITION, RECEIVE_SNAPSHOT
        ) and self.world.net.proc_alive(msg.peer):
            self.arm_election()

    # -- effect executor (mirrors ServerProc._execute) ---------------------------------

    def _execute(self, effects: List[fx.Effect]) -> None:
        w = self.world
        server = self.server
        appends: List[Command] = []
        for eff in effects:
            if isinstance(eff, fx.SendRpc):
                ok = w.net.send(self.sid, eff.to, eff.msg)
                if not ok:
                    peer = server.cluster.get(eff.to)
                    if peer is not None and peer.status == "normal":
                        peer.status = "disconnected"
            elif isinstance(eff, fx.SendVoteRequests):
                for to, rpc in eff.requests:
                    w.net.send(self.sid, to, rpc)
            elif isinstance(eff, fx.NextEvent):
                self.post(eff.msg, front=True)
            elif isinstance(eff, fx.Reply):
                w.record_reply(eff.from_ref, eff.reply)
            elif isinstance(eff, fx.Notify):
                w.notifications.append((eff.who, self.sid, list(eff.correlations)))
            elif isinstance(eff, fx.SendMsg):
                w.client_msgs.append((self.name, eff.to, eff.msg))
            elif isinstance(eff, fx.RecordLeader):
                w.leaderboard[eff.cluster_name] = (eff.leader, eff.members)
            elif isinstance(eff, fx.SendSnapshot):
                self._start_snapshot_sender(eff.to)
            elif isinstance(eff, fx.StateEnter):
                self._on_state_enter(eff.role)
            elif isinstance(eff, fx.StopServer):
                w.trace("stop", w.clock.now_ms, self.name)
                self.crash()
            elif isinstance(eff, fx.StartSnapshotRetryTimer):
                self._arm_snap_retry(eff.to, eff.delay_ms)
            elif isinstance(eff, fx.Timer):
                self._machine_timer(eff)
            elif isinstance(eff, fx.ModCall):
                try:
                    eff.fn(*eff.args)
                except Exception:  # noqa: BLE001
                    pass
            elif isinstance(eff, fx.BgWork):
                # background work runs inline: determinism over fidelity
                try:
                    eff.fn()
                except Exception as e:  # noqa: BLE001
                    if eff.err_fn is not None:
                        eff.err_fn(e)
            elif isinstance(eff, fx.Monitor):
                w.monitors.setdefault((eff.kind, eff.target), set()).add(self.name)
            elif isinstance(eff, fx.Demonitor):
                watchers = w.monitors.get((eff.kind, eff.target))
                if watchers is not None:
                    watchers.discard(self.name)
            elif isinstance(eff, fx.LogRead):
                entries = server.log.sparse_read(list(eff.indexes))
                out = eff.fn(entries)
                if out is not None:
                    self.post(out)
            elif isinstance(eff, fx.Aux):
                self.post(("aux", "cast", eff.cmd, None))
            elif isinstance(eff, fx.Append):
                if server.role == LEADER:
                    appends.append(Command(
                        kind=USR, data=eff.cmd, reply_mode=eff.reply_mode,
                        from_ref=eff.from_ref, internal=True,
                    ))
            elif isinstance(eff, fx.TryAppend):
                appends.append(Command(
                    kind=USR, data=eff.cmd, reply_mode=eff.reply_mode,
                    from_ref=(
                        eff.from_ref if server.role == LEADER else None
                    ),
                    internal=True,
                ))
        for cmd in reversed(appends):
            self.post(cmd, front=True)

    def _on_state_enter(self, role: str) -> None:
        w = self.world
        w.trace("state", w.clock.now_ms, self.name, role,
                self.server.current_term)
        if role != AWAIT_CONDITION and self.condition_ref is not None:
            w.sched.cancel(self.condition_ref)
            self.condition_ref = None
        if role == LEADER:
            w.sched.cancel(self.election_ref)
            self.election_ref = None
        else:
            # follower/pre_vote/candidate/await_condition/receive_
            # snapshot all keep an election pending; a live leader's
            # traffic re-arms it before it fires
            self.arm_election()
        if role == AWAIT_CONDITION:
            # the hold must expire even when the condition's trigger is
            # lost to the network (proc.py arms the same timer): the
            # generation-tagged ConditionTimeout runs the Condition's
            # timeout path — repeated catch-up reply, fall back to
            # follower — instead of wedging until the end of time
            cond = self.server.condition
            dur_ms = w.cond_timeout_ms
            if cond is not None and cond.timeout_duration_ms is not None:
                dur_ms = cond.timeout_duration_ms
            gen = self.server.condition_generation
            w.sched.cancel(self.condition_ref)
            self.condition_ref = None
            if self.running and w.clock.now_ms < w.end_ms:

                def fire(gen: int = gen) -> None:
                    self.condition_ref = None
                    if self.running:
                        w.trace("ctimo", w.clock.now_ms, self.name, gen)
                        self.post(ConditionTimeout(generation=gen))

                self.condition_ref = w.sched.after_ms(dur_ms, fire)

    # -- machine timers --------------------------------------------------------------

    def _machine_timer(self, eff: fx.Timer) -> None:
        w = self.world
        old = self.machine_timers.pop(eff.name, None)
        w.sched.cancel(old)
        if eff.ms is None:
            return

        def fire() -> None:
            self.machine_timers.pop(eff.name, None)
            if self.running and self.server.role == LEADER:
                w.trace("mtimer", w.clock.now_ms, self.name, repr(eff.name))
                self.post(Command(kind=USR, data=("timeout", eff.name),
                                  internal=True))

        self.machine_timers[eff.name] = w.sched.after_ms(int(eff.ms), fire)

    # -- snapshot transfer (the sim SnapshotSender) ------------------------------------

    def _start_snapshot_sender(self, to: ServerId) -> None:
        w = self.world
        if to in self.senders:
            return
        w.sched.cancel(self.snap_retry.pop(to, None))
        peer = self.server.cluster.get(to)
        if peer is not None and status_kind(peer.status) == "snapshot_backoff":
            peer.status = ("sending_snapshot", peer.status[1])
        got = self.server.log.read_snapshot()
        if got is None:
            if peer is not None and status_kind(peer.status) == "sending_snapshot":
                peer.status = "normal"
            return
        meta, state = got
        live = (
            self.server.log.sparse_read(list(meta.live_indexes))
            if meta.live_indexes
            else []
        )
        # stop-and-wait chunk plan: INIT (acked) -> optional PRE with
        # sparse live entries (acked) -> LAST carrying the state as one
        # direct-object chunk (answered by InstallSnapshotResult).
        # deepcopy mirrors the pickle round-trip of the real sender —
        # receiver state must never alias the sender's.
        chunks: List[Tuple[int, str, Any]] = [(0, CHUNK_INIT, b"")]
        no = 1
        if live:
            chunks.append((no, CHUNK_PRE, live))
            no += 1
        chunks.append((no, CHUNK_LAST, copy.deepcopy(state)))
        sender = {
            "to": to, "meta": meta, "chunks": chunks, "i": 0,
            "term": self.server.current_term, "gen": 0,
        }
        self.senders[to] = sender
        w.trace("snap", w.clock.now_ms, self.name, to[1], meta.index)
        self._send_chunk(sender)

    def _send_chunk(self, sender: Dict[str, Any]) -> None:
        w = self.world
        to = sender["to"]
        no, phase, data = sender["chunks"][sender["i"]]
        w.net.send(self.sid, to, InstallSnapshotRpc(
            term=sender["term"], leader_id=self.server.id,
            meta=sender["meta"], chunk_no=no, chunk_phase=phase, data=data,
        ))
        sender["gen"] += 1
        gen = sender["gen"]

        def watchdog() -> None:
            s = self.senders.get(to)
            if self.running and s is sender and s["gen"] == gen:
                # no ack/result within the window: dropped chunk or
                # blocked return path — fail into backoff+retry
                self.post(("__snap_fail__", to))

        w.sched.after_ms(w.snap_ack_timeout_ms, watchdog)

    def _sender_ack(self, peer: ServerId, ack: InstallSnapshotAck) -> None:
        sender = self.senders[peer]
        no, _phase, _data = sender["chunks"][sender["i"]]
        if ack.chunk_no < no:
            return  # duplicate ack of an older chunk
        sender["i"] += 1
        if sender["i"] < len(sender["chunks"]):
            self._send_chunk(sender)
        # else: LAST is in flight; its watchdog covers the result

    def _arm_snap_retry(self, to: ServerId, delay_ms: int) -> None:
        w = self.world
        w.sched.cancel(self.snap_retry.pop(to, None))

        def fire() -> None:
            self.snap_retry.pop(to, None)
            if self.running:
                self.post(("snapshot_retry_timeout", to))

        self.snap_retry[to] = w.sched.after_ms(int(delay_ms), fire)


@dataclasses.dataclass
class SimResult:
    schedule: Schedule  # ops materialized: replayable as-is
    violations: List[str]
    trace_text: str
    final: Dict[str, Tuple[int, str]]  # node -> (last_applied, state fp)
    steps: int
    virtual_ms: int
    replies: Dict[int, List[Any]]
    client_msgs: List[Tuple[str, Any, Any]]

    @property
    def ok(self) -> bool:
        return not self.violations


class SimWorld:
    # timing model (virtual ms). Constants, not config: schedules must
    # stay comparable across runs and sessions.
    tick_ms = 60
    election_ms = 150  # base; arm() randomizes to [1x, 2x)
    wal_ms = 1
    snap_ack_timeout_ms = 400
    cond_timeout_ms = 500  # default await_condition hold (proc.py: 30s)
    check_quorum_s = 0.9
    MAX_STEPS = 5_000_000

    def __init__(self, sched_in: Schedule) -> None:
        self.schedule_in = sched_in
        self.clock = VirtualClock()
        self.sched = SimScheduler(self.clock)
        # election-jitter stream, decorrelated from net/ops/nemesis
        self.rng = random.Random((sched_in.seed << 2) ^ 0x454C45)  # "ELE"
        self.end_ms = sched_in.horizon_ms + sched_in.settle_ms
        self.members = tuple(
            ("srv", f"n{i}") for i in range(sched_in.nodes)
        )
        self.ctr = ra_counters.registry().new(("sim", "plane"), SIM_FIELDS)
        # lease plane (docs/INTERNALS.md §20): per-node clock RATE skew
        # drawn from its own seed stream, bounded by the schedule; the
        # drift epsilon covers 2x the bound over both promise windows
        self.lease = sched_in.lease
        skew = sched_in.skew_ppm * 1e-6
        skew_rng = random.Random((sched_in.seed << 3) ^ 0x534B57)  # "SKW"
        self.clock_rates = {
            f"n{i}": (skew_rng.uniform(-skew, skew) if skew else 0.0)
            for i in range(sched_in.nodes)
        }
        self.lease_drift_eps_s = 0.002 + 4.0 * skew * (self.election_ms / 1000.0)
        # kvread stale-read oracle state: acked write floor (raft index
        # of the highest acked put), per-read floors at invocation, and
        # which client refs were seq writes
        self._acked_floor = -1
        self._read_floor: Dict[int, int] = {}
        self._seq_write_refs: Set[int] = set()
        # acked-writes-survive oracle (§21): raft index -> state fp at
        # the apply that was acked; any later apply at that index with
        # a different fp means a confirmed write was destroyed
        self._acked_fp: Dict[int, str] = {}
        self.disk_budget = sched_in.disk_budget_bytes
        self._old_leader: Optional[str] = None
        self._session_ctr = (
            ra_counters.registry().new(("session", "sim"), SESSION_FIELDS)
            if sched_in.workload == "session"
            else None
        )
        self.invariant = invariant_for(sched_in.workload)
        self.inv_tracker: Dict[str, Dict[str, Any]] = {}
        self._checked_to: Dict[str, int] = {}  # node -> highest oracle-checked index
        self.trace_lines: List[str] = []
        self.violations: List[str] = []
        self.replies: Dict[int, List[Any]] = {}
        self.notifications: List[Any] = []
        self.client_msgs: List[Tuple[str, Any, Any]] = []
        self.monitors: Dict[Tuple[str, Any], Set[str]] = {}
        self.leaderboard: Dict[str, Any] = {}
        self.digests: Dict[str, Dict[int, str]] = {}
        self.steps = 0
        self._op_i = 0
        self.net = SimNetwork(
            self.sched, sched_in.seed,
            drop_p=sched_in.drop_p, dup_p=sched_in.dup_p,
            delay_p=sched_in.delay_p, delay_ms_max=sched_in.delay_ms_max,
            ctr=self.ctr, trace=self._trace_net,
        )
        self.nodes: Dict[str, SimNode] = {}
        for i in range(sched_in.nodes):
            node = SimNode(self, i)
            self.nodes[node.name] = node
            self.digests[node.name] = {}
        self.planner = None
        self._nem_seen = 0
        if sched_in.nemesis:
            from ra_tpu.nemesis import (
                NemesisContext,
                Planner,
                standard_dimensions,
            )

            ctx = NemesisContext(
                peers=lambda: list(self.nodes),
                members=lambda: list(self.nodes),
                block=self.net.block,
                unblock_all=self.net.unblock_all,
                restart=self.restart,
            )
            self.planner = Planner(
                ctx, sched_in.seed, "sim",
                standard_dimensions(partitions=True, oneway=True,
                                    restarts=True),
            )

    # -- factories -------------------------------------------------------------

    def make_machine(self, node_name: str):
        # the counter-carrying instance lives on n0 only: apply runs on
        # every replica, a shared vector would count everything x nodes
        ctr = self._session_ctr if node_name == "n0" else None
        return make_machine(self.schedule_in.workload, ctr=ctr)

    # -- tracing / recording ----------------------------------------------------

    def trace(self, *fields: Any) -> None:
        self.trace_lines.append(" ".join(str(f) for f in fields))

    def _trace_net(self, kind: str, seq: int, frm: str, to: str,
                   msgkind: str, *extra: Any) -> None:
        self.trace("net", self.clock.now_ms, kind, f"#{seq}",
                   f"{frm}->{to}", msgkind, *extra)

    def count_step(self) -> None:
        self.steps += 1
        if self.steps > self.MAX_STEPS:
            raise RuntimeError("sim storm: step budget exhausted")

    def violation(self, msg: str) -> None:
        if len(self.violations) < 32:
            self.violations.append(msg)

    def record_apply(self, node_name: str, index: int, cmd: Any,
                     pre: Any, post: Any, effs: Any) -> None:
        fp = _fp(post)
        self.trace("apply", self.clock.now_ms, node_name, index, fp[:8])
        want = self._acked_fp.get(index)
        if want is not None and fp != want:
            self.violation(
                f"acked write lost: index {index} re-applied on "
                f"{node_name} as {fp}, acked state was {want}"
            )
        mine = self.digests[node_name]
        prev = mine.get(index)
        if prev is not None and prev != fp:
            # a restart replays the log from the snapshot; a
            # deterministic machine must land on the identical state
            self.violation(
                f"replay divergence on {node_name} at index {index}: "
                f"{prev} -> {fp}"
            )
        mine[index] = fp
        # state-machine safety, checked at the earliest possible moment:
        # two replicas that applied the same index must hold equal state
        for other, d in self.digests.items():
            if other != node_name and d.get(index, fp) != fp:
                self.violation(
                    f"state divergence at index {index}: "
                    f"{node_name}={fp} vs {other}={d[index]}"
                )
        # replayed indexes (crash-restart re-applying below the old
        # last_applied) were already oracle-checked on first apply; the
        # stateful invariant trackers (e.g. fencing-token high-water)
        # must not see the history twice
        if index <= self._checked_to.get(node_name, 0):
            return
        self._checked_to[node_name] = index
        if self.invariant is not None:
            tracker = self.inv_tracker.setdefault(node_name, {})
            msg = self.invariant(cmd, pre, post, effs, tracker)
            if msg:
                self.violation(f"[{node_name} @idx {index}] {msg}")

    def record_install(self, node_name: str, index: int, state: Any) -> None:
        fp = _fp(state)
        self.trace("install", self.clock.now_ms, node_name, index, fp[:8])
        self.digests[node_name][index] = fp
        for other, d in self.digests.items():
            if other != node_name and d.get(index, fp) != fp:
                self.violation(
                    f"snapshot/state divergence at index {index}: "
                    f"{node_name}={fp} vs {other}={d[index]}"
                )

    def record_reply(self, from_ref: Any, reply: Any) -> None:
        if not (isinstance(from_ref, tuple) and len(from_ref) == 2):
            return
        kind, i = from_ref
        if kind == "cli":
            self.replies.setdefault(i, []).append(reply)
            if (i in self._seq_write_refs
                    and isinstance(reply, tuple) and reply
                    and reply[0] == "ok"):
                # KvMachine's put reply carries the applied raft index:
                # the monotone sequence the read oracle floors against
                idx = reply[1][1] if isinstance(reply[1], tuple) else -1
                if idx > self._acked_floor:
                    self._acked_floor = idx
                if idx >= 0 and idx not in self._acked_fp:
                    for d in self.digests.values():
                        if idx in d:
                            self._acked_fp[idx] = d[idx]
                            break
        elif kind == "rd":
            self.replies.setdefault(i, []).append(reply)
            floor = self._read_floor.pop(i, None)
            if (floor is None or not isinstance(reply, tuple) or not reply
                    or reply[0] != "ok"):
                return  # redirects/timeouts carry no linearizability claim
            self.trace("readok", self.clock.now_ms, i, reply[1], floor)
            if reply[1] < floor:
                # the lease's whole claim: a consistent read invoked
                # after a write was acked must observe it
                self.violation(
                    f"stale consistent read rd/{i}: observed seq index "
                    f"{reply[1]} < acked floor {floor} at invocation"
                )

    # -- nemesis callbacks ---------------------------------------------------------

    def restart(self, node_name: str) -> None:
        node = self.nodes[node_name]
        self.trace("restart", self.clock.now_ms, node_name)
        node.crash()
        node.boot()

    # -- op injection ------------------------------------------------------------------

    def current_leader(self) -> Optional[SimNode]:
        best = None
        for name in sorted(self.nodes):
            node = self.nodes[name]
            if node.running and node.server.role == LEADER:
                if best is None or node.server.current_term > best.server.current_term:
                    best = node
        return best

    def _inject(self, t_ms: int, op: Tuple[Any, ...]) -> None:
        kind = op[0]
        if kind == "cmd":
            self._op_i += 1
            i = self._op_i
            target = self.current_leader()
            if target is None:
                for name in sorted(self.nodes):
                    if self.nodes[name].running:
                        target = self.nodes[name]
                        break
            if target is None:
                return
            if (isinstance(op[1], tuple) and len(op[1]) >= 2
                    and op[1][0] == "put" and op[1][1] == "seq"):
                self._seq_write_refs.add(i)
            self.trace("cmd", t_ms, i, target.name, repr(op[1]))
            target.post(Command(kind=USR, data=op[1],
                                reply_mode="await_consensus",
                                from_ref=("cli", i)))
        elif kind == "read":
            # consistent read (docs/INTERNALS.md §20). Targets a node
            # directly — including non-leaders, which drop it — so a
            # deposed leader still inside its lease window answers and
            # is held to the acked-write floor captured right here.
            tgt = op[1]
            if tgt == "leader":
                node = self.current_leader()
            elif tgt == "old":
                node = self.nodes.get(self._old_leader or "")
            else:
                node = self.nodes.get(f"n{int(tgt) % len(self.nodes)}")
            if node is None or not node.running:
                return
            self._op_i += 1
            i = self._op_i
            self._read_floor[i] = self._acked_floor
            self.trace("read", t_ms, i, node.name, self._acked_floor)
            from ra_tpu.sim.workloads import read_seq_index

            node.post(("consistent_query", read_seq_index, ("rd", i)))
        elif kind == "isolate" and op[1] == "leader":
            target = self.current_leader()
            if target is None:
                return
            self._old_leader = target.name
            for other in self.nodes:
                if other != target.name:
                    self.net.block(target.name, other)
                    self.net.block(other, target.name)
            self.trace("isolate", t_ms, target.name)
        elif kind == "etimo":
            # deterministic election trigger: the first running voter
            # that is not the old leader campaigns NOW (the server's
            # own stickiness standing guard still applies)
            for name in sorted(self.nodes):
                if name != self._old_leader and self.nodes[name].running:
                    self.trace("etimo_op", t_ms, name)
                    self.nodes[name].post(ElectionTimeout())
                    break
        elif kind == "unblock":
            self.trace("unblock", t_ms)
            self.net.unblock_all()
        elif kind == "down":
            target = op[1]
            watchers = sorted(self.monitors.get(("process", target), ()))
            self.trace("cdown", t_ms, target, ",".join(watchers) or "-")
            for w in watchers:
                node = self.nodes[w]
                if node.running:
                    node.post(DownEvent(target, "sim_down"))
        elif kind == "nem" and self.planner is not None:
            self.planner.step(op[1])
            sched = self.planner.schedule
            while self._nem_seen < len(sched):
                op_i, name, verb, detail = sched[self._nem_seen]
                self._nem_seen += 1
                self.trace("nem", t_ms, op_i, name, verb, detail)

    def _heal(self) -> None:
        self.trace("heal", self.clock.now_ms)
        if self.planner is not None:
            self.planner.heal_all("horizon")
            sched = self.planner.schedule
            while self._nem_seen < len(sched):
                op_i, name, verb, detail = sched[self._nem_seen]
                self._nem_seen += 1
                self.trace("nem", self.clock.now_ms, op_i, name, verb, detail)
        self.net.unblock_all()
        for name in sorted(self.nodes):
            if not self.nodes[name].running:
                self.nodes[name].boot()
        for name in sorted(self.nodes):
            node = self.nodes[name]
            if node.space_degraded or node._parked:
                node.disk_heal()

    # -- run ---------------------------------------------------------------------------

    def run(self) -> SimResult:
        sched_in = self.schedule_in
        ops = sched_in.resolve_ops()
        for t_ms, op in ops:
            self.sched.after_ms(t_ms, lambda t=t_ms, op=op: self._inject(t, op))
        # at the horizon every fault heals and crashed nodes reboot; the
        # settle window is for convergence (elections, snapshot
        # catch-up, lease expiries)
        self.sched.after_ms(sched_in.horizon_ms, self._heal)
        for name in sorted(self.nodes):
            self.nodes[name].start()
        while self.sched.run_next():
            pass
        self.ctr.incr("sim_schedules_run")
        if self.violations:
            self.ctr.incr("sim_schedules_failed")
        self.ctr.incr("sim_steps_executed", self.steps)
        self.ctr.incr("sim_virtual_ms", self.clock.now_ms)
        # acked-writes-survive oracle (§21), end-of-run form: after the
        # horizon heal + settle, every surviving replica's state must
        # reflect the highest acked seq write. A space failure handled
        # as poison truncates the durable tail on every replica (same
        # byte accounting, same log), and the acked index silently
        # vanishes — invisible to the per-apply oracles because meta's
        # last_applied stays above the truncated entry, so nothing ever
        # re-applies at that index.
        if self.disk_budget and self._acked_floor >= 0:
            for name in sorted(self.nodes):
                node = self.nodes[name]
                if not node.running:
                    continue
                st = node.server.machine_state
                got = st.get("seq") if isinstance(st, dict) else None
                at = got[0] if got else -1
                if at < self._acked_floor:
                    self.violation(
                        f"acked write lost on {name}: seq last written at "
                        f"index {at} < acked floor {self._acked_floor}"
                    )
        final = {
            name: (node.server.last_applied, _fp(node.server.machine_state))
            for name, node in self.nodes.items()
            if node.running
        }
        for name in sorted(final):
            self.trace("final", name, final[name][0], final[name][1])
        return SimResult(
            schedule=sched_in.with_ops(ops),
            violations=list(self.violations),
            trace_text="\n".join(self.trace_lines) + "\n",
            final=final,
            steps=self.steps,
            virtual_ms=self.clock.now_ms,
            replies=dict(self.replies),
            client_msgs=list(self.client_msgs),
        )


def run_schedule(sched: Schedule) -> SimResult:
    """Run one schedule to completion under a fresh world."""
    return SimWorld(sched).run()
