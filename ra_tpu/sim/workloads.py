"""Sim workloads: op generators, machine factories, and oracles.

One entry per workload kind ("kv" | "fifo" | "session"). Each supplies:

- ``generate_ops(schedule)`` — the seeded external timeline (client
  commands, client downs, nemesis steps), a pure function of the
  schedule so a run replays from the schedule alone;
- a machine factory (small snapshot intervals so release cursors, log
  truncation, and therefore real snapshot transfers happen inside a
  ten-virtual-second run);
- a per-apply invariant — the workload's safety oracle, checked on
  EVERY replica at EVERY applied index by the world's recording
  wrapper. Invariants are written against what correct code can
  legitimately do, not against incidental behaviour:

  * fifo: a consumer-down requeue batch must redeliver in ascending
    msg_id order — counting both same-apply deliveries to other ready
    consumers and what stays parked at the queue head (the
    reversed-requeue failpoint violates exactly this, and a
    multi-consumer interleaving of CORRECT downs does not);
  * session: lock safety — every lock owner is a live session, fencing
    tokens per key strictly increase, and a session leaves the state
    only via its own close or an attributable expiry (a ``down``
    builtin or a matching-generation ``timeout``);
  * kv: no per-apply invariant; the cross-replica digest check in the
    world (state-machine safety: equal states at equal applied index)
    carries it.
"""

from __future__ import annotations

import random
from typing import Any, Callable, Dict, List, Optional, Tuple

from ra_tpu.effects import SendMsg
from ra_tpu.models.fifo import FifoMachine
from ra_tpu.models.kv import KvMachine
from ra_tpu.models.session import SessionMachine

WORKLOADS = ("kv", "fifo", "session")

_KV_KEYS = 8
_FIFO_CONSUMERS = ("c0", "c1", "c2")
_SESSIONS = ("s0", "s1", "s2", "s3")
_LOCK_KEYS = ("lk0", "lk1", "lk2")


def make_machine(workload: str, ctr=None):
    """Machine factory for one replica. ``ctr`` (SESSION_FIELDS) goes to
    exactly one replica's machine — apply runs on every replica, so a
    shared vector would multiply every count by the cluster size."""
    if workload == "kv":
        return KvMachine(snapshot_interval=24)
    if workload == "fifo":
        return FifoMachine()
    if workload == "session":
        return SessionMachine(ctr=ctr)
    raise ValueError(f"unknown workload {workload!r}")


# -- op generation -------------------------------------------------------------


def generate_ops(sched) -> List[Tuple[int, Tuple[Any, ...]]]:
    rng = random.Random((sched.seed << 4) ^ 0x4F5053)  # "OPS"
    gen = {
        "kv": _gen_kv,
        "fifo": _gen_fifo,
        "session": _gen_session,
    }[sched.workload]
    ops: List[Tuple[int, Tuple[Any, ...]]] = []
    # ops spread across the horizon with jittered gaps; the settle
    # window after the horizon is op-free so the cluster can quiesce
    t = 0
    gap = max(2, (2 * sched.horizon_ms) // max(1, sched.n_ops))
    for i in range(sched.n_ops):
        t += 1 + rng.randrange(gap)
        if t >= sched.horizon_ms:
            break
        ops.append((t, gen(rng, i)))
    if sched.nemesis:
        k = 0
        for t in range(300, sched.horizon_ms, 400):
            ops.append((t, ("nem", k)))
            k += 1
    ops.sort(key=lambda p: p[0])
    return ops


def _gen_kv(rng: random.Random, i: int) -> Tuple[Any, ...]:
    r = rng.random()
    key = f"k{rng.randrange(_KV_KEYS)}"
    if r < 0.75:
        return ("cmd", ("put", key, i))
    if r < 0.9:
        return ("cmd", ("delete", key))
    return ("cmd", ("keys",))


def _gen_fifo(rng: random.Random, i: int) -> Tuple[Any, ...]:
    r = rng.random()
    cid = rng.choice(_FIFO_CONSUMERS)
    if r < 0.5:
        return ("cmd", ("enqueue", f"m{i}"))
    if r < 0.72:
        return ("cmd", ("checkout", cid, 1 + rng.randrange(3)))
    if r < 0.9:
        # settle a plausible id; settling a non-inflight id is a no-op
        return ("cmd", ("settle", cid, 1 + rng.randrange(max(i, 1))))
    return ("down", cid)


def _gen_session(rng: random.Random, i: int) -> Tuple[Any, ...]:
    r = rng.random()
    sid = rng.choice(_SESSIONS)
    key = rng.choice(_LOCK_KEYS)
    if r < 0.25:
        return ("cmd", ("session_open", sid, 200 + rng.randrange(1200)))
    if r < 0.4:
        return ("cmd", ("session_renew", sid))
    if r < 0.48:
        return ("cmd", ("session_close", sid))
    if r < 0.68:
        return ("cmd", ("lock_acquire", sid, key))
    if r < 0.78:
        return ("cmd", ("lock_acquire", sid, key, "steal"))
    if r < 0.9:
        return ("cmd", ("lock_release", sid, key))
    return ("down", sid)


# -- per-apply invariants (the workload oracles) --------------------------------


def invariant_for(workload: str) -> Optional[Callable]:
    return {
        "kv": None,
        "fifo": _fifo_invariant,
        "session": _session_invariant,
    }[workload]


def _fifo_invariant(cmd, pre, post, effs,
                    tracker: Dict[str, Any]) -> Optional[str]:
    if isinstance(cmd, tuple) and cmd and cmd[0] in ("down", "cancel"):
        cid = cmd[1]
        batch = sorted((pre.consumers.get(cid) or {}).keys())
        if len(batch) >= 2:
            # the requeued batch lands at the queue FRONT, and _service
            # may hand part (or all) of it to other ready consumers
            # within the same apply — walking the queue front in order.
            # So the observable redelivery order is: batch members among
            # this apply's delivery effects (in effect order), then the
            # batch members still parked at the queue head. Correct code
            # makes that concatenation exactly the ascending batch; the
            # reversed-requeue failpoint cannot.
            batch_set = set(batch)
            delivered = [
                e.msg[1] for e in effs
                if isinstance(e, SendMsg) and e.msg
                and e.msg[0] == "delivery" and e.msg[1] in batch_set
            ]
            head = []
            for mid, _m in post.queue:
                if mid not in batch_set:
                    break
                head.append(mid)
            if delivered + head != batch:
                return (
                    f"requeue order violated: consumer {cid} went down "
                    f"holding {batch}, redelivery order {delivered + head}"
                )
    return None


def _session_invariant(cmd, pre, post, effs,
                       tracker: Dict[str, Any]) -> Optional[str]:
    # 1. lock safety: every holder is a live session
    for key, (owner, token) in post.locks.items():
        if owner not in post.sessions:
            return f"lock {key} held by dead session {owner} (token {token})"
    # 2. fencing tokens strictly increase per key across grants
    last: Dict[Any, int] = tracker.setdefault("tokens", {})
    for key, (owner, token) in post.locks.items():
        prev = last.get(key)
        if prev is not None and token < prev:
            return f"fencing token regressed on {key}: {prev} -> {token}"
        last[key] = max(token, prev or 0)
    # 3. every expiry attributable: sessions leave only via their own
    #    close, a down builtin, or a matching-generation ttl timeout
    gone = set(pre.sessions) - set(post.sessions)
    if gone:
        op = cmd[0] if isinstance(cmd, tuple) and cmd else None
        if op not in ("session_close", "down", "timeout"):
            return f"sessions {sorted(gone)} vanished on {op!r} command"
        if op == "timeout":
            name = cmd[1]
            sid, gen = name[1], name[2]
            if gone != {sid} or pre.sessions[sid].gen != gen:
                return (
                    f"timeout {name!r} expired {sorted(gone)} "
                    f"(gen mismatch or wrong session)"
                )
    return None
