"""Sim workloads: op generators, machine factories, and oracles.

One entry per workload kind ("kv" | "fifo" | "session"). Each supplies:

- ``generate_ops(schedule)`` — the seeded external timeline (client
  commands, client downs, nemesis steps), a pure function of the
  schedule so a run replays from the schedule alone;
- a machine factory (small snapshot intervals so release cursors, log
  truncation, and therefore real snapshot transfers happen inside a
  ten-virtual-second run);
- a per-apply invariant — the workload's safety oracle, checked on
  EVERY replica at EVERY applied index by the world's recording
  wrapper. Invariants are written against what correct code can
  legitimately do, not against incidental behaviour:

  * fifo: a consumer-down requeue batch must redeliver in ascending
    msg_id order — counting both same-apply deliveries to other ready
    consumers and what stays parked at the queue head (the
    reversed-requeue failpoint violates exactly this, and a
    multi-consumer interleaving of CORRECT downs does not);
  * session: lock safety — every lock owner is a live session, fencing
    tokens per key strictly increase, and a session leaves the state
    only via its own close or an attributable expiry (a ``down``
    builtin or a matching-generation ``timeout``);
  * kv: no per-apply invariant; the cross-replica digest check in the
    world (state-machine safety: equal states at equal applied index)
    carries it.
"""

from __future__ import annotations

import random
from typing import Any, Callable, Dict, List, Optional, Tuple

from ra_tpu.effects import SendMsg
from ra_tpu.models.fifo import FifoMachine
from ra_tpu.models.kv import KvMachine
from ra_tpu.models.session import SessionMachine

WORKLOADS = ("kv", "fifo", "session", "kvread")

_KV_KEYS = 8
_FIFO_CONSUMERS = ("c0", "c1", "c2")
_SESSIONS = ("s0", "s1", "s2", "s3")
_LOCK_KEYS = ("lk0", "lk1", "lk2")


def make_machine(workload: str, ctr=None):
    """Machine factory for one replica. ``ctr`` (SESSION_FIELDS) goes to
    exactly one replica's machine — apply runs on every replica, so a
    shared vector would multiply every count by the cluster size."""
    if workload in ("kv", "kvread"):
        return KvMachine(snapshot_interval=24)
    if workload == "fifo":
        return FifoMachine()
    if workload == "session":
        return SessionMachine(ctr=ctr)
    raise ValueError(f"unknown workload {workload!r}")


# -- op generation -------------------------------------------------------------


def generate_ops(sched) -> List[Tuple[int, Tuple[Any, ...]]]:
    rng = random.Random((sched.seed << 4) ^ 0x4F5053)  # "OPS"
    if sched.workload == "kvread":
        return _gen_kvread_ops(sched, rng)
    gen = {
        "kv": _gen_kv,
        "fifo": _gen_fifo,
        "session": _gen_session,
    }[sched.workload]
    ops: List[Tuple[int, Tuple[Any, ...]]] = []
    # ops spread across the horizon with jittered gaps; the settle
    # window after the horizon is op-free so the cluster can quiesce
    t = 0
    gap = max(2, (2 * sched.horizon_ms) // max(1, sched.n_ops))
    for i in range(sched.n_ops):
        t += 1 + rng.randrange(gap)
        if t >= sched.horizon_ms:
            break
        ops.append((t, gen(rng, i)))
    if sched.nemesis:
        k = 0
        for t in range(300, sched.horizon_ms, 400):
            ops.append((t, ("nem", k)))
            k += 1
    ops.sort(key=lambda p: p[0])
    return ops


def _gen_kvread_ops(sched, rng: random.Random) -> List[Tuple[int, Tuple[Any, ...]]]:
    """Lease read-safety workload (docs/INTERNALS.md §20): writes to
    one key interleaved with dense consistent reads fanned across
    every node. The oracle lives in the world's reply recorder: a
    write's ack carries its raft index; a read invoked after that ack
    must observe a "seq" entry at an index >= the acked floor — the
    linearizability claim the leader lease makes. Reads land on every
    node (not just the believed leader) precisely so a deposed leader
    still inside a too-long lease window serves one and gets caught."""
    ops: List[Tuple[int, Tuple[Any, ...]]] = []
    t = 0
    gap = max(2, (2 * sched.horizon_ms) // max(1, sched.n_ops))
    for _ in range(sched.n_ops):
        t += 1 + rng.randrange(gap)
        if t >= sched.horizon_ms:
            break
        if rng.random() < 0.45:
            ops.append((t, ("cmd", ("put", "seq", 0))))  # value unused
        else:
            ops.append((t, ("read", rng.randrange(sched.nodes))))
    if sched.nemesis:
        k = 0
        for t in range(300, sched.horizon_ms, 400):
            ops.append((t, ("nem", k)))
            k += 1
    ops.sort(key=lambda p: p[0])
    return ops


def read_seq_index(state) -> int:
    """The consistent-read probe for the kvread workload: the raft
    index the "seq" key was last written at (-1 before any write).
    Module-level so a dumped schedule replays without a closure."""
    entry = state.get("seq")
    return entry[0] if entry else -1


def _gen_kv(rng: random.Random, i: int) -> Tuple[Any, ...]:
    r = rng.random()
    key = f"k{rng.randrange(_KV_KEYS)}"
    if r < 0.75:
        return ("cmd", ("put", key, i))
    if r < 0.9:
        return ("cmd", ("delete", key))
    return ("cmd", ("keys",))


def _gen_fifo(rng: random.Random, i: int) -> Tuple[Any, ...]:
    r = rng.random()
    cid = rng.choice(_FIFO_CONSUMERS)
    if r < 0.5:
        return ("cmd", ("enqueue", f"m{i}"))
    if r < 0.72:
        return ("cmd", ("checkout", cid, 1 + rng.randrange(3)))
    if r < 0.9:
        # settle a plausible id; settling a non-inflight id is a no-op
        return ("cmd", ("settle", cid, 1 + rng.randrange(max(i, 1))))
    return ("down", cid)


def _gen_session(rng: random.Random, i: int) -> Tuple[Any, ...]:
    r = rng.random()
    sid = rng.choice(_SESSIONS)
    key = rng.choice(_LOCK_KEYS)
    if r < 0.25:
        return ("cmd", ("session_open", sid, 200 + rng.randrange(1200)))
    if r < 0.4:
        return ("cmd", ("session_renew", sid))
    if r < 0.48:
        return ("cmd", ("session_close", sid))
    if r < 0.68:
        return ("cmd", ("lock_acquire", sid, key))
    if r < 0.78:
        return ("cmd", ("lock_acquire", sid, key, "steal"))
    if r < 0.9:
        return ("cmd", ("lock_release", sid, key))
    return ("down", sid)


# -- per-apply invariants (the workload oracles) --------------------------------


def invariant_for(workload: str) -> Optional[Callable]:
    return {
        "kv": None,
        "kvread": None,  # read oracle runs in the world's reply recorder
        "fifo": _fifo_invariant,
        "session": _session_invariant,
    }[workload]


def _fifo_invariant(cmd, pre, post, effs,
                    tracker: Dict[str, Any]) -> Optional[str]:
    if isinstance(cmd, tuple) and cmd and cmd[0] in ("down", "cancel"):
        cid = cmd[1]
        batch = sorted((pre.consumers.get(cid) or {}).keys())
        if len(batch) >= 2:
            # the requeued batch lands at the queue FRONT, and _service
            # may hand part (or all) of it to other ready consumers
            # within the same apply — walking the queue front in order.
            # So the observable redelivery order is: batch members among
            # this apply's delivery effects (in effect order), then the
            # batch members still parked at the queue head. Correct code
            # makes that concatenation exactly the ascending batch; the
            # reversed-requeue failpoint cannot.
            batch_set = set(batch)
            delivered = [
                e.msg[1] for e in effs
                if isinstance(e, SendMsg) and e.msg
                and e.msg[0] == "delivery" and e.msg[1] in batch_set
            ]
            head = []
            for mid, _m in post.queue:
                if mid not in batch_set:
                    break
                head.append(mid)
            if delivered + head != batch:
                return (
                    f"requeue order violated: consumer {cid} went down "
                    f"holding {batch}, redelivery order {delivered + head}"
                )
    return None


def _session_invariant(cmd, pre, post, effs,
                       tracker: Dict[str, Any]) -> Optional[str]:
    # 1. lock safety: every holder is a live session
    for key, (owner, token) in post.locks.items():
        if owner not in post.sessions:
            return f"lock {key} held by dead session {owner} (token {token})"
    # 2. fencing tokens strictly increase per key across grants
    last: Dict[Any, int] = tracker.setdefault("tokens", {})
    for key, (owner, token) in post.locks.items():
        prev = last.get(key)
        if prev is not None and token < prev:
            return f"fencing token regressed on {key}: {prev} -> {token}"
        last[key] = max(token, prev or 0)
    # 3. every expiry attributable: sessions leave only via their own
    #    close, a down builtin, or a matching-generation ttl timeout
    gone = set(pre.sessions) - set(post.sessions)
    if gone:
        op = cmd[0] if isinstance(cmd, tuple) and cmd else None
        if op not in ("session_close", "down", "timeout"):
            return f"sessions {sorted(gone)} vanished on {op!r} command"
        if op == "timeout":
            name = cmd[1]
            sid, gen = name[1], name[2]
            if gone != {sid} or pre.sessions[sid].gen != gen:
                return (
                    f"timeout {name!r} expired {sorted(gone)} "
                    f"(gen mismatch or wrong session)"
                )
    return None
