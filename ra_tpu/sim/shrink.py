"""Auto-shrinker: delta-debug a failing schedule to a minimal repro.

Determinism makes shrinking trivially sound: a candidate schedule either
reproduces the violation or it doesn't — there is no flakiness to
tolerate, so plain ddmin (Zeller/Hildebrandt) over the materialized op
list converges without repetition heuristics. The result is 1-minimal:
removing any single remaining op makes the failure disappear.

Only the external op timeline is shrunk. Seed-derived internals
(election jitter, network fault draws, nemesis choices) replay
identically under the same parameters, so candidates stay meaningful —
the same storms hit a shorter client history.
"""

from __future__ import annotations

from typing import Callable, List, Optional, Tuple

from ra_tpu.sim.schedule import Op, Schedule


def default_fails(sched: Schedule) -> bool:
    from ra_tpu.sim.world import run_schedule

    return not run_schedule(sched).ok


def shrink(
    sched: Schedule,
    fails: Optional[Callable[[Schedule], bool]] = None,
    ctr=None,
) -> Tuple[Schedule, int]:
    """ddmin the schedule's ops down to a 1-minimal failing list.

    Returns ``(minimized schedule, replays executed)``. Raises
    ``ValueError`` if the input schedule does not fail — shrinking a
    passing schedule would silently return garbage.
    """
    fails = fails or default_fails
    ops: List[Op] = list(sched.resolve_ops())
    base = sched.with_ops(ops)  # materialized: candidates are explicit data
    iterations = 0

    def check(candidate: List[Op]) -> bool:
        nonlocal iterations
        iterations += 1
        if ctr is not None:
            ctr.incr("sim_shrink_iterations")
        return fails(base.with_ops(candidate))

    if not check(ops):
        raise ValueError("schedule does not fail; nothing to shrink")

    n = 2
    while len(ops) >= 2:
        size = len(ops) // n
        reduced = False
        # complement-only ddmin: try dropping each of the n chunks
        for i in range(n):
            start = i * size
            end = start + size if i < n - 1 else len(ops)
            candidate = ops[:start] + ops[end:]
            if candidate and check(candidate):
                ops = candidate
                n = max(n - 1, 2)
                reduced = True
                break
        if not reduced:
            if n >= len(ops):
                break  # granularity 1 and nothing droppable: 1-minimal
            n = min(len(ops), 2 * n)

    if ctr is not None:
        ctr.incr("sim_minimized_ops", len(ops))
    return base.with_ops(ops), iterations
