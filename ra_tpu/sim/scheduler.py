"""Deterministic single-threaded run queue over virtual time.

This replaces every concurrency source the threaded runtime has —
``TimerService`` wheels, actor mailup threads, WAL fsync completions,
transport deliveries — with ONE ordered heap of ``(t_ms, seq, fn)``.
``seq`` is a global arrival counter, so events at the same virtual
millisecond run in the order they were scheduled (FIFO tie-break): the
whole execution is a pure function of (schedule, seed), which is the
determinism invariant the sim tests assert byte-for-byte
(docs/INTERNALS.md §19).

Cancellation is tombstone-based (drop the ref from the live map) so a
cancel never perturbs heap order — the popped tombstone is skipped.
"""

from __future__ import annotations

import heapq
from typing import Any, Callable, Dict, List, Optional, Tuple

from ra_tpu.sim.clock import VirtualClock


class SimScheduler:
    def __init__(self, clock: VirtualClock) -> None:
        self.clock = clock
        self._heap: List[Tuple[int, int, int]] = []  # (t_ms, seq, ref)
        self._live: Dict[int, Callable[[], None]] = {}
        self._seq = 0

    def after_ms(self, delay_ms: int, fn: Callable[[], None]) -> int:
        """Schedule fn at now + delay_ms; returns a cancellable ref."""
        self._seq += 1
        ref = self._seq
        t = self.clock.now_ms + max(0, int(delay_ms))
        heapq.heappush(self._heap, (t, ref, ref))
        self._live[ref] = fn
        return ref

    def cancel(self, ref: Optional[int]) -> None:
        if ref is not None:
            self._live.pop(ref, None)

    def pending(self) -> int:
        return len(self._live)

    def run_next(self) -> bool:
        """Advance virtual time to the next live event and run it.
        Returns False when the queue is drained."""
        while self._heap:
            t, _seq, ref = heapq.heappop(self._heap)
            fn = self._live.pop(ref, None)
            if fn is None:
                continue  # cancelled tombstone
            self.clock.advance_to(t)
            fn()
            return True
        return False


class SimTimerService:
    """``ra_tpu.runtime.timers.TimerService`` facade over the sim run
    queue (after/cancel/close in seconds), for code written against the
    threaded timer wheel. The sim world itself schedules in ms."""

    def __init__(self, sched: SimScheduler) -> None:
        self._sched = sched

    def after(self, delay_s: float, fn: Callable[[], None]):
        return self._sched.after_ms(int(round(delay_s * 1000.0)), fn)

    def cancel(self, ref) -> None:
        self._sched.cancel(ref)

    def close(self) -> None:
        pass
