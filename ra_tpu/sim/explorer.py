"""Schedule explorer: sweep seeds x workloads, shrink what fails.

The CI-facing entry point of the simulation plane
(``python -m ra_tpu.sim.explorer``, wired through
``scripts/sim_sweep.sh``). Each (workload, seed) pair becomes one
``Schedule`` with network faults and the nemesis planner on; failures
are auto-shrunk and dumped as standalone repro text a developer replays
with ``ra_tpu.sim.schedule.loads`` + ``run_schedule``.

Virtual time is what makes the sweep cheap: a 12-virtual-second
schedule (8s of ops + storms, 4s of quiescence) executes in tens of
wall milliseconds because sleeps cost nothing — the run queue jumps the
clock. Measured rates live in docs/INTERNALS.md §19.
"""

from __future__ import annotations

import argparse
import sys
import time
from typing import Any, Dict, List, Optional, Sequence

from ra_tpu import counters as ra_counters
from ra_tpu.counters import SIM_FIELDS
from ra_tpu.sim.schedule import Schedule, dumps
from ra_tpu.sim.shrink import shrink
from ra_tpu.sim.workloads import WORKLOADS
from ra_tpu.sim.world import run_schedule

# default fault mix: lossy, dup-happy, jittery — plus the nemesis
# planner (partitions / one-way links / restarts) on top
DEFAULT_FAULTS = dict(drop_p=0.02, dup_p=0.02, delay_p=0.15)


def explore(
    workloads: Sequence[str],
    seeds: Sequence[int],
    n_ops: int = 60,
    nemesis: bool = True,
    faults: Optional[Dict[str, float]] = None,
    shrink_failures: bool = True,
) -> Dict[str, Any]:
    """Run every (workload, seed) schedule; return a sweep summary with
    minimized repros for each failure."""
    fa = DEFAULT_FAULTS if faults is None else faults
    ctr = ra_counters.registry().new(("sim", "plane"), SIM_FIELDS)
    t0 = time.perf_counter()  # wall clock: we're OUTSIDE the sim here
    ran = 0
    steps = 0
    virtual_ms = 0
    failures: List[Dict[str, Any]] = []
    for workload in workloads:
        for seed in seeds:
            # kvread is the lease read-safety workload: leases on and
            # per-node clock rate skew at the covered bound, so the
            # sweep probes the drift-epsilon math, not a lease-off path
            lease_kw = (dict(lease=True, skew_ppm=10_000)
                        if workload == "kvread" else {})
            sched = Schedule(seed=seed, workload=workload, n_ops=n_ops,
                             nemesis=nemesis, **fa, **lease_kw)
            res = run_schedule(sched)
            ran += 1
            steps += res.steps
            virtual_ms += res.virtual_ms
            if res.ok:
                continue
            failure: Dict[str, Any] = {
                "workload": workload,
                "seed": seed,
                "violations": res.violations,
                "schedule": dumps(res.schedule),
            }
            if shrink_failures:
                minimized, replays = shrink(res.schedule, ctr=ctr)
                failure["minimized"] = dumps(minimized)
                failure["minimized_ops"] = len(minimized.ops)
                failure["shrink_replays"] = replays
            failures.append(failure)
    wall_s = time.perf_counter() - t0
    return {
        "schedules": ran,
        "failures": failures,
        "steps": steps,
        "virtual_ms": virtual_ms,
        "wall_s": wall_s,
        "per_min": (ran / wall_s * 60.0) if wall_s > 0 else float("inf"),
    }


def main(argv: Optional[Sequence[str]] = None) -> int:
    p = argparse.ArgumentParser(
        description="sweep seeded simulation schedules; shrink failures"
    )
    p.add_argument("--workloads", default=",".join(WORKLOADS),
                   help="comma-separated subset of: " + ",".join(WORKLOADS))
    p.add_argument("--seeds", type=int, default=10,
                   help="schedules per workload")
    p.add_argument("--start", type=int, default=0, help="first seed")
    p.add_argument("--ops", type=int, default=60, help="client ops per schedule")
    p.add_argument("--no-nemesis", action="store_true",
                   help="network faults only, no planner storms")
    p.add_argument("--no-shrink", action="store_true",
                   help="report failures without minimizing them")
    args = p.parse_args(argv)

    workloads = [w.strip() for w in args.workloads.split(",") if w.strip()]
    for w in workloads:
        if w not in WORKLOADS:
            p.error(f"unknown workload {w!r}")
    seeds = range(args.start, args.start + args.seeds)

    summary = explore(
        workloads, list(seeds), n_ops=args.ops,
        nemesis=not args.no_nemesis,
        shrink_failures=not args.no_shrink,
    )
    print(
        f"sim sweep: {summary['schedules']} schedules, "
        f"{len(summary['failures'])} failed, "
        f"{summary['steps']} steps, "
        f"{summary['virtual_ms'] / 1000.0:.1f}s virtual in "
        f"{summary['wall_s']:.1f}s wall "
        f"({summary['per_min']:.0f} schedules/min)"
    )
    for f in summary["failures"]:
        print(f"\nFAIL workload={f['workload']} seed={f['seed']}")
        for v in f["violations"]:
            print(f"  violation: {v}")
        if "minimized" in f:
            print(f"  minimized to {f['minimized_ops']} ops "
                  f"({f['shrink_replays']} replays):")
            for line in f["minimized"].splitlines():
                print(f"    {line}")
    return 1 if summary["failures"] else 0


if __name__ == "__main__":
    sys.exit(main())
