"""Schedule: the complete, replayable input of one simulated run.

A ``Schedule`` plus the code under test fully determines execution —
the determinism invariant (docs/INTERNALS.md §19). Two kinds of input
live here:

- **parameters** (seed, fault probabilities, horizon): every internal
  random choice — election jitter, network drop/dup/delay decisions,
  nemesis planner draws — comes from streams derived from ``seed``;
- **ops**: the externally injected timeline — client commands, client
  process downs, nemesis steps — as explicit ``(t_ms, op)`` pairs.

``ops=None`` means "generate from the seed" (``resolve_ops``); the
shrinker materializes the generated list once and then delta-debugs the
explicit list, so a minimized repro is a plain data file with no
generator behind it. ``dumps``/``loads`` is a line-oriented text format
(one op per line) chosen so the determinism test can assert
byte-identical replay and a human can read a minimized repro directly.

Op vocabulary:
  ("cmd", payload)        -- client command to the current leader
  ("down", target)        -- monitored client process dies
  ("nem", op_i)           -- one nemesis planner step (planner rng decides)
  ("read", target)        -- consistent_query; target is a node index,
                             "leader" (current), or "old" (the leader
                             captured by the last isolate op)
  ("isolate", "leader")   -- block the current leader from everyone,
                             both directions, and remember it as "old"
  ("etimo", "other")      -- deterministic ElectionTimeout at the first
                             running voter that is not the old leader
  ("unblock",)            -- heal every directed block now
"""

from __future__ import annotations

import ast
import dataclasses
import sys
from typing import Any, List, Optional, Tuple

Op = Tuple[int, Tuple[Any, ...]]  # (t_ms, op)


def _canon(x: Any) -> Any:
    """Canonicalize op aliasing. State digests hash ``pickle`` bytes,
    and pickle memoizes: a payload string shared by identity between
    two state slots pickles as a back-reference, while two equal but
    distinct strings pickle twice. Generated ops alias module constants
    and interned literals; ``loads`` goes through ``ast.literal_eval``,
    which never builds a code object and so never interns — equal
    schedules, different bytes. Interning every string and rebuilding
    every container at the injection boundary makes both paths
    byte-identical under the digest."""
    if isinstance(x, str):
        return sys.intern(x)
    if isinstance(x, tuple):
        return tuple(_canon(v) for v in x)
    if isinstance(x, list):
        return [_canon(v) for v in x]
    if isinstance(x, dict):
        return {_canon(k): _canon(v) for k, v in x.items()}
    if isinstance(x, (set, frozenset)):
        return type(x)(_canon(v) for v in x)
    return x


@dataclasses.dataclass(frozen=True)
class Schedule:
    seed: int
    workload: str  # "kv" | "fifo" | "session"
    n_ops: int = 60
    horizon_ms: int = 8_000
    settle_ms: int = 4_000
    nodes: int = 3
    drop_p: float = 0.0
    dup_p: float = 0.0
    delay_p: float = 0.0
    delay_ms_max: int = 40
    nemesis: bool = False
    # clock-bound leader leases (docs/INTERNALS.md §20): lease=True
    # starts every server lease-enabled; skew_ppm bounds the per-node
    # clock RATE skew (parts per million, drawn from the seed) that the
    # lease drift epsilon is widened to cover
    lease: bool = False
    skew_ppm: int = 0
    # storage-pressure plane (docs/INTERNALS.md §21): a per-node disk
    # byte budget (0 = unlimited). Writes that would exceed it fail
    # space-class: the node parks them (degraded) until the horizon
    # heal frees space — acked writes must survive the episode
    disk_budget_bytes: int = 0
    ops: Optional[Tuple[Op, ...]] = None  # explicit timeline overrides n_ops

    def with_ops(self, ops: List[Op]) -> "Schedule":
        return dataclasses.replace(self, ops=tuple(ops))

    def resolve_ops(self) -> List[Op]:
        if self.ops is not None:
            return [_canon(op) for op in self.ops]
        from ra_tpu.sim.workloads import generate_ops

        return [_canon(op) for op in generate_ops(self)]


def dumps(sched: Schedule) -> str:
    """Canonical one-op-per-line text; ops are materialized so the dump
    stands alone as a repro (no generator needed to re-run it)."""
    lines = [
        f"# ra_tpu sim schedule v1",
        f"seed={sched.seed} workload={sched.workload} nodes={sched.nodes}",
        f"horizon_ms={sched.horizon_ms} settle_ms={sched.settle_ms}",
        f"drop_p={sched.drop_p} dup_p={sched.dup_p} delay_p={sched.delay_p}"
        f" delay_ms_max={sched.delay_ms_max} nemesis={sched.nemesis}",
        f"lease={sched.lease} skew_ppm={sched.skew_ppm}"
        f" disk_budget_bytes={sched.disk_budget_bytes}",
    ]
    for t_ms, op in sched.resolve_ops():
        lines.append(f"{t_ms} {op!r}")
    return "\n".join(lines) + "\n"


def loads(text: str) -> Schedule:
    head: dict = {}
    ops: List[Op] = []
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        if "=" in line.split(" ", 1)[0]:
            for kv in line.split():
                k, v = kv.split("=", 1)
                head[k] = v
        else:
            t_s, op_s = line.split(" ", 1)
            ops.append((int(t_s), ast.literal_eval(op_s)))
    return Schedule(
        seed=int(head["seed"]),
        workload=head["workload"],
        nodes=int(head.get("nodes", 3)),
        horizon_ms=int(head.get("horizon_ms", 8_000)),
        settle_ms=int(head.get("settle_ms", 4_000)),
        drop_p=float(head.get("drop_p", 0.0)),
        dup_p=float(head.get("dup_p", 0.0)),
        delay_p=float(head.get("delay_p", 0.0)),
        delay_ms_max=int(head.get("delay_ms_max", 40)),
        nemesis=head.get("nemesis", "False") == "True",
        lease=head.get("lease", "False") == "True",
        skew_ppm=int(head.get("skew_ppm", 0)),
        disk_budget_bytes=int(head.get("disk_budget_bytes", 0)),
        ops=tuple(ops),
    )
