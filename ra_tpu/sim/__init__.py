"""Deterministic simulation plane (docs/INTERNALS.md §19).

One seeded run queue over virtual time replaces every concurrency
source of the threaded runtime; a ``Schedule`` fully determines
execution, failures auto-shrink to minimal standalone repros.
"""

from ra_tpu.sim.clock import SIM_EPOCH_S, VirtualClock
from ra_tpu.sim.schedule import Schedule, dumps, loads
from ra_tpu.sim.scheduler import SimScheduler, SimTimerService
from ra_tpu.sim.shrink import shrink
from ra_tpu.sim.transport import SimNetwork
from ra_tpu.sim.world import SimResult, SimWorld, run_schedule
from ra_tpu.sim.workloads import WORKLOADS

__all__ = [
    "SIM_EPOCH_S",
    "VirtualClock",
    "Schedule",
    "dumps",
    "loads",
    "SimScheduler",
    "SimTimerService",
    "shrink",
    "SimNetwork",
    "SimResult",
    "SimWorld",
    "run_schedule",
    "WORKLOADS",
]
