"""Offline WAL replay debugger.

Capability parity with the reference's ``ra_dbg:replay_log/4``
(``src/ra_dbg.erl:12-30``): re-read a server's persisted log (WAL +
segments) outside any running system and fold a machine over it,
optionally calling a callback per applied entry — for post-mortem
debugging of machine behavior.
"""

from __future__ import annotations

import os
from typing import Any, Callable, Optional, Tuple

from ra_tpu.log.log import Log
from ra_tpu.log.segment_writer import SegmentWriter
from ra_tpu.log.tables import TableRegistry
from ra_tpu.log.wal import Wal
from ra_tpu.machine import Machine, normalize_apply_result
from ra_tpu.protocol import Command, USR


def replay_log(
    node_dir: str,
    uid: str,
    machine: Machine,
    on_entry: Optional[Callable[[int, Any, Any], None]] = None,
    to_index: Optional[int] = None,
) -> Tuple[Any, int]:
    """Rebuild the log from ``<node_dir>/{wal,data/<uid>}`` and apply all
    USR entries in order. Returns (final_machine_state, last_applied)."""
    tables = TableRegistry()
    sink: list = []
    sw = SegmentWriter(
        os.path.join(node_dir, "data"), tables, lambda u, e: sink.append((u, e)),
        threaded=False,
    )
    wal = Wal(
        os.path.join(node_dir, "wal"), tables, lambda u, e: sink.append((u, e)),
        segment_writer=sw, threaded=False, sync_method="none",
    )
    log = Log(uid, os.path.join(node_dir, "data", uid), tables, wal)
    snap = log.read_snapshot()
    if snap is not None:
        meta, state = snap
        from_idx = meta.index + 1
        mac_state = state
    else:
        from_idx = 1
        mac_state = machine.init({"name": uid})
    last = log.last_index_term()[0]
    hi = min(last, to_index) if to_index is not None else last
    applied = from_idx - 1
    for i in range(from_idx, hi + 1):
        e = log.fetch(i)
        if e is None:
            continue  # compacted dead entry
        cmd = e.cmd
        if isinstance(cmd, Command) and cmd.kind == USR:
            mac_state, reply, _effs = normalize_apply_result(
                machine.apply({"index": i, "term": e.term, "machine_version": 0},
                              cmd.data, mac_state)
            )
            if on_entry is not None:
                on_entry(i, cmd.data, mac_state)
        applied = i
    wal.close()
    sw.close()
    log.close()
    return mac_state, applied
