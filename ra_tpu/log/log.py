"""The real per-server log: memtable + shared WAL + segments + snapshots.

Implements ``LogApi`` over the storage engines, with the reference's
async write model (reference: ``src/ra_log.erl`` — append/write go to the
memtable then the WAL :484-591; ``("written", term, seq)`` events advance
the durable watermark with overwrite-staleness checks :895-1163;
``("segments", seq, refs)`` events shrink the memtable; release cursors
decide snapshots :1282-1436; ``resend`` protocol re-feeds the WAL after
gaps :1651).

Events arrive via ``handle_event`` from whatever thread the runtime
routes them on; the owning server must serialize calls (the server proc
event loop does).
"""

from __future__ import annotations

import os
import pickle
import time
from typing import Any, Callable, List, Optional, Sequence, Tuple

from ra_tpu.log.api import LogApi
from ra_tpu.log.segments import SegmentSet
from ra_tpu.log.snapshot import CHECKPOINT, RECOVERY, SNAPSHOT, SnapshotStore
from ra_tpu.log.tables import TableRegistry
from ra_tpu.log.wal import Wal
from ra_tpu.protocol import Entry, SnapshotMeta, encode_cmd
from ra_tpu.utils.seq import Seq

MIN_SNAPSHOT_INTERVAL = 4096
MIN_CHECKPOINT_INTERVAL = 16384


class Log(LogApi):
    def __init__(
        self,
        uid: str,
        server_dir: str,
        tables: TableRegistry,
        wal: Wal,
        min_snapshot_interval: int = MIN_SNAPSHOT_INTERVAL,
        min_checkpoint_interval: int = MIN_CHECKPOINT_INTERVAL,
        snapshot_store: Optional[SnapshotStore] = None,
        major_every_minors: int = 2,
        bg_submit=None,
        segment_index_mode: str = "map",
        sync_pool=None,
    ):
        self.uid = uid
        self.server_dir = server_dir
        os.makedirs(server_dir, exist_ok=True)
        self.tables = tables
        self.wal = wal
        self.mt = tables.mem_table(uid)
        self.segs = SegmentSet(
            os.path.join(server_dir, "segments"), index_mode=segment_index_mode
        )
        self.snapshots = snapshot_store or SnapshotStore(server_dir, sync_pool=sync_pool)
        self.min_snapshot_interval = min_snapshot_interval
        self.min_checkpoint_interval = min_checkpoint_interval
        # major compaction policy: schedule a grouping pass every N
        # minor (snapshot-floor) compactions (the reference's
        # {num_minors, N} major strategy; cf. src/ra_kv.erl:80-103)
        self.major_every_minors = major_every_minors
        self.bg_submit = bg_submit  # None -> run major passes inline
        self._minors_since_major = 0
        self.resend_window_s = 20.0
        self._last_resend_t = float("-inf")

        # recover tail state
        self._snapshot_meta = self.snapshots.current()
        snap_idx = self._snapshot_meta.index if self._snapshot_meta else 0
        snap_term = self._snapshot_meta.term if self._snapshot_meta else 0
        if self._snapshot_meta is not None:
            self.tables.set_snapshot_state(
                uid, snap_idx, Seq.from_list(self._snapshot_meta.live_indexes)
            )
        mt_rng = self.mt.range()
        seg_rng = self.segs.range()
        last = max(
            snap_idx,
            mt_rng[1] if mt_rng else 0,
            seg_rng[1] if seg_rng else 0,
        )
        self._last_index = last
        t = self.fetch_term(last)
        self._last_term = t if t is not None else snap_term
        # everything already on disk is durable
        self._written_index = last
        self._written_term = self._last_term
        self._last_checkpoint_idx = snap_idx
        self._last_snapshot_candidate: Optional[Tuple[int, Any]] = None

    # ------------------------------------------------------------------
    # writes

    def append(self, entry: Entry) -> None:
        if entry.index != self._last_index + 1:
            raise ValueError(
                f"non-contiguous append {entry.index} after {self._last_index}"
            )
        tid = self.mt.insert(entry)
        self.wal.write(self.uid, entry.index, entry.term, encode_cmd(entry.cmd), tid=tid)
        self._last_index = entry.index
        self._last_term = entry.term

    def append_many(self, entries: Sequence[Entry]) -> None:
        """Leader bulk append: one memtable run insert, one WAL lock
        round, and one serialization per DISTINCT command object (a
        pipelined wave fans the same Command instance across entries —
        pickling it once per batch instead of once per entry)."""
        if not entries:
            return
        if entries[0].index != self._last_index + 1:
            raise ValueError(
                f"non-contiguous append {entries[0].index} after "
                f"{self._last_index}"
            )
        self._bulk_insert(entries)
        self._last_index = entries[-1].index
        self._last_term = entries[-1].term

    def _bulk_insert(self, entries: Sequence[Entry]) -> None:
        tid = self.mt.insert_run(entries)
        if tid is None:
            # overwrite/rotation inside the run: per-entry path
            for e in entries:
                t = self.mt.insert(e)
                self.wal.write(self.uid, e.index, e.term,
                               encode_cmd(e.cmd), tid=t)
            return
        memo: dict = {}
        payloads = []
        terms = []
        for e in entries:
            c = e.cmd
            enc = memo.get(id(c))
            if enc is None:
                memo[id(c)] = enc = encode_cmd(c)
            payloads.append(enc)
            terms.append(e.term)
        # ONE queue item + run-level writer bookkeeping for the whole
        # contiguous run (the WAL expands it to per-entry frames)
        self.wal.write_run(self.uid, entries[0].index, terms, payloads, tid)

    def write(self, entries: Sequence[Entry]) -> None:
        if not entries:
            return
        first = entries[0].index
        if first > self._last_index + 1:
            raise ValueError(f"gap: write at {first}, last is {self._last_index}")
        if first <= self._last_index:
            # divergent suffix rewrite: rewind the durable watermark too
            self.wal.truncate_write(self.uid, first)
            self.mt.truncate_from(first)
            self._rewind_to(first - 1)
        self._bulk_insert(entries)
        self._last_index = entries[-1].index
        self._last_term = entries[-1].term

    def write_sparse(self, entry: Entry) -> None:
        """Out-of-order live-entry write during snapshot install."""
        tid = self.mt.insert_sparse(entry)
        self.wal.write(
            self.uid, entry.index, entry.term, encode_cmd(entry.cmd),
            sparse=True, tid=tid,
        )

    def set_last_index(self, idx: int) -> None:
        self.wal.truncate_write(self.uid, idx + 1)
        self.mt.truncate_from(idx + 1)
        self._rewind_to(idx)
        self._last_index = idx
        t = self.fetch_term(idx)
        self._last_term = t if t is not None else 0

    def _rewind_to(self, idx: int) -> None:
        if self._written_index > idx:
            self._written_index = idx
            t = self.fetch_term(idx)
            self._written_term = t if t is not None else 0

    # ------------------------------------------------------------------
    # events

    def handle_event(self, evt: Any) -> List[Any]:
        if not isinstance(evt, tuple) or not evt:
            return []
        tag = evt[0]
        if tag == "written":
            _, term, seq = evt
            if seq is None or seq.is_empty():
                return []
            last = seq.last()
            # stale-write check: the entry at `last` must still carry the
            # term that was written (it may have been overwritten since)
            t = self.fetch_term(last)
            if t == term and last > self._written_index:
                self._written_index = min(last, self._last_index)
                self._written_term = term
            return []
        if tag == "segments":
            _, tid_seqs, refs = evt
            for fname, rng in refs:
                self.segs.add_ref(fname, rng)
            for tid, seq in tid_seqs:
                self.mt.record_flushed(seq, tid=tid)
            return []
        if tag == "resend_write":
            # throttled: a flood of gap notifications must not re-queue
            # the same tail repeatedly (reference: resend_window_seconds,
            # src/ra_log.erl:65,1651)
            _, from_idx = evt
            self._resend(from_idx)
            return []
        if tag == "wal_up":
            # the WAL came back after a failure: resend everything past
            # the durable watermark (bypasses the throttle — this is the
            # recovery moment itself)
            self._resend(self._written_index + 1, force=True)
            return []
        return []

    def _resend(self, from_idx: int, force: bool = False) -> None:
        now = time.monotonic()
        if not force and (now - self._last_resend_t) < self.resend_window_s:
            return
        self._last_resend_t = now
        if force:
            # post-failure resend: truncate markers issued while the WAL
            # was down were dropped, and the retained failed file may
            # hold a since-discarded suffix — re-establish the cut in
            # the fresh file before replaying the current tail
            self.wal.truncate_write(self.uid, from_idx)
        for i in range(from_idx, self._last_index + 1):
            got = self.mt.get_with_tid(i)
            if got is not None:
                e, tid = got
                # tag with the table that OWNS the entry: tagging an
                # older table's entry with the head tid would make the
                # eventual flush read get_from(head, i) -> None and
                # silently drop the only durable copy
                self.wal.write(self.uid, e.index, e.term, encode_cmd(e.cmd),
                               tid=tid)

    # ------------------------------------------------------------------
    # reads

    def last_index_term(self) -> Tuple[int, int]:
        return self._last_index, self._last_term

    def last_written(self) -> Tuple[int, int]:
        return self._written_index, self._written_term

    def fetch(self, idx: int) -> Optional[Entry]:
        e = self.mt.get(idx)
        if e is not None:
            return e
        return self.segs.fetch(idx)

    def fetch_term(self, idx: int) -> Optional[int]:
        if idx == 0:
            return 0
        e = self.mt.get(idx)
        if e is not None:
            return e.term
        t = self.segs.fetch_term(idx)
        if t is not None:
            return t
        if self._snapshot_meta is not None and idx == self._snapshot_meta.index:
            return self._snapshot_meta.term
        return None

    def fetch_range(self, lo: int, hi: int) -> List[Entry]:
        """Batched contiguous read (the AER-construction / apply hot
        path): ONE memtable chain pass for the whole range instead of a
        per-index table walk, segment fallback only for flushed holes.
        Stops at the first truly-missing index (base-class contract)."""
        if hi < lo:
            return []
        got = self.mt.get_range(lo, hi)
        out: List[Entry] = []
        segs_fetch = self.segs.fetch
        for k, e in enumerate(got):
            if e is None:
                e = segs_fetch(lo + k)
                if e is None:
                    break
            out.append(e)
        return out

    def fold(self, lo: int, hi: int, fn: Callable[[Entry, Any], Any], acc: Any) -> Any:
        for i in range(lo, hi + 1):
            e = self.fetch(i)
            if e is None:
                raise KeyError(f"missing log entry {i} (uid={self.uid})")
            acc = fn(e, acc)
        return acc

    def sparse_read(self, idxs: Sequence[int]) -> List[Entry]:
        out = []
        for i in idxs:
            e = self.fetch(i)
            if e is not None:
                out.append(e)
        return out

    # ------------------------------------------------------------------
    # snapshots

    def snapshot_index_term(self) -> Optional[Tuple[int, int]]:
        m = self._snapshot_meta
        return (m.index, m.term) if m else None

    def snapshot_meta(self) -> Optional[SnapshotMeta]:
        return self._snapshot_meta

    def read_snapshot(self) -> Optional[Tuple[SnapshotMeta, Any]]:
        return self.snapshots.read(SNAPSHOT)

    def install_snapshot(self, meta: SnapshotMeta, machine_state: Any) -> List[Any]:
        self.snapshots.write(meta, machine_state, kind=SNAPSHOT)
        self._post_install(meta)
        return []

    def _post_install(self, meta: SnapshotMeta) -> None:
        self._post_snapshot(meta)
        if self._last_index < meta.index:
            self._last_index = meta.index
            self._last_term = meta.term
        if self._written_index < meta.index:
            self._written_index = meta.index
            self._written_term = meta.term

    # -- streaming transfer (reference: src/ra_snapshot.erl:135-210,
    # 742-860) -------------------------------------------------------------

    def begin_snapshot_read(self, chunk_size: int):
        return self.snapshots.begin_read_stream(chunk_size)

    def begin_accept_snapshot(self, meta: SnapshotMeta):
        return self.snapshots.begin_accept(meta)

    def complete_accept_snapshot(self, accept) -> Any:
        state = accept.complete()  # decodes from disk, promotes the dir
        self._post_install(accept.meta)
        return state

    def _post_snapshot(self, meta: SnapshotMeta) -> None:
        live = Seq.from_list(meta.live_indexes)
        self._snapshot_meta = meta
        self.tables.set_snapshot_state(self.uid, meta.index, live)
        self.mt.set_first(meta.index + 1, live=live)
        self.segs.truncate_below(meta.index, live)
        self._minors_since_major += 1
        if self._minors_since_major >= self.major_every_minors:
            self._minors_since_major = 0
            if self.bg_submit is not None:
                self.bg_submit(lambda: self.segs.major_compact(meta.index, live))
            else:
                self.segs.major_compact(meta.index, live)

    def major_compaction(self):
        """Explicit major compaction pass (grouping + merge + symlink
        protocol); normally scheduled automatically every
        ``major_every_minors`` snapshots."""
        meta = self._snapshot_meta
        if meta is None:
            return {"unreferenced": [], "linked": [], "compacted": []}
        return self.segs.major_compact(
            meta.index, Seq.from_list(meta.live_indexes)
        )

    def update_release_cursor(
        self, idx: int, cluster, machine_version: int, machine_state: Any,
        live_indexes=(),
    ) -> List[Any]:
        cur = self._snapshot_meta.index if self._snapshot_meta else 0
        if idx <= cur or (idx - cur) < self.min_snapshot_interval:
            return []
        return self._take_snapshot(
            idx, cluster, machine_version, machine_state,
            live_indexes=tuple(i for i in live_indexes if i <= idx),
        )

    def force_snapshot(
        self, idx, cluster, machine_version, machine_state, live_indexes=()
    ) -> List[Any]:
        return self._take_snapshot(
            idx, cluster, machine_version, machine_state,
            live_indexes=tuple(i for i in live_indexes if i <= idx),
        )

    def _take_snapshot(self, idx, cluster, machine_version, machine_state,
                       live_indexes: Tuple[int, ...] = ()) -> List[Any]:
        t = self.fetch_term(idx)
        if t is None:
            return []
        meta = SnapshotMeta(
            index=idx,
            term=t,
            cluster=tuple(cluster),
            machine_version=machine_version,
            live_indexes=tuple(live_indexes),
        )
        self.snapshots.write(meta, machine_state, kind=SNAPSHOT)
        self._post_snapshot(meta)
        return []

    def checkpoint(
        self, idx, cluster, machine_version, machine_state, live_indexes=()
    ) -> List[Any]:
        if (idx - self._last_checkpoint_idx) < self.min_checkpoint_interval:
            return []
        t = self.fetch_term(idx)
        if t is None:
            return []
        # live indexes are carried in the checkpoint meta: a later
        # promotion installs it as a snapshot and must retain them
        meta = SnapshotMeta(
            index=idx, term=t, cluster=tuple(cluster),
            machine_version=machine_version,
            live_indexes=tuple(i for i in live_indexes if i <= idx),
        )
        self.snapshots.write(meta, machine_state, kind=CHECKPOINT)
        self._last_checkpoint_idx = idx
        return []

    def promote_checkpoint(self, idx: int) -> List[Any]:
        meta = self.snapshots.promote_checkpoint(idx)
        if meta is not None:
            self._post_snapshot(meta)
        return []

    def write_recovery_checkpoint(self, meta: SnapshotMeta, machine_state: Any) -> None:
        """Orderly-shutdown capture to skip replay on restart."""
        self.snapshots.write(meta, machine_state, kind=RECOVERY)

    def read_recovery_checkpoint(self) -> Optional[Tuple[SnapshotMeta, Any]]:
        return self.snapshots.read(RECOVERY)

    def discard_recovery_checkpoint(self) -> None:
        """Recovery checkpoints are single-use (consumed at boot)."""
        self.snapshots.delete_kind(RECOVERY)

    # ------------------------------------------------------------------

    def close(self) -> None:
        self.segs.close()

    def overview(self) -> dict:
        ov = super().overview()
        ov.update(
            {
                "uid": self.uid,
                "mem_table_size": len(self.mt),
                "num_segments": self.segs.num_segments(),
                "wal_last_seq": self.wal.last_writer_seq(self.uid),
            }
        )
        return ov
