"""Shared write-ahead log.

One WAL per system serves *all* raft groups on the node: every append
from every group funnels into one append-only file and one fsync per
batch — the amortization trick at the heart of the reference's design
(reference: ``src/ra_log_wal.erl`` — gen_batch_server batching, writer-id
dictionary compression :482-499, per-writer gap detection :551-586,
rollover handing memtable seqs to the segment writer :641-688, chunked
recovery :393-470).

File format (little-endian):

    header   : magic b"RTW1"
    uid-def  : kind=1 | ref u16 | len u16 | uid utf-8
    entry    : kind=2 | ref u16 | idx u64 | term u64 | crc u32 | len u32
               | payload
    trunc    : kind=3 | ref u16 | idx u64   (explicit truncate-from marker)

CRC32 covers idx|term|payload. A short/corrupt tail record is treated as
a clean EOF (torn final write), matching standard WAL recovery rules.

Threading: producers call ``write``/``truncate_write`` from any thread; a
single writer thread drains the queue in batches of up to
``max_batch_size``, performs one write+fsync, then fires the per-writer
``("written", term, seq)`` notifications. ``threaded=False`` gives tests
a deterministic ``flush()``-driven mode.
"""

from __future__ import annotations

import os
import struct
import threading
import zlib
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from ra_tpu import counters as ra_counters
from ra_tpu.log.tables import TableRegistry
from ra_tpu.utils.seq import Seq

MAGIC = b"RTW1"
K_UID = 1
K_ENTRY = 2
K_TRUNC = 3

_ENTRY_HDR = struct.Struct("<BHQQII")
_UID_HDR = struct.Struct("<BHH")
_TRUNC_HDR = struct.Struct("<BHQ")

NotifyFn = Callable[[str, Any], None]


class Wal:
    def __init__(
        self,
        dir: str,
        tables: TableRegistry,
        notify: NotifyFn,
        segment_writer=None,
        max_size_bytes: int = 256 * 1024 * 1024,
        max_batch_size: int = 8192,
        sync_method: str = "datasync",  # datasync | sync | none
        compute_checksums: bool = True,
        threaded: bool = True,
        counter=None,
        native: bool = True,
    ):
        self.dir = dir
        os.makedirs(dir, exist_ok=True)
        self.tables = tables
        self.notify = notify
        self.segment_writer = segment_writer
        self.max_size_bytes = max_size_bytes
        self.max_batch_size = max_batch_size
        self.sync_method = sync_method
        self.compute_checksums = compute_checksums
        # resolve (and if needed g++-build) the native framer NOW, off the
        # commit path — a lazy first-batch build would stall every queued
        # append behind a compiler run
        if native:
            from ra_tpu import native as _native

            native = _native.available()
        self._native = native
        self.counter = counter or ra_counters.Counters("wal", ra_counters.WAL_FIELDS)

        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._queue: deque = deque()
        self._closed = False

        # per-open-file state
        self._file = None
        self._file_num = 0
        self._file_path: Optional[str] = None
        self._bytes = 0
        self._uid_refs: Dict[str, int] = {}
        self._file_seqs: Dict[str, Seq] = {}  # what this file holds, per uid
        # per-writer last contiguous idx (gap detection)
        self._last_idx: Dict[str, int] = {}

        self._recover()
        self._open_next()

        self._thread: Optional[threading.Thread] = None
        if threaded:
            self._thread = threading.Thread(target=self._run, name="ra-wal", daemon=True)
            self._thread.start()

    # ------------------------------------------------------------------
    # public API

    def write(
        self, uid: str, idx: int, term: int, payload: bytes, sparse: bool = False
    ) -> bool:
        """Queue an append. ``sparse`` marks out-of-order live-entry
        writes (snapshot install pre-phase) that bypass gap detection.
        Returns False when the WAL is closed."""
        with self._cv:
            if self._closed:
                return False
            self._queue.append(("s" if sparse else "w", uid, idx, term, payload))
            self._cv.notify()
        return True

    def truncate_write(self, uid: str, idx: int) -> bool:
        """Record an explicit truncate-from marker (divergent suffix
        rewrite starts at idx)."""
        with self._cv:
            if self._closed:
                return False
            self._queue.append(("t", uid, idx, 0, b""))
            self._cv.notify()
        return True

    def last_writer_seq(self, uid: str) -> Optional[int]:
        with self._lock:
            return self._last_idx.get(uid)

    def flush(self) -> None:
        """Drain and persist everything queued (synchronous mode / tests;
        also used for orderly shutdown)."""
        while True:
            with self._lock:
                batch = self._take_batch_locked()
            if not batch:
                return
            self._write_batch(batch)

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self.flush()
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None

    # ------------------------------------------------------------------
    # writer loop

    def _run(self) -> None:
        while True:
            with self._cv:
                while not self._queue and not self._closed:
                    self._cv.wait(timeout=0.5)
                if self._closed and not self._queue:
                    return
                batch = self._take_batch_locked()
            if batch:
                self._write_batch(batch)

    def _take_batch_locked(self) -> List[Tuple]:
        batch = []
        while self._queue and len(batch) < self.max_batch_size:
            batch.append(self._queue.popleft())
        return batch

    def _write_batch(self, batch: List[Tuple]) -> None:
        # first pass: bookkeeping + record collection; second: framing
        # (natively when ra_tpu.native built) + one write/fsync
        records: List[Tuple[int, int, int, int, bytes]] = []
        # (uid, term) -> indexes written in this batch
        written: Dict[Tuple[str, int], List[int]] = {}
        resends: List[Tuple[str, int]] = []
        for kind, uid, idx, term, payload in batch:
            if kind == "t":
                ref = self._uid_ref(uid, records)
                records.append((K_TRUNC, ref, idx, 0, b""))
                self._last_idx[uid] = idx - 1
                self._file_seqs[uid] = self._file_seqs.get(uid, Seq.empty()).limit(idx - 1)
                continue
            snap_idx = self.tables.snapshot_index(uid)
            # drop writes below the snapshot floor (dead indexes); they
            # still count as durable for the writer's bookkeeping
            if idx <= snap_idx and idx not in self.tables.live_indexes(uid):
                written.setdefault((uid, term), []).append(idx)
                self._last_idx[uid] = max(self._last_idx.get(uid, 0), idx)
                continue
            if kind != "s":
                last = self._last_idx.get(uid)
                # indexes at or below the snapshot are durable-or-dead, so
                # a jump to snap_idx+1 after a snapshot install is in-seq
                if last is not None and idx > max(last, snap_idx) + 1:
                    # gap: a write got lost upstream — ask the server to
                    # resend from the hole instead of persisting out of
                    # order
                    self.counter.incr("out_of_seq")
                    resends.append((uid, max(last, snap_idx) + 1))
                    continue
            ref = self._uid_ref(uid, records)
            records.append((K_ENTRY, ref, idx, term, payload))
            seq = self._file_seqs.get(uid, Seq.empty())
            if kind == "s":
                # sparse writes never imply truncation of higher indexes
                self._last_idx[uid] = max(self._last_idx.get(uid, 0), idx)
                self._file_seqs[uid] = seq.add(idx)
            else:
                self._last_idx[uid] = idx
                if idx <= (seq.last() or 0):
                    seq = seq.limit(idx - 1)  # overwrite rewinds
                self._file_seqs[uid] = seq.add(idx)
            written.setdefault((uid, term), []).append(idx)

        if records:
            buf = self._frame(records)
            self._file.write(buf)
            self._sync()
            self.counter.incr("batches")
            self.counter.incr("writes", len(batch))
            self.counter.incr("bytes_written", len(buf))
            self.counter.put("batch_size", len(batch))
            self._bytes += len(buf)
        for (uid, term), idxs in written.items():
            self.notify(uid, ("written", term, Seq.from_list(idxs)))
        for uid, from_idx in resends:
            self.notify(uid, ("resend_write", from_idx))
        if self._bytes >= self.max_size_bytes:
            self._rollover()

    def _sync(self) -> None:
        self._file.flush()
        if self.sync_method == "datasync":
            os.fdatasync(self._file.fileno())
            self.counter.incr("fsyncs")
        elif self.sync_method == "sync":
            os.fsync(self._file.fileno())
            self.counter.incr("fsyncs")

    def _uid_ref(self, uid: str, records: List[Tuple]) -> int:
        ref = self._uid_refs.get(uid)
        if ref is None:
            ref = len(self._uid_refs) + 1
            self._uid_refs[uid] = ref
            ub = uid.encode()
            records.append((K_UID, ref, len(ub), 0, ub))
        return ref

    def _frame(self, records: List[Tuple[int, int, int, int, bytes]]) -> bytes:
        """Frame records for the file — native C++ when available
        (ra_tpu.native.wal_native), byte-identical Python fallback."""
        if self._native:
            from ra_tpu import native

            out = native.frame_batch(records, compute_crc=self.compute_checksums)
            if out is not None:
                return out
            self._native = False  # build failed: stay on the fallback
        buf = bytearray()
        for kind, ref, idx, term, payload in records:
            if kind == K_UID:
                buf += _UID_HDR.pack(K_UID, ref, len(payload))
                buf += payload
            elif kind == K_TRUNC:
                buf += _TRUNC_HDR.pack(K_TRUNC, ref, idx)
            else:
                crc = (
                    zlib.crc32(struct.pack("<QQ", idx, term) + payload)
                    if self.compute_checksums
                    else 0
                )
                buf += _ENTRY_HDR.pack(K_ENTRY, ref, idx, term, crc, len(payload))
                buf += payload
        return bytes(buf)

    # ------------------------------------------------------------------
    # rollover & recovery

    def _open_next(self) -> None:
        self._file_num += 1
        self._file_path = os.path.join(self.dir, f"{self._file_num:08d}.wal")
        self._file = open(self._file_path, "ab")
        if self._file.tell() == 0:
            self._file.write(MAGIC)
            self._file.flush()
        self._bytes = self._file.tell()
        self._uid_refs = {}
        self._file_seqs = {}
        self.counter.incr("wal_files")

    def _rollover(self) -> None:
        self.counter.incr("rollovers")
        self._file.close()
        full_path, seqs = self._file_path, self._file_seqs
        self._open_next()
        if self.segment_writer is not None:
            self.segment_writer.flush_mem_tables(
                {uid: seq for uid, seq in seqs.items() if not seq.is_empty()},
                wal_file=full_path,
            )
        else:
            os.unlink(full_path)

    def force_rollover(self) -> None:
        """Test/ops hook: roll the current file regardless of size."""
        with self._lock:
            self._rollover()

    def _recover(self) -> None:
        """Re-read surviving WAL files into memtables and hand them to the
        segment writer, then start from a fresh file."""
        files = sorted(
            f for f in os.listdir(self.dir) if f.endswith(".wal")
        )
        from ra_tpu.protocol import Entry
        import pickle

        for fname in files:
            path = os.path.join(self.dir, fname)
            seqs: Dict[str, Seq] = {}
            uids: Dict[int, str] = {}
            try:
                data = open(path, "rb").read()
            except OSError:
                continue
            if not data.startswith(MAGIC):
                os.unlink(path)
                continue
            pos = 4
            n = len(data)
            while pos < n:
                kind = data[pos]
                try:
                    if kind == K_UID:
                        _, ref, ln = _UID_HDR.unpack_from(data, pos)
                        pos += _UID_HDR.size
                        uids[ref] = data[pos : pos + ln].decode()
                        pos += ln
                    elif kind == K_TRUNC:
                        _, ref, idx = _TRUNC_HDR.unpack_from(data, pos)
                        pos += _TRUNC_HDR.size
                        uid = uids[ref]
                        self.tables.mem_table(uid).truncate_from(idx)
                        seqs[uid] = seqs.get(uid, Seq.empty()).limit(idx - 1)
                        self._last_idx[uid] = idx - 1
                    elif kind == K_ENTRY:
                        _, ref, idx, term, crc, ln = _ENTRY_HDR.unpack_from(data, pos)
                        pos += _ENTRY_HDR.size
                        payload = data[pos : pos + ln]
                        if len(payload) < ln:
                            break  # torn tail
                        pos += ln
                        if self.compute_checksums and crc:
                            if zlib.crc32(struct.pack("<QQ", idx, term) + payload) != crc:
                                break  # corrupt tail
                        uid = uids[ref]
                        mt = self.tables.mem_table(uid)
                        mt.insert(Entry(idx, term, pickle.loads(payload)))
                        seq = seqs.get(uid, Seq.empty())
                        if idx <= (seq.last() or 0):
                            seq = seq.limit(idx - 1)
                        seqs[uid] = seq.add(idx)
                        self._last_idx[uid] = idx
                    else:
                        break  # unknown/corrupt: stop at tail
                except (struct.error, KeyError, IndexError, EOFError):
                    break
            live = {u: s for u, s in seqs.items() if not s.is_empty()}
            if self.segment_writer is not None and live:
                self.segment_writer.flush_mem_tables(live, wal_file=path)
            elif not live:
                os.unlink(path)
            # else: no segment writer configured — the file is the only
            # durable copy of these entries (the memtable rebuild above is
            # RAM only), so it must survive until a segment writer flushes
            # it; recovery re-reads it next boot (idempotent inserts)
            num = int(fname.split(".")[0])
            self._file_num = max(self._file_num, num)

    def overview(self) -> Dict[str, Any]:
        return {
            "file": self._file_path,
            "bytes": self._bytes,
            "writers": len(self._last_idx),
            "counters": self.counter.to_dict(),
        }
