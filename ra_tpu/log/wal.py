"""Shared write-ahead log.

One WAL per system serves *all* raft groups on the node: every append
from every group funnels into one append-only file and one fsync per
batch — the amortization trick at the heart of the reference's design
(reference: ``src/ra_log_wal.erl`` — gen_batch_server batching, writer-id
dictionary compression :482-499, per-writer gap detection :551-586,
rollover handing memtable seqs to the segment writer :641-688, chunked
recovery :393-470).

File format (little-endian):

    header   : magic b"RTW1"
    uid-def  : kind=1 | ref u16 | len u16 | uid utf-8
    entry    : kind=2 | ref u16 | idx u64 | term u64 | crc u32 | len u32
               | payload
    trunc    : kind=3 | ref u16 | idx u64   (explicit truncate-from marker)

CRC32 covers idx|term|payload. A short/corrupt tail record is treated as
a clean EOF (torn final write), matching standard WAL recovery rules.

Threading: producers call ``write``/``truncate_write`` from any thread; a
single writer thread drains the queue in batches of up to
``max_batch_size``, performs one write+fsync, then fires the per-writer
``("written", term, seq)`` notifications. ``threaded=False`` gives tests
a deterministic ``flush()``-driven mode.
"""

from __future__ import annotations

import os
import struct
import threading
import time
import zlib
from collections import deque
from typing import Any, Callable, Dict, List, Optional, Tuple

from ra_tpu import counters as ra_counters
from ra_tpu import faults
from ra_tpu.log.tables import TableRegistry
from ra_tpu.utils.lib import retry
from ra_tpu.utils.seq import Seq

MAGIC = b"RTW1"


class WalCorruptionError(RuntimeError):
    """Mid-file WAL corruption: an unreadable record with VALID DATA
    after it. Recovery refuses to silently drop acked entries — this is
    bit rot or tampering, not a torn tail (a partial FINAL record, with
    nothing but zero padding or EOF beyond, truncates cleanly instead).
    Reference behavior: checksum_failure_in_middle_of_file_should_fail
    vs recover_with_partial_last_entry (test/ra_log_wal_SUITE.erl)."""


K_UID = 1
K_ENTRY = 2
K_TRUNC = 3
K_SPARSE = 4  # entry layout; no gap/truncate semantics on recovery
# in-memory record marker for a contiguous same-writer run; expanded to
# per-entry K_ENTRY frames at framing time (never written to disk).
# Value mirrored in ra_tpu/native/__init__.py.
K_RUN = 100

_ENTRY_HDR = struct.Struct("<BHQQII")
_UID_HDR = struct.Struct("<BHH")
_TRUNC_HDR = struct.Struct("<BHQ")

NotifyFn = Callable[[str, Any], None]


class Wal:
    def __init__(
        self,
        dir: str,
        tables: TableRegistry,
        notify: NotifyFn,
        segment_writer=None,
        max_size_bytes: int = 256 * 1024 * 1024,
        max_batch_size: int = 8192,
        sync_method: str = "datasync",  # datasync | sync | none
        compute_checksums: bool = True,
        threaded: bool = True,
        counter=None,
        native: bool = True,
        group_commit_max_delay_s: float = 0.002,
        group_commit_min_gain: int = 8,
    ):
        self.dir = dir
        os.makedirs(dir, exist_ok=True)
        self.tables = tables
        self.notify = notify
        # optional bulk channel: called with [(uid, event), ...] once
        # per batch instead of one notify() per writer (hosts that route
        # events through a shared lock set this — e.g. a coordinator's
        # deliver_many)
        self.notify_many: Optional[Callable[[List[Tuple[str, Any]]], None]] = None
        self.segment_writer = segment_writer
        self.max_size_bytes = max_size_bytes
        self.max_batch_size = max_batch_size
        self.sync_method = sync_method
        self.compute_checksums = compute_checksums
        # failpoint scope label (multi-node tests target one node's
        # storage); the owning node sets it to its name
        self.fault_scope: Optional[str] = None
        # resolve (and if needed g++-build) the native framer NOW, off the
        # commit path — a lazy first-batch build would stall every queued
        # append behind a compiler run
        if native:
            from ra_tpu import native as _native

            native = _native.available()
        self._native = native
        # adaptive group commit (docs/INTERNALS.md §15): a flush may
        # hold its batch open for up to ``group_commit_max_delay_s``
        # while a burst is still arriving, so the burst pays ONE fsync.
        # The wait is entered only when the smoothed arrival rate
        # predicts at least ``group_commit_min_gain`` more entries
        # within the bound — an idle write never waits on a timer.
        self.group_commit_max_delay_s = group_commit_max_delay_s
        self.group_commit_min_gain = group_commit_min_gain
        from ra_tpu.li import LeakyIntegrator

        self._gc_rate = LeakyIntegrator()
        self._gc_t = time.monotonic()
        # fsync-wait and batch-flush histograms (docs/INTERNALS.md §13);
        # keyed by the WAL directory's basename so every WAL in a
        # multi-node process exports its own distribution
        from ra_tpu import obs as _obs

        _norm = os.path.normpath(dir)
        _parent = os.path.basename(os.path.dirname(_norm))
        _scope = (
            f"{_parent}/{os.path.basename(_norm)}" if _parent
            else (os.path.basename(_norm) or "wal")
        )
        self._scope = _scope
        # registered vector (scrapeable): the group-commit delay gauge
        # and flush counters ride the same exposition as the histograms
        self.counter = counter or ra_counters.new(
            ("wal", _scope), ra_counters.WAL_FIELDS
        )
        self._h_fsync = _obs.histogram(
            ("wal", _scope, "fsync"), help="WAL fsync/fdatasync wait"
        )
        self._h_batch = _obs.histogram(
            ("wal", _scope, "batch"),
            help="WAL batch flush (frame + write + fsync + notify)",
        )
        self._h_flush_wait = _obs.histogram(
            ("wal", _scope, "flush_wait"),
            help="adaptive group-commit coalescing wait before a flush",
        )
        self._obs_rec = _obs.flight_recorder()
        # batch flushes land on the wave timeline too (their own lane
        # per WAL scope) so Perfetto shows fsync work overlapping the
        # coordinator's device/host phases; one attr check while off
        self._trace = _obs.trace_buffer()

        self._lock = threading.Lock()
        self._cv = threading.Condition(self._lock)
        self._queue: deque = deque()
        self._closed = False
        # failure handling: an I/O error flips the WAL into a failed
        # state (writes rejected) until reopen() rolls a fresh file —
        # the analog of the reference WAL process crashing and being
        # supervisor-restarted (src/ra_log_wal.erl + ra_log_wal_sup)
        self._failed = False
        # errno-aware failure taxonomy (docs/INTERNALS.md §21): set
        # alongside _failed to "space" (ENOSPC/EDQUOT — durable state
        # provably untouched, node degrades and probe-resumes) or
        # "integrity" (everything else — the poison path, unchanged)
        self.failure_class: Optional[str] = None
        self.on_failure: Optional[Callable[[BaseException], None]] = None
        # serializes file I/O (writer thread) against reopen() (restart
        # thread) — without it a reopen can close the file mid-write
        self._io_lock = threading.Lock()

        # per-open-file state
        self._file = None
        self._file_num = 0
        self._file_path: Optional[str] = None
        self._bytes = 0
        self._uid_refs: Dict[str, int] = {}
        # what this file holds: per uid, per memtable table id
        self._file_seqs: Dict[str, Dict[int, Seq]] = {}
        # per-writer last contiguous idx (gap detection)
        self._last_idx: Dict[str, int] = {}

        self._recover()
        self._open_next()

        self._thread: Optional[threading.Thread] = None
        if threaded:
            # arm-waker: the idle loop below blocks UNTIMED when no
            # wal.thread failpoint is armed; arming one while the
            # writer is parked must wake it so the crash bites within
            # one wakeup even with zero traffic (docs/INTERNALS.md §16)
            faults.on_arm(self._arm_wake)
            self._thread = threading.Thread(target=self._run, name="ra-wal", daemon=True)
            self._thread.start()

    def _arm_wake(self) -> None:
        with self._cv:
            self._cv.notify_all()

    # ------------------------------------------------------------------
    # public API

    def write(
        self, uid: str, idx: int, term: int, payload: bytes,
        sparse: bool = False, tid: int = 0,
    ) -> bool:
        """Queue an append. ``sparse`` marks out-of-order live-entry
        writes (snapshot install pre-phase) that bypass gap detection;
        ``tid`` names the memtable table holding the entry (successor
        chains — the segment writer flushes from exactly that table).
        Returns False when the WAL is closed."""
        with self._cv:
            if self._closed or self._failed:
                return False
            self._queue.append(("s" if sparse else "w", uid, idx, term, payload, tid))
            if len(self._queue) == 1:
                # a non-empty queue already has a wakeup in flight (or
                # the writer is mid-flush and re-checks before waiting);
                # per-append notifies were a measurable share of a
                # 10k-group wave's enqueue fan-out
                self._cv.notify()
        return True

    def write_run(self, uid: str, first: int, terms, payloads, tid: int = 0) -> bool:
        """Queue a contiguous ascending run of appends as ONE queue item
        (the pipelined hot path: the writer loop does run-level — not
        per-entry — bookkeeping, and framing expands the run natively).
        ``terms[k]``/``payloads[k]`` belong to index ``first + k``; all
        entries live in memtable table ``tid``."""
        if not payloads:
            # an empty run must not rewind _last_idx to first-1 in the
            # writer loop or frame a zero-entry K_RUN record
            return True
        with self._cv:
            if self._closed or self._failed:
                return False
            self._queue.append(("r", uid, first, terms, payloads, tid))
            if len(self._queue) == 1:
                self._cv.notify()
        return True

    def truncate_write(self, uid: str, idx: int) -> bool:
        """Record an explicit truncate-from marker (divergent suffix
        rewrite starts at idx)."""
        with self._cv:
            if self._closed or self._failed:
                return False
            self._queue.append(("t", uid, idx, 0, b"", 0))
            if len(self._queue) == 1:
                self._cv.notify()
        return True

    def last_writer_seq(self, uid: str) -> Optional[int]:
        with self._lock:
            return self._last_idx.get(uid)

    def flush(self) -> None:
        """Drain and persist everything queued (synchronous mode / tests;
        also used for orderly shutdown)."""
        while True:
            with self._lock:
                batch = self._take_batch_locked()
            if not batch:
                return
            t0 = time.perf_counter_ns()
            self._write_batch(batch)
            dt = time.perf_counter_ns() - t0
            self._h_batch.record(dt)
            if self._trace.enabled:
                self._trace.span("wal_batch", f"wal:{self._scope}", t0, dt,
                                 cat="wal")

    def close(self) -> None:
        faults.off_arm(self._arm_wake)
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5)
        self.flush()
        with self._lock:
            if self._file is not None:
                self._file.close()
                self._file = None
        # unregister OUR counter vector only (a restart may have
        # registered a successor under the same scope already)
        if ra_counters.fetch(("wal", self._scope)) is self.counter:
            ra_counters.delete(("wal", self._scope))

    # ------------------------------------------------------------------
    # writer loop

    def _run(self) -> None:
        while True:
            # injected thread death (ThreadCrash is a BaseException: it
            # falls through the except below and kills the thread; the
            # node's infra supervisor detects and heals)
            faults.fire("wal.thread", self.fault_scope)
            with self._cv:
                while not self._queue and not self._closed:
                    # event-driven idle (docs/INTERNALS.md §16):
                    # producers notify on empty->non-empty, close()
                    # notifies all, and faults.arm() nudges via the
                    # arm-waker — an idle WAL writer consumes zero
                    # CPU. The timed tick survives ONLY while a
                    # wal.thread failpoint is armed: a crash_thread
                    # nemesis must keep biting within one tick while
                    # its trigger (e.g. prob) rolls the dice
                    if faults.any_armed("wal.thread"):
                        self._cv.wait(timeout=0.5)
                    else:
                        self._cv.wait()
                    # idle loop checks the site too (the cv lock
                    # releases on unwind)
                    faults.fire("wal.thread", self.fault_scope)
                if self._closed and not self._queue:
                    return
                batch = self._take_batch_locked()
            if batch:
                try:
                    batch = self._coalesce(batch)
                    t0 = time.perf_counter_ns()
                    self._write_batch(batch)
                    dt = time.perf_counter_ns() - t0
                    self._h_batch.record(dt)
                    if self._trace.enabled:
                        self._trace.span("wal_batch", f"wal:{self._scope}",
                                         t0, dt, cat="wal")
                except Exception as exc:  # noqa: BLE001
                    # any unexpected error is a failure episode, same as
                    # a file I/O error: the batch is unacked (servers
                    # resend after reopen) and the writer thread LIVES —
                    # a silently dead WAL thread would wedge every
                    # server on the node. BaseExceptions still kill the
                    # thread; the node's infra supervisor revives it.
                    self._fail(exc)

    def _take_batch_locked(self) -> List[Tuple]:
        batch = []
        while self._queue and len(batch) < self.max_batch_size:
            batch.append(self._queue.popleft())
        return batch

    def _coalesce(self, batch: List[Tuple]) -> List[Tuple]:
        """Adaptive group commit: hold a small batch open for up to
        ``group_commit_max_delay_s`` while a burst is still arriving,
        so the whole burst rides one write+fsync instead of several.

        Policy (docs/INTERNALS.md §15):
        - the smoothed arrival rate must predict >= ``group_commit_min_
          gain`` further entries inside the delay bound, or the batch
          flushes immediately — an unloaded write never waits;
        - a batch already at half ``max_batch_size`` flushes now;
        - within the wait, the batch extends every time new items land
          and flushes the moment a wait interval brings nothing (the
          burst drained) or the deadline/batch cap is hit.

        Threaded writer loop only — ``flush()`` (tests, shutdown) stays
        deterministic and never waits."""
        d = self.group_commit_max_delay_s
        # update the arrival-rate estimate on every flush (batch items
        # per elapsed wall time since the previous flush decision)
        now = time.monotonic()
        # window floor: a lone write moments after the previous flush
        # decision must not read as a high-rate burst — rate is "items
        # per recent 25ms+ window", so only sustained arrival streams
        # clear the coalescing gate
        rate = self._gc_rate.sample(len(batch), max(now - self._gc_t, 0.025))
        self._gc_t = now
        if (
            d <= 0
            or len(batch) >= self.max_batch_size // 2
            or rate * d < self.group_commit_min_gain
        ):
            self.counter.put("group_commit_delay_us", 0)
            return batch
        t0 = time.perf_counter_ns()
        deadline = t0 + int(d * 1e9)
        tick = d / 4
        while True:
            with self._cv:
                if self._closed:
                    break
                if not self._queue:
                    self._cv.wait(timeout=tick)
                got = len(self._queue)
                while self._queue and len(batch) < self.max_batch_size:
                    batch.append(self._queue.popleft())
            if (
                got == 0  # a whole interval brought nothing: burst over
                or len(batch) >= self.max_batch_size
                or time.perf_counter_ns() >= deadline
            ):
                break
        dt = time.perf_counter_ns() - t0
        self._h_flush_wait.record(dt)
        self.counter.incr("group_commit_waits")
        self.counter.put("group_commit_delay_us", dt // 1000)
        # the wait itself feeds the estimate too (long quiet waits decay
        # the rate so the NEXT lone write flushes immediately)
        now = time.monotonic()
        self._gc_rate.sample(0, now - self._gc_t)
        self._gc_t = now
        return batch

    def _write_batch(self, batch: List[Tuple]) -> None:
        # first pass: bookkeeping + record collection; second: framing
        # (natively when ra_tpu.native built) + one write/fsync.
        # Per-(uid, table) index accumulation is BATCH-LEVEL and
        # RUN-LEVEL: indexes collect into (lo, hi) pair lists and merge
        # into the file seqs once per uid — per-entry Seq unions (plus
        # per-entry snapshot floor lookups) dominated the whole WAL at
        # 10k-group batches, and "r" run items process a whole
        # contiguous append run with O(1) bookkeeping.
        records: List[Tuple] = []
        # (uid, term) -> (lo, hi) pairs written in this batch
        written: Dict[Tuple[str, int], List[Tuple[int, int]]] = {}
        resends: List[Tuple[str, int]] = []
        # uid -> [last_any_idx, {tid: [(lo, hi), ...]}] pending in batch
        acc: Dict[str, list] = {}
        # uid -> [snap_idx, live_indexes-or-None] (one lookup per uid)
        snap_cache: Dict[str, list] = {}
        n_entries = 0

        def flush_uid(uid: str, info) -> None:
            per_uid = self._file_seqs.setdefault(uid, {})
            for t, pairs in info[1].items():
                cur = per_uid.get(t)
                add = Seq(pairs)
                per_uid[t] = add if cur is None or cur.is_empty() else cur.union(add)
            info[1] = {}

        def get_info(uid: str):
            info = acc.get(uid)
            if info is None:
                per_uid = self._file_seqs.setdefault(uid, {})
                last_any = max((sq.last() or 0 for sq in per_uid.values()), default=0)
                info = acc[uid] = [last_any, {}]
            return info

        def get_snap(uid: str):
            sc = snap_cache.get(uid)
            if sc is None:
                sc = snap_cache[uid] = [self.tables.snapshot_index(uid), None]
            return sc

        def note_pair(pairs_by_key, key, lo: int, hi: int) -> None:
            pend = pairs_by_key.get(key)
            if pend is None:
                pairs_by_key[key] = [(lo, hi)]
            else:
                tlo, thi = pend[-1]
                if thi + 1 == lo:
                    pend[-1] = (tlo, hi)
                else:
                    pend.append((lo, hi))

        def one(kind, uid, idx, term, payload, tid) -> None:
            nonlocal n_entries
            sc = get_snap(uid)
            snap_idx = sc[0]
            if idx <= snap_idx:
                # drop writes below the snapshot floor (dead indexes);
                # they still count as durable for writer bookkeeping
                if sc[1] is None:
                    sc[1] = self.tables.live_indexes(uid)
                if idx not in sc[1]:
                    note_pair(written, (uid, term), idx, idx)
                    self._last_idx[uid] = max(self._last_idx.get(uid, 0), idx)
                    return
            if kind != "s":
                last = self._last_idx.get(uid)
                # indexes at or below the snapshot are durable-or-dead, so
                # a jump to snap_idx+1 after a snapshot install is in-seq
                if last is not None and idx > max(last, snap_idx) + 1:
                    # gap: a write got lost upstream — ask the server to
                    # resend from the hole instead of persisting out of
                    # order
                    self.counter.incr("out_of_seq")
                    resends.append((uid, max(last, snap_idx) + 1))
                    return
            ref = self._uid_ref(uid, records)
            records.append((K_SPARSE if kind == "s" else K_ENTRY, ref, idx, term, payload))
            n_entries += 1
            info = get_info(uid)
            if kind == "s":
                # sparse writes never imply truncation of higher indexes
                self._last_idx[uid] = max(self._last_idx.get(uid, 0), idx)
                if idx > info[0]:
                    info[0] = idx
            else:
                self._last_idx[uid] = idx
                if idx <= info[0]:
                    # overwrite rewinds this file's view across ALL
                    # tables of the uid (superseded entries), including
                    # indexes still pending in this batch
                    flush_uid(uid, info)
                    per_uid = self._file_seqs[uid]
                    for t in list(per_uid):
                        per_uid[t] = per_uid[t].limit(idx - 1)
                info[0] = idx
            note_pair(info[1], tid, idx, idx)
            note_pair(written, (uid, term), idx, idx)

        for item in batch:
            kind = item[0]
            if kind == "r":
                _, uid, first, terms, payloads, tid = item
                m = len(payloads)
                snap_idx = get_snap(uid)[0]
                if first <= snap_idx:
                    # run overlaps the snapshot floor (rare): per-entry
                    # path keeps the dead-index filtering exact
                    for k in range(m):
                        one("w", uid, first + k, terms[k], payloads[k], tid)
                    continue
                last = self._last_idx.get(uid)
                if last is not None and first > max(last, snap_idx) + 1:
                    self.counter.incr("out_of_seq")
                    resends.append((uid, max(last, snap_idx) + 1))
                    continue
                last_e = first + m - 1
                ref = self._uid_ref(uid, records)
                records.append((K_RUN, ref, first, terms, payloads))
                n_entries += m
                info = get_info(uid)
                self._last_idx[uid] = last_e
                if first <= info[0]:
                    flush_uid(uid, info)
                    per_uid = self._file_seqs[uid]
                    for t in list(per_uid):
                        per_uid[t] = per_uid[t].limit(first - 1)
                info[0] = last_e
                note_pair(info[1], tid, first, last_e)
                # written events key on (uid, term): split multi-term runs
                if terms[0] == terms[-1]:
                    note_pair(written, (uid, terms[0]), first, last_e)
                else:
                    lo, t0 = first, terms[0]
                    for k in range(1, m):
                        if terms[k] != t0:
                            note_pair(written, (uid, t0), lo, first + k - 1)
                            lo, t0 = first + k, terms[k]
                    note_pair(written, (uid, t0), lo, last_e)
            elif kind == "t":
                _, uid, idx, _term, _payload, _tid = item
                info = acc.get(uid)
                if info is not None:
                    flush_uid(uid, info)
                    info[0] = idx - 1
                ref = self._uid_ref(uid, records)
                records.append((K_TRUNC, ref, idx, 0, b""))
                self._last_idx[uid] = idx - 1
                for t, sq in self._file_seqs.get(uid, {}).items():
                    self._file_seqs[uid][t] = sq.limit(idx - 1)
            else:
                one(kind, item[1], item[2], item[3], item[4], item[5])

        for uid, info in acc.items():
            if info[1]:
                flush_uid(uid, info)

        if records:
            err = None
            n_bytes = None
            # native hot path: ONE call frames + writes + fsyncs the
            # whole batch (no Python-side byte assembly or copy). Any
            # armed write/fsync failpoint routes through the Python
            # path so injection semantics stay byte-exact with tests —
            # as does an instance-level ``_sync`` override (the WAL-
            # death injection seam tests/self-healing rely on).
            if (
                self._native
                and "_sync" not in self.__dict__
                and not faults.any_armed("wal.write", "wal.fsync")
            ):
                from ra_tpu import native

                with self._io_lock:
                    if self._failed:
                        return  # failed window: batch unacked, drop it
                    try:
                        self._file.flush()
                        got = native.write_batch(
                            records, self._file.fileno(), self.sync_method,
                            compute_crc=self.compute_checksums,
                        )
                    except (OSError, ValueError) as exc:
                        err = exc
                        got = None
                if err is None:
                    if got is None:
                        self._native = False  # lib lost/format miss: fall back
                        self.counter.incr("native_fallbacks")
                    else:
                        n_bytes, fsync_ns = got
                        self.counter.incr("native_batches")
                        if self.sync_method in ("datasync", "sync"):
                            self.counter.incr("fsyncs")
                            self.counter.incr("fsync_time_us", fsync_ns // 1000)
                            self._h_fsync.record(fsync_ns)
            if err is None and n_bytes is None:
                buf = self._frame(records)
                n_bytes = len(buf)
                with self._io_lock:
                    if self._failed:
                        return  # failed window: batch is unacked, drop it
                    try:
                        faults.checked_write("wal.write", self._file, buf,
                                             self.fault_scope)
                        self._sync()
                    except (OSError, ValueError) as exc:
                        err = exc
            if err is not None:
                # the whole batch is unacked (no written events fire) —
                # entries survive in memtables; servers hold/resend once
                # reopen() brings a fresh file up. (_fail outside the io
                # lock: it takes the queue lock, which reopen holds
                # while waiting for the io lock.)
                self._fail(err)
                return
            self.counter.incr("batches")
            # 'writes'/'batch_size' count QUEUE ITEMS (incl. truncate
            # markers and dead-index-dropped writes) — the pre-run-record
            # semantics dashboards may rely on; 'entries' counts the
            # expanded log entries actually framed (runs widened)
            self.counter.incr("writes", len(batch))
            self.counter.incr("entries", n_entries)
            self.counter.incr("bytes_written", n_bytes)
            self.counter.put("batch_size", len(batch))
            self._bytes += n_bytes
        if self.notify_many is not None and len(written) > 1:
            # one transport/lock round for the whole batch's written
            # events (a 10k-group batch otherwise pays 10k lock rounds)
            self.notify_many(
                [(uid, ("written", term, Seq(pairs)))
                 for (uid, term), pairs in written.items()]
            )
        else:
            for (uid, term), pairs in written.items():
                self.notify(uid, ("written", term, Seq(pairs)))
        for uid, from_idx in resends:
            self.notify(uid, ("resend_write", from_idx))
        if self._bytes >= self.max_size_bytes:
            self._rollover()

    def _sync(self) -> None:
        # fsync failure is POISON (fsyncgate): the page-cache state of
        # the file is unknowable afterwards, so the raise below fails
        # the whole writer (batch unacked, _failed set) and reopen()
        # abandons the file — a later fsync on the same fd must never
        # "succeed" and ack entries the kernel already dropped
        # the timed window covers the failpoint fire + flush + syscall:
        # the brownout detector differences fsyncs/fsync_time_us, and an
        # injected ("latency", s) fault must look exactly like the slow
        # device it models
        t0 = time.perf_counter_ns()
        faults.fire("wal.fsync", self.fault_scope)
        self._file.flush()
        if self.sync_method == "datasync":
            os.fdatasync(self._file.fileno())
        elif self.sync_method == "sync":
            os.fsync(self._file.fileno())
        else:
            return
        dt = time.perf_counter_ns() - t0
        self.counter.incr("fsyncs")
        self.counter.incr("fsync_time_us", dt // 1000)
        self._h_fsync.record(dt)

    def _uid_ref(self, uid: str, records: List[Tuple]) -> int:
        ref = self._uid_refs.get(uid)
        if ref is None:
            ref = len(self._uid_refs) + 1
            self._uid_refs[uid] = ref
            ub = uid.encode()
            records.append((K_UID, ref, len(ub), 0, ub))
        return ref

    def _frame(self, records: List[Tuple[int, int, int, int, bytes]]) -> bytes:
        """Frame records for the file — native C++ when available
        (ra_tpu.native.wal_native), byte-identical Python fallback."""
        if self._native:
            from ra_tpu import native

            out = native.frame_batch(records, compute_crc=self.compute_checksums)
            if out is not None:
                return out
            self._native = False  # build failed: stay on the fallback
            self.counter.incr("native_fallbacks")
        buf = bytearray()
        for rec in records:
            kind = rec[0]
            if kind == K_UID:
                _, ref, _idx, _term, payload = rec
                buf += _UID_HDR.pack(K_UID, ref, len(payload))
                buf += payload
            elif kind == K_TRUNC:
                # unpack the record's OWN ref: reusing the previous
                # iteration's ref bound a truncate marker to whatever
                # writer happened to precede it in the batch — recovery
                # would truncate the wrong log (caught by the native/
                # Python byte-parity test; the native framer was right)
                _, ref, idx, _term, _payload = rec
                buf += _TRUNC_HDR.pack(K_TRUNC, ref, idx)
            elif kind == K_RUN:
                # expand to per-entry frames (disk format is unchanged)
                _, ref, first, terms, payloads = rec
                for k, payload in enumerate(payloads):
                    idx, term = first + k, terms[k]
                    crc = (
                        zlib.crc32(struct.pack("<QQ", idx, term) + payload)
                        if self.compute_checksums
                        else 0
                    )
                    buf += _ENTRY_HDR.pack(K_ENTRY, ref, idx, term, crc,
                                           len(payload))
                    buf += payload
            else:  # K_ENTRY / K_SPARSE share the layout
                _, ref, idx, term, payload = rec
                crc = (
                    zlib.crc32(struct.pack("<QQ", idx, term) + payload)
                    if self.compute_checksums
                    else 0
                )
                buf += _ENTRY_HDR.pack(kind, ref, idx, term, crc, len(payload))
                buf += payload
        return bytes(buf)

    # ------------------------------------------------------------------
    # rollover & recovery

    def _open_next(self) -> None:
        self._file_num += 1
        self._file_path = os.path.join(self.dir, f"{self._file_num:08d}.wal")

        def _open():
            faults.fire("wal.open", self.fault_scope)
            return open(self._file_path, "ab")

        # transient open failures (EMFILE/EAGAIN bursts) retry with
        # bounded backoff (reference: ra_file.erl retries every op)
        self._file = retry(_open, attempts=3, delay_s=0.02)
        if self._file.tell() == 0:
            self._file.write(MAGIC)
            self._file.flush()
        self._bytes = self._file.tell()
        self._uid_refs = {}
        self._file_seqs = {}
        self.counter.incr("wal_files")

    def _rollover(self) -> None:
        self.counter.incr("rollovers")
        self._file.close()
        full_path, seqs = self._file_path, self._file_seqs
        self._open_next()
        if self.segment_writer is not None:
            self.segment_writer.flush_mem_tables(
                self._flush_jobs(seqs), wal_file=full_path
            )
        # no segment writer: the rolled file is the only durable copy of
        # its entries — keep it for boot-time recovery

    @staticmethod
    def _flush_jobs(seqs):
        """{uid: {tid: Seq}} -> {uid: [(tid, Seq), ...]} handoff shape
        (tid-ordered, empties dropped) — one definition for the roll and
        recovery paths."""
        jobs = {
            uid: [(t, sq) for t, sq in sorted(per.items()) if not sq.is_empty()]
            for uid, per in seqs.items()
        }
        return {uid: ts for uid, ts in jobs.items() if ts}

    def force_rollover(self) -> None:
        """Test/ops hook: roll the current file regardless of size."""
        with self._lock:
            self._rollover()

    def _fail(self, exc: BaseException) -> None:
        # both framers (native write_batch re-raises -(1000+errno) as a
        # real OSError; the Python path raises the OSError directly)
        # funnel here, so one classification covers both — the
        # native/Python parity the taxonomy tests assert is structural
        from ra_tpu.pressure import CLASS_SPACE, classify_storage_error

        klass = classify_storage_error(exc)
        with self._cv:
            if self._failed:
                return  # one failure episode -> one on_failure callback
            self._failed = True
            self.failure_class = klass
        self.counter.incr("failures")
        if klass == CLASS_SPACE:
            self.counter.incr("space_failures")
        self._obs_rec.record(
            "wal_failure", node=self.fault_scope,
            detail=f"{klass}: {type(exc).__name__}: {exc}",
        )
        cb = self.on_failure
        if cb is not None:
            try:
                cb(exc)
            except Exception:  # noqa: BLE001
                pass

    @property
    def failed(self) -> bool:
        return self._failed

    @property
    def degraded(self) -> bool:
        """True while the live failure episode is space-class: the node
        is in storage_degraded (admission rejects RA_NOSPACE, probe
        loop armed) rather than poisoned."""
        return self._failed and self.failure_class == "space"

    def thread_alive(self) -> bool:
        """Writer-thread liveness for the node's infra supervisor
        (non-threaded mode drains synchronously: always 'alive')."""
        return self._thread is None or self._thread.is_alive()

    def revive_thread(self) -> None:
        """Restart a dead writer thread (supervision; the queue and
        file state survive — un-drained writes flush on the new
        thread). Synchronized: concurrent healers must never start two
        writer threads (batch bookkeeping has no writer-side lock)."""
        with self._cv:
            self._revive_thread_locked()

    def _revive_thread_locked(self) -> None:
        if self._closed or self._thread is None or self._thread.is_alive():
            return
        self._thread = threading.Thread(target=self._run, name="ra-wal", daemon=True)
        self._thread.start()

    def reopen(self) -> bool:
        """Roll to a fresh file after a failure (the supervisor-restart
        analog). The failed file stays on disk — acked batches in it are
        durable and boot recovery re-reads it. Per-writer gap state is
        reset so servers' resent tails are accepted in-seq. Also revives
        a dead writer thread, so one code path heals both failure
        shapes (I/O error, thread death)."""
        with self._cv:
            if not self._failed:
                self._revive_thread_locked()
                return True  # another reopen already succeeded
            with self._io_lock:
                try:
                    if self._file is not None:
                        try:
                            self._file.close()
                        except OSError:
                            pass
                    self._queue.clear()  # unacked queue: servers resend
                    self._open_next()
                    # probe write: _open_next put 4 magic bytes on a
                    # fresh file, proving the filesystem extends files
                    # again; firing the write failpoint here makes an
                    # armed ENOSPC storm hold the WAL down (degraded)
                    # until the storm heals instead of letting reopen
                    # "succeed" into the next failing batch
                    faults.fire("wal.write", self.fault_scope)
                    self._last_idx = {}
                    self._failed = False
                    self.failure_class = None
                except OSError:
                    return False
            self._revive_thread_locked()
        return True

    def _recover(self) -> None:
        """Re-read surviving WAL files into memtables and hand them to the
        segment writer, then start from a fresh file."""
        files = sorted(
            f for f in os.listdir(self.dir) if f.endswith(".wal")
        )
        from ra_tpu.protocol import Entry
        import pickle

        for fname in files:
            path = os.path.join(self.dir, fname)
            live_seqs = self._recover_file(path, Entry, pickle)
            if live_seqs is None:
                continue
            if self.segment_writer is not None and live_seqs:
                self.segment_writer.flush_mem_tables(
                    self._flush_jobs(live_seqs), wal_file=path
                )
            elif not live_seqs:
                os.unlink(path)
            # else: no segment writer configured — the file is the only
            # durable copy of these entries (the memtable rebuild above is
            # RAM only), so it must survive until a segment writer flushes
            # it; recovery re-reads it next boot (idempotent inserts)
            num = int(fname.split(".")[0])
            self._file_num = max(self._file_num, num)

    # recovery streams files in bounded chunks instead of loading them
    # whole (a 256 MB WAL x several files must not need that much RAM at
    # boot; reference reads 32 MB chunks, src/ra_log_wal.erl:393-470)
    RECOVER_CHUNK = 8 * 1024 * 1024

    def _recover_file(self, path: str, Entry, pickle):
        """Parse one WAL file streaming; returns {uid: {tid: seq}} or
        None when the file was unreadable/invalid (and removed)."""
        seqs: Dict[str, Dict[int, Seq]] = {}
        uids: Dict[int, str] = {}
        try:
            f = open(path, "rb")
        except OSError:
            return None
        with f:
            if f.read(4) != MAGIC:
                f.close()
                os.unlink(path)
                return None
            buf = b""
            pos = 0
            eof = False

            def read_chunk() -> bytes:
                faults.fire("wal.recover_read", self.fault_scope)
                return f.read(self.RECOVER_CHUNK)

            def ensure(n: int) -> bool:
                nonlocal buf, pos, eof
                while len(buf) - pos < n and not eof:
                    # transient read errors retry; a persistently bad
                    # disk surfaces the OSError to boot (data may be
                    # recoverable later — never silently unlink)
                    chunk = retry(read_chunk, attempts=3, delay_s=0.02)
                    if not chunk:
                        eof = True
                        break
                    buf = buf[pos:] + chunk
                    pos = 0
                return len(buf) - pos >= n

            def fail_if_data_follows(what: str) -> None:
                """Distinguish a torn tail from mid-file corruption: any
                non-zero byte beyond the bad record means valid data
                would be silently dropped — refuse to recover."""
                rest = buf[pos:]
                if any(rest):
                    raise WalCorruptionError(
                        f"{path}: {what} at offset ~{f.tell() - len(rest)} "
                        "with data following — refusing to truncate "
                        "acked entries (restore the file or delete it "
                        "explicitly to accept the loss)"
                    )
                while True:
                    chunk = f.read(self.RECOVER_CHUNK)
                    if not chunk:
                        return
                    if any(chunk):
                        raise WalCorruptionError(
                            f"{path}: {what} with data following — "
                            "refusing to truncate acked entries"
                        )

            while True:
                if not ensure(1):
                    break
                kind = buf[pos]
                try:
                    if kind == K_UID:
                        if not ensure(_UID_HDR.size):
                            break
                        _, ref, ln = _UID_HDR.unpack_from(buf, pos)
                        if not ensure(_UID_HDR.size + ln):
                            break
                        pos += _UID_HDR.size
                        uids[ref] = buf[pos : pos + ln].decode()
                        pos += ln
                    elif kind == K_TRUNC:
                        if not ensure(_TRUNC_HDR.size):
                            break
                        _, ref, idx = _TRUNC_HDR.unpack_from(buf, pos)
                        pos += _TRUNC_HDR.size
                        uid = uids[ref]
                        self.tables.mem_table(uid).truncate_from(idx)
                        for t in list(seqs.get(uid, {})):
                            seqs[uid][t] = seqs[uid][t].limit(idx - 1)
                        self._last_idx[uid] = idx - 1
                    elif kind in (K_ENTRY, K_SPARSE):
                        if not ensure(_ENTRY_HDR.size):
                            break
                        _, ref, idx, term, crc, ln = _ENTRY_HDR.unpack_from(buf, pos)
                        if ln > max(self.max_size_bytes, 1 << 30):
                            # the length field is unprotected by the
                            # record CRC; an implausible value is a bit
                            # flip, not a torn write (a low-byte flip is
                            # caught by the CRC check below instead)
                            raise WalCorruptionError(
                                f"{path}: implausible record length {ln} "
                                "— refusing to truncate acked entries"
                            )
                        if not ensure(_ENTRY_HDR.size + ln):
                            break  # torn tail
                        pos += _ENTRY_HDR.size
                        payload = buf[pos : pos + ln]
                        pos += ln
                        if self.compute_checksums and crc:
                            if zlib.crc32(struct.pack("<QQ", idx, term) + payload) != crc:
                                # torn FINAL record truncates; corruption
                                # with live data after it must fail loud
                                fail_if_data_follows("checksum failure")
                                break
                        uid = uids[ref]
                        # pre-init registered this uid's snapshot floor
                        # before recovery ran: skip dead indexes instead
                        # of resurrecting them (reference:
                        # ra_log_pre_init.erl:31-45)
                        snap_idx = self.tables.snapshot_index(uid)
                        if idx <= snap_idx and idx not in self.tables.live_indexes(uid):
                            self._last_idx[uid] = max(self._last_idx.get(uid, 0), idx)
                            continue
                        mt = self.tables.mem_table(uid)
                        per = seqs.setdefault(uid, {})
                        if kind == K_SPARSE:
                            # sparse records carry no contiguity or
                            # truncation semantics: never rewind the
                            # writer watermark or clip higher entries
                            t = mt.insert_sparse(Entry(idx, term, pickle.loads(payload)))
                            per[t] = per.get(t, Seq.empty()).add(idx)
                            self._last_idx[uid] = max(self._last_idx.get(uid, 0), idx)
                            continue
                        t = mt.insert(Entry(idx, term, pickle.loads(payload)))
                        last_any = max((sq.last() or 0 for sq in per.values()), default=0)
                        if idx <= last_any:
                            for tt in list(per):
                                per[tt] = per[tt].limit(idx - 1)
                        per[t] = per.get(t, Seq.empty()).add(idx)
                        self._last_idx[uid] = idx
                    else:
                        # unknown kind byte: zero padding ends the file
                        # cleanly; anything else is corruption
                        fail_if_data_follows(f"unknown record kind {kind}")
                        break
                except (struct.error, KeyError, IndexError, EOFError):
                    fail_if_data_follows("unparseable record")
                    break
        return {
            u: {t: sq for t, sq in per.items() if not sq.is_empty()}
            for u, per in seqs.items()
            if any(not sq.is_empty() for sq in per.values())
        }

    def overview(self) -> Dict[str, Any]:
        return {
            "file": self._file_path,
            "bytes": self._bytes,
            "writers": len(self._last_idx),
            "counters": self.counter.to_dict(),
        }
