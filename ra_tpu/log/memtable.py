"""Per-server in-memory table of recent log entries.

Plays the role of the reference's ETS-backed memtables (reference:
``src/ra_mt.erl`` — strictly-monotone inserts, flush-driven deletion,
range tracking), re-designed as a plain dict + range bookkeeping owned by
the runtime's table registry (``ra_tpu.log.tables``). Entries live here
from the moment they are appended until the segment writer has flushed
them to disk; reads always prefer the memtable.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

from ra_tpu.protocol import Entry
from ra_tpu.utils.seq import Seq


class MemTable:
    __slots__ = ("uid", "entries", "_seq")

    def __init__(self, uid: str):
        self.uid = uid
        self.entries: Dict[int, Entry] = {}
        self._seq: Seq = Seq.empty()

    def insert(self, entry: Entry) -> None:
        """Insert; overwriting an existing index truncates everything at
        and above it first (divergent-suffix rewrite)."""
        if entry.index in self.entries:
            self.truncate_from(entry.index)
        self.entries[entry.index] = entry
        self._seq = self._seq.add(entry.index)

    def insert_sparse(self, entry: Entry) -> None:
        """Out-of-order insert for snapshot live entries."""
        self.entries[entry.index] = entry
        self._seq = self._seq.add(entry.index)

    def truncate_from(self, idx: int) -> None:
        for i in list(self.entries):
            if i >= idx:
                del self.entries[i]
        self._seq = self._seq.limit(idx - 1)

    def get(self, idx: int) -> Optional[Entry]:
        return self.entries.get(idx)

    def record_flushed(self, seq: Seq) -> None:
        """Delete entries the segment writer has persisted."""
        for i in seq:
            self.entries.pop(i, None)
        self._seq = self._seq.subtract(seq)

    def set_first(self, idx: int, live=None) -> None:
        """Drop everything below idx (snapshot truncation), retaining any
        indexes in `live` (a Seq of live indexes below the snapshot)."""
        for i in list(self.entries):
            if i < idx and (live is None or i not in live):
                del self.entries[i]
        kept = self._seq.floor(idx)
        if live is not None:
            kept = kept.union(self._seq.intersect(live))
        self._seq = kept

    def seq(self) -> Seq:
        return self._seq

    def range(self) -> Optional[Tuple[int, int]]:
        return self._seq.range()

    def __len__(self) -> int:
        return len(self.entries)
