"""Per-server in-memory table of recent log entries.

Plays the role of the reference's ETS-backed memtables (reference:
``src/ra_mt.erl`` — strictly-monotone inserts within one table,
**successor chaining** on overwrite or size rotation :86-225,
flush-driven deletion :439, range tracking). Entries live here from the
moment they are appended until the segment writer has flushed them.

Why chains matter: the segment writer flushes a rolled WAL file's
entries concurrently with the server possibly overwriting a divergent
suffix. Entries are therefore **never overwritten in place** (the
reference's core invariant, docs/internals/LOG.md:82-96): an overwrite
(or a table exceeding ``max_entries``) starts a successor table; the
old table keeps its entries — identified by table id — until the flush
that references them completes. Reads serve the *visible* view (newest
table first, truncations applied); flush reads address an exact table
id and see exactly what the WAL file contained.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional, Sequence, Tuple

from ra_tpu.protocol import Entry
from ra_tpu.utils.seq import Seq

# rotation threshold (reference: ?MAX_MEMTBL_ENTRIES, src/ra_mt.erl:39)
MAX_MEMTBL_ENTRIES = 1_000_000


class _Table:
    __slots__ = ("tid", "entries", "seq")

    def __init__(self, tid: int):
        self.tid = tid
        self.entries: Dict[int, Entry] = {}
        # the VISIBLE indexes of this table (truncations shrink it; the
        # entries dict keeps rows for in-flight flushes)
        self.seq: Seq = Seq.empty()


class MemTable:
    __slots__ = ("uid", "max_entries", "_tables", "_next_tid")

    def __init__(self, uid: str, max_entries: int = MAX_MEMTBL_ENTRIES):
        self.uid = uid
        self.max_entries = max_entries
        self._tables: List[_Table] = [_Table(0)]  # newest first
        self._next_tid = 1

    # -- writes ------------------------------------------------------------

    @property
    def current_tid(self) -> int:
        return self._tables[0].tid

    def _successor(self) -> _Table:
        t = _Table(self._next_tid)
        self._next_tid += 1
        self._tables.insert(0, t)
        return t

    def insert(self, entry: Entry) -> int:
        """Insert; returns the table id that took the entry. Overwriting
        an index present in the head table (divergent-suffix rewrite) or
        exceeding the rotation threshold starts a successor table —
        never an in-place mutation."""
        head = self._tables[0]
        if entry.index in head.entries or len(head.entries) >= self.max_entries:
            # visibility: everything at/above the overwritten index is
            # superseded across the whole chain
            if entry.index in head.entries:
                self._limit_visible(entry.index - 1)
            head = self._successor()
        head.entries[entry.index] = entry
        head.seq = head.seq.add(entry.index)
        return head.tid

    def insert_run(self, entries) -> Optional[int]:
        """Bulk insert of a strictly-new contiguous ascending run (the
        leader/steady-follower append path): one visibility-seq update
        for the whole run instead of per-entry copies. Returns the table
        id that took the run, or None when the run needs the per-entry
        path (overwrite of a live index or table rotation) — the caller
        then loops :meth:`insert`."""
        head = self._tables[0]
        first = entries[0].index
        if (
            first in head.entries
            or len(head.entries) + len(entries) > self.max_entries
        ):
            return None
        d = head.entries
        for e in entries:
            d[e.index] = e
        head.seq = head.seq.append_run(first, entries[-1].index)
        return head.tid

    def insert_sparse(self, entry: Entry) -> int:
        """Out-of-order insert for snapshot live entries (no truncation
        semantics)."""
        head = self._tables[0]
        if entry.index in head.entries:
            head = self._successor()
        head.entries[entry.index] = entry
        head.seq = head.seq.add(entry.index)
        return head.tid

    def truncate_from(self, idx: int) -> None:
        self._limit_visible(idx - 1)

    def _limit_visible(self, last: int) -> None:
        for t in self._tables:
            t.seq = t.seq.limit(last)
        self._gc_tables()

    # -- reads -------------------------------------------------------------

    def get_range(self, lo: int, hi: int) -> List[Optional[Entry]]:
        """Visible entries for ``[lo, hi]`` (None holes) in ONE pass
        over the table chain: seq RANGE intersections instead of a
        per-index membership bisect per table — the read hot path for
        AER construction and the apply loop."""
        n = hi - lo + 1
        out: List[Optional[Entry]] = [None] * n
        remaining = n
        for t in self._tables:
            if remaining == 0:
                break
            entries = t.entries
            for rlo, rhi in t.seq.ranges():
                if rhi < lo or rlo > hi:
                    continue
                for i in range(max(rlo, lo), min(rhi, hi) + 1):
                    k = i - lo
                    if out[k] is None:
                        ent = entries.get(i)
                        if ent is not None:
                            out[k] = ent
                            remaining -= 1
        return out

    def get(self, idx: int) -> Optional[Entry]:
        """Visible read: newest table first, truncations respected."""
        for t in self._tables:
            if idx in t.seq:
                e = t.entries.get(idx)
                if e is not None:
                    return e
        return None

    def get_with_tid(self, idx: int) -> Optional[Tuple[Entry, int]]:
        """Visible read returning the holding table's id (resends must
        tag WAL records with the table that actually owns the entry)."""
        for t in self._tables:
            if idx in t.seq:
                e = t.entries.get(idx)
                if e is not None:
                    return e, t.tid
        return None

    def get_from(self, tid: int, idx: int) -> Optional[Entry]:
        """Exact-table read for flush jobs: returns what that table
        holds even if a successor has since superseded the index."""
        for t in self._tables:
            if t.tid == tid:
                return t.entries.get(idx)
        return None

    # -- deletion ----------------------------------------------------------

    def record_flushed(self, seq: Seq, tid: int) -> None:
        """Delete entries the segment writer persisted from the exact
        table the WAL handed over (reference: record_flushed on tid)."""
        for t in self._tables:
            if t.tid != tid:
                continue
            for i in seq:
                t.entries.pop(i, None)
            t.seq = t.seq.subtract(seq)
        self._gc_tables()

    def set_first(self, idx: int, live=None) -> None:
        """Drop everything below idx (snapshot truncation), retaining any
        indexes in `live` (a Seq of live indexes below the snapshot)."""
        for t in self._tables:
            for i in list(t.entries):
                if i < idx and (live is None or i not in live):
                    del t.entries[i]
            kept = t.seq.floor(idx)
            if live is not None:
                kept = kept.union(t.seq.intersect(live))
            t.seq = kept
        self._gc_tables()

    def _gc_tables(self) -> None:
        # Drop non-head tables whose VISIBLE seq is empty: every row
        # still in them is superseded (truncation/overwrite made it
        # invisible; the replacement entries live in a successor with
        # their own WAL records), so pending flushes that wanted them
        # may safely skip. This bounds chain growth under leadership
        # churn — without it, superseded rows whose file seqs the WAL
        # rewound are never referenced by any flush and leak forever.
        self._tables = [self._tables[0]] + [
            t for t in self._tables[1:] if t.entries and not t.seq.is_empty()
        ]

    # -- introspection -----------------------------------------------------

    def seq(self) -> Seq:
        out = Seq.empty()
        for t in self._tables:
            out = out.union(t.seq)
        return out

    def range(self) -> Optional[Tuple[int, int]]:
        return self.seq().range()

    def num_tables(self) -> int:
        return len(self._tables)

    def __len__(self) -> int:
        return sum(len(t.entries) for t in self._tables)
