"""External log read plans.

The counterpart of the reference's ``ra_log_read_plan`` (reference:
``src/ra_log_read_plan.erl:10-31``): a server captures a small PLAN
(uid, indexes, storage locations) inside its event loop, and the CALLER
executes the actual reads outside the server process — memtable lookups
go through the node's shared TableRegistry (the ETS analog) and segment
reads open the files read-only. Heavy log reads (ra_kv-style
log-as-value-store gets) therefore never block the consensus path.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ra_tpu.protocol import Entry


@dataclasses.dataclass(frozen=True)
class ReadPlan:
    uid: str
    node_name: str
    server_dir: str  # absolute path holding the segments/ subdir
    indexes: Tuple[int, ...]

    def execute(self, registry=None) -> Dict[int, Entry]:
        """Run the reads on the CALLING thread. ``registry`` defaults to
        the process-global node registry (in-proc nodes); for a purely
        file-based read (another process) pass ``registry=False`` to
        skip memtables and read segments only."""
        import os

        out: Dict[int, Entry] = {}
        missing: List[int] = []
        mt = None
        if registry is not False:
            if registry is None:
                from ra_tpu.runtime.transport import registry as node_registry

                registry = node_registry()
            node = registry.get(self.node_name)
            if node is not None:
                mt = node.tables.mem_table(self.uid)
        for i in self.indexes:
            e = mt.get(i) if mt is not None else None
            if e is not None:
                out[i] = e
            else:
                missing.append(i)
        if missing:
            segdir = os.path.join(self.server_dir, "segments")
            if os.path.isdir(segdir):
                from ra_tpu.log.segments import SegmentSet

                # fresh read-only view; binary index mode keeps memory
                # flat for sparse reads over many segments. readonly
                # skips compaction recovery — a caller-side read must
                # not unlink the owner's in-flight compaction temps.
                segs = SegmentSet(segdir, index_mode="binary", readonly=True)
                try:
                    for i in missing:
                        e = segs.fetch(i)
                        if e is not None:
                            out[i] = e
                finally:
                    segs.close()
        return out


def exec_read_plan(plan: ReadPlan, registry=None) -> Dict[int, Entry]:
    """Module-level convenience mirroring the reference API shape."""
    return plan.execute(registry=registry)
