"""Per-server set of segment files + open-file cache + compaction.

The role of the reference's ``ra_log_segments`` (segment-ref set, FLRU
fd cache, compaction planning — ``src/ra_log_segments.erl:191-344``).

Compaction tiers:
- snapshot-floor truncation deletes whole segments with no live index
  and no tail, and minor-compacts straddling segments in place;
- **major compaction** groups adjacent below-floor segments that are
  <50% live (by entries or bytes), merges each group's live entries
  into the group's first segment, and turns the rest into symlinks —
  crash-safe via the reference's marker protocol
  (``docs/internals/COMPACTION.md:144-176``): write a
  ``<first>.compaction_group`` manifest, build ``<first>.compacting``,
  atomic-rename over the first segment, then symlink the others and
  delete the manifest. Recovery inspects the manifest to tell a
  pre-rename crash (discard partial work) from a post-rename one
  (recreate symlinks).
"""

from __future__ import annotations

import bisect
import os
import pickle
import threading
import time
from typing import Dict, List, Optional, Tuple

from ra_tpu import faults
from ra_tpu.log.segment import SegmentReader, SegmentWriterHandle
from ra_tpu.protocol import Entry
from ra_tpu.utils.flru import FLRU
from ra_tpu.utils.lib import retry, sync_dir
from ra_tpu.utils.seq import Seq

# symlinks left by major compaction are kept briefly so in-flight
# readers of the old names can finish (reference: ?SYMLINK_KEEPFOR_S,
# src/ra_log_segments.erl:41)
SYMLINK_KEEP_S = 60.0


class SegmentSet:
    def __init__(
        self,
        dir: str,
        open_cache: int = 8,
        index_mode: str = "map",
        readonly: bool = False,
    ):
        self.dir = dir
        os.makedirs(dir, exist_ok=True)
        self.index_mode = index_mode  # "map" | "binary" (low-memory)
        self.readonly = readonly
        self._lock = threading.RLock()
        # filename -> (lo, hi) inclusive range
        self.refs: Dict[str, Tuple[int, int]] = {}
        self._cache: FLRU[str, SegmentReader] = FLRU(
            open_cache, on_evict=lambda k, r: r.close()
        )
        # interval index over refs for O(log n) point lookups (the
        # reference keeps segment refs in a sorted ra_lol structure,
        # src/ra_log_segments.erl:64-66): items sorted by lo, plus a
        # prefix-max of hi so the left-walk prunes immediately
        self._items: List[Tuple[int, int, str]] = []
        self._los: List[int] = []
        self._pmax: List[int] = []
        # a readonly view (external ReadPlan.execute) must not run crash
        # recovery: unlinking the owning process's in-flight .compacting
        # temp or .compaction_group marker would abort its live pass
        if not readonly:
            self._recover_compaction()
        for f in sorted(os.listdir(dir)):
            p = os.path.join(dir, f)
            if f.endswith(".segment") and not os.path.islink(p):
                try:
                    r = SegmentReader(p)
                except (ValueError, OSError):
                    continue
                if r.range:
                    self.refs[f] = r.range
                r.close()
        self._rebuild_interval_index()

    def _rebuild_interval_index(self) -> None:
        items = sorted((rng[0], rng[1], f) for f, rng in self.refs.items())
        self._items = items
        self._los = [it[0] for it in items]
        pmax: List[int] = []
        m = -1
        for _lo, hi, _f in items:
            m = max(m, hi)
            pmax.append(m)
        self._pmax = pmax

    def _recover_compaction(self) -> None:
        """Finish or roll back a major compaction interrupted by a crash
        (reference recovery table, COMPACTION.md:168-176)."""
        listing = sorted(os.listdir(self.dir))
        markers = {f[: -len(".compaction_group")] for f in listing
                   if f.endswith(".compaction_group")}
        for f in listing:
            if f.endswith(".segment.compacting"):
                # minor-compaction temp: always safe to discard
                os.unlink(os.path.join(self.dir, f))
                continue
            if (
                f.endswith(".compacting")
                and f[: -len(".compacting")] not in markers
            ):
                # major temp created before its marker: roll back
                os.unlink(os.path.join(self.dir, f))
                continue
            if not f.endswith(".compaction_group"):
                continue
            marker = os.path.join(self.dir, f)
            try:
                with open(marker, "rb") as m:
                    files = pickle.load(m)
            except Exception:  # noqa: BLE001 — torn marker: roll back
                files = []
            tmp = marker[: -len(".compaction_group")] + ".compacting"
            if len(files) < 2 or os.path.exists(tmp):
                # pre-rename crash (or undecidable): discard partial
                # work, originals are intact
                if os.path.exists(tmp):
                    os.unlink(tmp)
            else:
                # rename completed: the first segment holds the merged
                # data; recreate the symlinks (idempotent)
                first = files[0]
                for other in files[1:]:
                    p = os.path.join(self.dir, other)
                    if os.path.islink(p):
                        continue
                    if os.path.exists(p):
                        os.unlink(p)
                    os.symlink(first, p)
            os.unlink(marker)
        sync_dir(self.dir)

    # -- bookkeeping ------------------------------------------------------

    def add_ref(self, fname: str, rng: Tuple[int, int]) -> None:
        with self._lock:
            self.refs[fname] = rng
            self._cache.evict(fname)  # re-open to see new entries
            self._rebuild_interval_index()

    def num_segments(self) -> int:
        return len(self.refs)

    def _reader(self, fname: str) -> SegmentReader:
        r = self._cache.get(fname)
        if r is None:
            r = SegmentReader(os.path.join(self.dir, fname), mode=self.index_mode)
            self._cache.insert(fname, r)
        return r

    def files_for(self, idx: int) -> List[str]:
        """Newest-first list of files whose range covers idx (later files
        hold rewrites and win). O(log n + matches) via the interval
        index — the hot AER-construction read path must not scan every
        segment ref."""
        j = bisect.bisect_right(self._los, idx) - 1
        out: List[str] = []
        pmax = self._pmax
        items = self._items
        while j >= 0 and pmax[j] >= idx:
            lo, hi, f = items[j]
            if lo <= idx <= hi:
                out.append(f)
            j -= 1
        if len(out) > 1:
            out.sort(reverse=True)
        return out

    # -- reads ------------------------------------------------------------

    def fetch_term(self, idx: int) -> Optional[int]:
        with self._lock:
            for f in self.files_for(idx):
                t = self._reader(f).term(idx)
                if t is not None:
                    return t
        return None

    def fetch(self, idx: int) -> Optional[Entry]:
        with self._lock:
            for f in self.files_for(idx):
                got = self._reader(f).read(idx)
                if got is not None:
                    term, payload = got
                    return Entry(idx, term, pickle.loads(payload))
        return None

    def range(self) -> Optional[Tuple[int, int]]:
        with self._lock:
            if not self.refs:
                return None
            return (
                min(lo for lo, _ in self.refs.values()),
                max(hi for _, hi in self.refs.values()),
            )

    # -- compaction -------------------------------------------------------

    def truncate_below(self, snapshot_idx: int, live: Seq) -> int:
        """Snapshot moved to snapshot_idx: delete segments that hold no
        index above it and no live index; minor-compact segments that
        straddle the floor but keep live/tail entries. Returns number of
        files removed."""
        removed = 0
        with self._lock:
            for f in sorted(self.refs):
                lo, hi = self.refs[f]
                if lo > snapshot_idx:
                    continue
                # live entries below the floor plus the tail above it
                # survive
                keep = live.in_range(lo, hi).union(
                    Seq.from_range(max(lo, snapshot_idx + 1), hi)
                )
                if keep.is_empty():
                    self._cache.evict(f)
                    try:
                        os.unlink(os.path.join(self.dir, f))
                    except OSError:
                        pass
                    del self.refs[f]
                    removed += 1
                elif hi > snapshot_idx and len(keep) < (hi - lo + 1):
                    # only floor-straddling segments are rewritten
                    # inline; fully-below-floor segments keep their dead
                    # entries until a major pass groups them (their
                    # sparseness is the grouping signal — reference
                    # minor compaction likewise only deletes). A failed
                    # rewrite keeps the original (dead-entry GC is
                    # best-effort; the next truncate retries it)
                    try:
                        self._minor_compact(f, keep)
                    except OSError:
                        tmp = os.path.join(self.dir, f + ".compacting")
                        if os.path.exists(tmp):
                            try:
                                os.unlink(tmp)
                            except OSError:
                                pass
            self._rebuild_interval_index()
        return removed

    def _minor_compact(self, fname: str, keep: Seq) -> None:
        """Rewrite fname with only `keep` indexes. Crash-safe: write
        `.compacting`, fsync, atomic-rename over the original."""
        src = self._reader(fname)
        tmp_path = os.path.join(self.dir, fname + ".compacting")
        if os.path.exists(tmp_path):
            os.unlink(tmp_path)
        w = SegmentWriterHandle(tmp_path, max_count=max(len(keep), 1))
        lo = hi = None
        for idx in keep:
            faults.fire("segments.compact_copy")
            got = src.read(idx)
            if got is None:
                continue
            term, payload = got
            w.append(idx, term, payload)
            lo = idx if lo is None else lo
            hi = idx
        w.sync()
        w.close()
        self._cache.evict(fname)

        def _swap():
            faults.fire("segments.compact_rename")
            os.replace(tmp_path, os.path.join(self.dir, fname))

        retry(_swap, attempts=3, delay_s=0.02)
        if lo is not None:
            self.refs[fname] = (lo, hi)

    # -- major compaction -------------------------------------------------

    def major_compact(
        self,
        snapshot_idx: int,
        live: Seq,
        max_count: int = 4096,
    ) -> Dict[str, List[str]]:
        """Merge groups of sparse below-floor segments (reference:
        take_group <50% live by entries or bytes, respecting max_count;
        src/ra_log_segments.erl:191-344). Returns the reference's result
        shape: {"unreferenced": deleted, "linked": now-symlinks,
        "compacted": rewritten first segments}."""
        result: Dict[str, List[str]] = {
            "unreferenced": [], "linked": [], "compacted": [],
        }
        with self._lock:
            self._prune_symlinks()
            # evaluate oldest-first; only segments entirely below the
            # snapshot floor participate (the tail is still hot)
            candidates: List[Tuple[str, List[int], bool]] = []
            for f in sorted(self.refs):
                lo, hi = self.refs[f]
                if hi > snapshot_idx:
                    continue
                r = self._reader(f)
                live_idx = [i for i in live.in_range(lo, hi) if i in r.index]
                if not live_idx:
                    self._cache.evict(f)
                    try:
                        os.unlink(os.path.join(self.dir, f))
                    except OSError:
                        pass
                    del self.refs[f]
                    result["unreferenced"].append(f)
                    continue
                total = len(r.index)
                live_bytes = sum(r.index[i][2] for i in live_idx)
                total_bytes = sum(e[2] for e in r.index.values()) or 1
                dense = (
                    len(live_idx) / total >= 0.5
                    and live_bytes / total_bytes >= 0.5
                )
                # small files stay groupable even when dense, so the
                # output of earlier major passes keeps folding together
                # (size-tiered behavior; bounds file count near
                # total_live / max_count)
                if total <= max_count // 4:
                    dense = False
                candidates.append((f, live_idx, dense))

            groups: List[List[Tuple[str, List[int]]]] = []
            cur: List[Tuple[str, List[int]]] = []
            cur_count = 0
            for f, live_idx, dense in candidates:
                if dense:
                    # dense segment breaks adjacency: finalize the group
                    if len(cur) > 1:
                        groups.append(cur)
                    cur, cur_count = [], 0
                    continue
                if cur and cur_count + len(live_idx) > max_count:
                    if len(cur) > 1:
                        groups.append(cur)
                    cur, cur_count = [], 0
                cur.append((f, live_idx))
                cur_count += len(live_idx)
            if len(cur) > 1:
                groups.append(cur)
            # the interval index must not outlive the unreferenced-file
            # deletions above: a concurrent reader resolving an index
            # through stale items would open an unlinked file (symlinked
            # names later are fine — they resolve to merged data)
            self._rebuild_interval_index()

        # the merges (candidate reads, entry copies, fsyncs) run OUTSIDE
        # the lock — consensus-path fetch/fetch_term must not block on a
        # disk-bound pass. The marker/symlink protocol already tolerates
        # concurrent readers of the old names; the swap step re-takes
        # the lock and verifies the group is still intact.
        for grp in groups:
            built = self._merge_group_build(grp)
            if built is None:
                continue
            tmp, marker, new_range = built
            with self._lock:
                self._merge_group_swap(
                    [f for f, _ in grp], tmp, marker, new_range, result
                )
        with self._lock:
            self._rebuild_interval_index()
        return result

    def _merge_group_build(self, grp):
        """Unlocked phase of one group merge: durable tmp + manifest,
        then copy live entries via privately-opened readers (the shared
        FLRU cache is lock-guarded). Returns (tmp, marker, range), or
        None after rolling back if a group file vanished concurrently
        (snapshot-floor truncation deleted it)."""
        files = [f for f, _ in grp]
        first = files[0]
        stem = first.split(".")[0]
        marker = os.path.join(self.dir, stem + ".compaction_group")
        tmp = os.path.join(self.dir, stem + ".compacting")
        total = sum(len(li) for _, li in grp)

        # 0. the .compacting inode must exist durably BEFORE the marker:
        # recovery reads "marker present + tmp absent" as "rename
        # completed", so tmp-after-marker ordering would misclassify a
        # crash in between as complete and symlink away unmerged data
        if os.path.exists(tmp):
            os.unlink(tmp)
        with open(tmp, "wb") as t:
            t.flush()
            os.fsync(t.fileno())
        sync_dir(self.dir)

        # 1. durable manifest of the group
        with open(marker, "wb") as m:
            pickle.dump(files, m)
            m.flush()
            os.fsync(m.fileno())
        sync_dir(self.dir)

        # 2. merge all live entries into the .compacting segment
        w = SegmentWriterHandle(tmp, max_count=max(total, 1))
        try:
            for f, live_idx in grp:
                faults.fire("segments.compact_copy")
                r = SegmentReader(os.path.join(self.dir, f), mode=self.index_mode)
                try:
                    for i in live_idx:
                        got = r.read(i)
                        if got is not None:
                            w.append(i, got[0], got[1])
                finally:
                    r.close()
        except (OSError, ValueError):
            w.close()
            self._abort_merge(marker, tmp)
            return None
        w.sync()
        w.close()
        return tmp, marker, w.range

    def _abort_merge(self, marker: str, tmp: str) -> None:
        # marker goes first: a crash between the unlinks must never
        # leave "marker present + tmp absent", which recovery reads as
        # a completed rename
        try:
            os.unlink(marker)
        except OSError:
            pass
        sync_dir(self.dir)
        try:
            os.unlink(tmp)
        except OSError:
            pass

    def _merge_group_swap(self, files, tmp, marker, new_range, result) -> None:
        """Locked phase: verify the group survived, atomic-rename the
        merged data over the first segment, symlink the rest."""
        first = files[0]
        if any(f not in self.refs for f in files):
            # truncation raced us and removed a member: the originals
            # (or their deletions) win; discard the merged tmp
            self._abort_merge(marker, tmp)
            return

        # 3. atomic rename over the FIRST segment (before symlinks, so a
        # reader following a symlink always sees merged data)
        for f in files:
            self._cache.evict(f)

        def _swap():
            faults.fire("segments.compact_rename")
            os.replace(tmp, os.path.join(self.dir, first))

        try:
            retry(_swap, attempts=3, delay_s=0.02)
        except OSError:
            # rename never landed: originals are intact — roll back
            self._abort_merge(marker, tmp)
            return
        sync_dir(self.dir)

        # 4. the rest become symlinks to the first
        for other in files[1:]:
            p = os.path.join(self.dir, other)
            os.unlink(p)
            os.symlink(first, p)
            del self.refs[other]
            result["linked"].append(other)
        sync_dir(self.dir)

        # 5. drop the manifest — compaction is complete
        os.unlink(marker)
        if new_range is not None:
            self.refs[first] = new_range
        result["compacted"].append(first)

    def _prune_symlinks(self) -> None:
        now = time.time()
        for f in os.listdir(self.dir):
            p = os.path.join(self.dir, f)
            if os.path.islink(p):
                try:
                    if now - os.lstat(p).st_mtime > SYMLINK_KEEP_S:
                        os.unlink(p)
                except OSError:
                    pass

    def close(self) -> None:
        with self._lock:
            self._cache.evict_all()
