"""Per-server set of segment files + open-file cache + compaction.

The role of the reference's ``ra_log_segments`` (segment-ref set, FLRU
fd cache, compaction planning — ``src/ra_log_segments.erl``). Round-1
compaction scope: snapshot-floor truncation deletes whole segments whose
range is entirely dead, and minor compaction rewrites a segment that
still holds live indexes; crash-safe via write-new + atomic rename.
"""

from __future__ import annotations

import os
import pickle
from typing import Dict, List, Optional, Tuple

from ra_tpu.log.segment import SegmentReader, SegmentWriterHandle
from ra_tpu.protocol import Entry
from ra_tpu.utils.flru import FLRU
from ra_tpu.utils.seq import Seq


class SegmentSet:
    def __init__(self, dir: str, open_cache: int = 8):
        self.dir = dir
        os.makedirs(dir, exist_ok=True)
        # filename -> (lo, hi) inclusive range
        self.refs: Dict[str, Tuple[int, int]] = {}
        self._cache: FLRU[str, SegmentReader] = FLRU(
            open_cache, on_evict=lambda k, r: r.close()
        )
        for f in sorted(os.listdir(dir)):
            if f.endswith(".segment"):
                try:
                    r = SegmentReader(os.path.join(dir, f))
                except (ValueError, OSError):
                    continue
                if r.range:
                    self.refs[f] = r.range
                r.close()

    # -- bookkeeping ------------------------------------------------------

    def add_ref(self, fname: str, rng: Tuple[int, int]) -> None:
        self.refs[fname] = rng
        self._cache.evict(fname)  # re-open to see new entries

    def num_segments(self) -> int:
        return len(self.refs)

    def _reader(self, fname: str) -> SegmentReader:
        r = self._cache.get(fname)
        if r is None:
            r = SegmentReader(os.path.join(self.dir, fname))
            self._cache.insert(fname, r)
        return r

    def files_for(self, idx: int) -> List[str]:
        """Newest-first list of files whose range covers idx (later files
        hold rewrites and win)."""
        return [
            f
            for f in sorted(self.refs, reverse=True)
            if self.refs[f][0] <= idx <= self.refs[f][1]
        ]

    # -- reads ------------------------------------------------------------

    def fetch_term(self, idx: int) -> Optional[int]:
        for f in self.files_for(idx):
            t = self._reader(f).term(idx)
            if t is not None:
                return t
        return None

    def fetch(self, idx: int) -> Optional[Entry]:
        for f in self.files_for(idx):
            got = self._reader(f).read(idx)
            if got is not None:
                term, payload = got
                return Entry(idx, term, pickle.loads(payload))
        return None

    def range(self) -> Optional[Tuple[int, int]]:
        if not self.refs:
            return None
        return (
            min(lo for lo, _ in self.refs.values()),
            max(hi for _, hi in self.refs.values()),
        )

    # -- compaction -------------------------------------------------------

    def truncate_below(self, snapshot_idx: int, live: Seq) -> int:
        """Snapshot moved to snapshot_idx: delete segments that hold no
        index above it and no live index; minor-compact segments that
        straddle the floor but keep live/tail entries. Returns number of
        files removed."""
        removed = 0
        for f in sorted(self.refs):
            lo, hi = self.refs[f]
            if lo > snapshot_idx:
                continue
            # live entries below the floor plus the tail above it survive
            keep = live.in_range(lo, hi).union(
                Seq.from_range(max(lo, snapshot_idx + 1), hi)
            )
            if keep.is_empty():
                self._cache.evict(f)
                try:
                    os.unlink(os.path.join(self.dir, f))
                except OSError:
                    pass
                del self.refs[f]
                removed += 1
            elif len(keep) < (hi - lo + 1):
                self._minor_compact(f, keep)
        return removed

    def _minor_compact(self, fname: str, keep: Seq) -> None:
        """Rewrite fname with only `keep` indexes. Crash-safe: write
        `.compacting`, fsync, atomic-rename over the original (reference
        uses the same write-new/rename shape: COMPACTION.md marker
        protocol)."""
        src = self._reader(fname)
        tmp_path = os.path.join(self.dir, fname + ".compacting")
        if os.path.exists(tmp_path):
            os.unlink(tmp_path)
        w = SegmentWriterHandle(tmp_path, max_count=max(len(keep), 1))
        lo = hi = None
        for idx in keep:
            got = src.read(idx)
            if got is None:
                continue
            term, payload = got
            w.append(idx, term, payload)
            lo = idx if lo is None else lo
            hi = idx
        w.sync()
        w.close()
        self._cache.evict(fname)
        os.replace(tmp_path, os.path.join(self.dir, fname))
        if lo is not None:
            self.refs[fname] = (lo, hi)

    def close(self) -> None:
        self._cache.evict_all()
