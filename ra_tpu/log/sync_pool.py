"""Pooled fsync workers.

The counterpart of the reference's ``ra_log_sync`` (reference:
``src/ra_log_sync.erl:32-35`` — a pool of batching fsync workers, sized
schedulers/4, serializing snapshot-directory syncs across servers so a
burst of snapshot writes cannot issue an fsync storm against the
device). Callers block until their sync lands (durability semantics
unchanged); the pool bounds CONCURRENCY and batches same-path requests.
"""

from __future__ import annotations

import os
import threading
from collections import deque
from typing import Dict, List, Optional


class SyncPool:
    def __init__(self, workers: Optional[int] = None,
                 coalesce_window_s: float = 0.001):
        n = workers or max(1, (os.cpu_count() or 1) // 4)
        # group-commit analog for snapshot syncs (docs/INTERNALS.md
        # §15): when a request arrives on the heels of another (a
        # snapshot burst across servers), hold it open briefly so
        # same-path joiners ride ONE fsync. Bounded and only armed
        # while a burst is evidently in progress — a lone sync pays
        # nothing.
        self.coalesce_window_s = coalesce_window_s
        self._last_req_t = float("-inf")
        self._req_gap = float("inf")  # arrival gap of the newest request
        self._cv = threading.Condition()
        self._queue: deque = deque()  # (path, Event, err_slot)
        self._closed = False
        self._threads = [
            threading.Thread(target=self._run, name=f"ra-sync-{i}", daemon=True)
            for i in range(n)
        ]
        for t in self._threads:
            t.start()

    def sync_path(self, path: str, timeout: Optional[float] = None) -> None:
        """fsync the file (or directory) at ``path`` via the pool;
        blocks until durable — like the inline os.fsync it replaces, a
        slow device makes this SLOWER, never a spurious failure (pass a
        timeout only where the caller can handle TimeoutError). Raises
        the worker's OSError on failure."""
        done = threading.Event()
        slot: Dict[str, BaseException] = {}
        with self._cv:
            if self._closed:
                # closed pool: sync inline so durability never silently
                # degrades
                self._fsync(path)
                return
            import time as _time

            now = _time.monotonic()
            self._req_gap = now - self._last_req_t
            self._last_req_t = now
            self._queue.append((path, done, slot))
            self._cv.notify()
        if not done.wait(timeout):
            raise TimeoutError(f"sync of {path!r} timed out")
        err = slot.get("err")
        if err is not None:
            raise err

    @staticmethod
    def _fsync(path: str) -> None:
        fd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)

    def _run(self) -> None:
        import time as _time

        while True:
            with self._cv:
                while not self._queue and not self._closed:
                    # event-driven idle: sync_path notifies on every
                    # enqueue and close() notifies all — idle workers
                    # consume zero CPU (docs/INTERNALS.md §16)
                    self._cv.wait()
                if self._closed and not self._queue:
                    return
                path, done, slot = self._queue.popleft()
                # adaptive coalescing: if another request landed within
                # the window just before this one, a burst is in
                # flight — hold briefly so its same-path joiners ride
                # this fsync (never armed for an isolated request)
                w = self.coalesce_window_s
                if (
                    w > 0 and not self._closed and not self._queue
                    and self._req_gap < 4 * w
                ):
                    # the newest request followed its predecessor
                    # closely: a burst — an isolated sync never waits
                    self._cv.wait(timeout=w)
                # batch: everyone queued behind us for the SAME path is
                # satisfied by this one fsync
                extra: List = []
                rest: deque = deque()
                while self._queue:
                    item = self._queue.popleft()
                    (extra if item[0] == path else rest).append(item)
                self._queue = rest
            try:
                self._fsync(path)
                err = None
            except OSError as e:
                err = e
            for _p, d, s in [(path, done, slot)] + extra:
                if err is not None:
                    s["err"] = err
                d.set()

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        for t in self._threads:
            t.join(timeout=2)
