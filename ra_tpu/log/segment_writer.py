"""Segment writer: flushes rolled-over memtable ranges to per-server
segment files.

The reference's ``ra_log_segment_writer`` (``src/ra_log_segment_writer
.erl``): one per system; takes ``{uid: seq}`` jobs from the WAL at
rollover, truncates the flush floor by each server's snapshot state,
appends entries from the memtable to the server's open segment (rolling
to a new segment when full), fsyncs, then notifies the server with
``("segments", flushed_seq, new_refs)`` so it can update its segment set
and shrink its memtable. Deletes the WAL file once flushed.

Runs jobs on a background thread (``threaded=False`` for deterministic
tests).
"""

from __future__ import annotations

import logging
import os
import pickle
import threading
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

from ra_tpu import counters as ra_counters
from ra_tpu import faults
from ra_tpu.log.segment import SegmentWriterHandle
from ra_tpu.protocol import encode_cmd
from ra_tpu.log.tables import TableRegistry
from ra_tpu.utils.lib import retry
from ra_tpu.utils.seq import Seq

NotifyFn = Callable[[str, object], None]

logger = logging.getLogger("ra_tpu")


class SegmentWriter:
    MAX_FLUSH_ATTEMPTS = 5

    def __init__(
        self,
        data_dir: str,
        tables: TableRegistry,
        notify: NotifyFn,
        max_entries: int = 4096,
        threaded: bool = True,
        counter=None,
    ):
        self.data_dir = data_dir
        self.tables = tables
        self.notify = notify
        self.max_entries = max_entries
        self.counter = counter or ra_counters.Counters(
            "segment_writer", ra_counters.SEGMENT_WRITER_FIELDS
        )
        # failpoint scope label; the owning node sets it to its name
        self.fault_scope: Optional[str] = None
        self._open: Dict[str, SegmentWriterHandle] = {}
        self._cv = threading.Condition()
        self._queue: deque = deque()
        self._inflight = None  # job popped but not finished (crash safety)
        self._closed = False
        self._idle = threading.Event()
        self._idle.set()
        self._thread: Optional[threading.Thread] = None
        if threaded:
            self._thread = threading.Thread(
                target=self._run, name="ra-segment-writer", daemon=True
            )
            self._thread.start()

    # ------------------------------------------------------------------

    def flush_mem_tables(
        self, seqs: Dict[str, List[Tuple[int, Seq]]],
        wal_file: Optional[str] = None,
    ) -> None:
        """``seqs``: {uid: [(tid, Seq), ...]} — the successor-chain
        handoff from WAL rollover (tid names the memtable table that
        holds each file's entries)."""
        norm = {uid: list(ts) for uid, ts in seqs.items()}
        with self._cv:
            if self._closed:
                return
            self._queue.append((norm, wal_file, 0))
            self._idle.clear()
            self._cv.notify()
        if self._thread is None:
            self._drain()

    def wait_idle(self, timeout: float = 10.0) -> bool:
        return self._idle.wait(timeout)

    def thread_alive(self) -> bool:
        """Flusher-thread liveness for the node's infra supervisor
        (non-threaded mode drains synchronously: always 'alive')."""
        return self._thread is None or self._thread.is_alive()

    def revive_thread(self) -> None:
        """Restart a dead flusher thread (supervision). The job queue
        survives, and a job that was IN FLIGHT when the thread died is
        requeued at the front (its seqs dict already dropped finished
        uids, so completed flushes are not replayed)."""
        with self._cv:
            if self._closed or self._thread is None or self._thread.is_alive():
                return
            if self._inflight is not None:
                self._queue.appendleft(self._inflight)
                self._inflight = None
            self._thread = threading.Thread(
                target=self._run, name="ra-segment-writer", daemon=True
            )
            self._thread.start()

    def my_segments(self, uid: str) -> List[str]:
        d = self._server_dir(uid)
        if not os.path.isdir(d):
            return []
        return sorted(f for f in os.listdir(d) if f.endswith(".segment"))

    def close(self) -> None:
        with self._cv:
            self._closed = True
            self._cv.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=10)
        self._drain()
        for h in self._open.values():
            h.close()
        self._open.clear()

    # ------------------------------------------------------------------

    def _run(self) -> None:
        while True:
            # injected thread death — supervision revives via
            # revive_thread (in-flight job requeues at the front)
            faults.fire("segment_writer.thread", self.fault_scope)
            with self._cv:
                while not self._queue and not self._closed:
                    self._idle.set()
                    self._cv.wait(timeout=0.5)
                    # idle loop checks the site too (see Wal._run)
                    faults.fire("segment_writer.thread", self.fault_scope)
                if self._closed and not self._queue:
                    self._idle.set()
                    return
            self._drain()

    def _drain(self) -> None:
        while True:
            with self._cv:
                if not self._queue:
                    self._idle.set()
                    return
                job = self._queue.popleft()
                self._inflight = job
            seqs, wal_file, attempt = job
            try:
                self._flush_job(seqs)
            except Exception as exc:  # noqa: BLE001
                # The WAL file is the only durable copy of these entries
                # until the flush lands in segments: never unlink it on
                # failure, and never let one bad flush kill the writer.
                # Retry with backoff (requeued at the FRONT so per-uid
                # flush order is preserved); after that, leave the WAL
                # file on disk so boot-time recovery can replay it.
                self.counter.incr("flush_errors")
                with self._cv:
                    self._inflight = None
                    if attempt + 1 < self.MAX_FLUSH_ATTEMPTS:
                        self._queue.appendleft((seqs, wal_file, attempt + 1))
                        # interruptible backoff (close() notifies); total
                        # worst-case stall per job is < 1s
                        self._cv.wait(timeout=min(0.05 * (2 ** attempt), 0.4))
                    else:
                        logger.error(
                            "segment_writer: flush failed after %d attempts, "
                            "retaining %r: %r", attempt + 1, wal_file, exc,
                        )
                continue
            with self._cv:
                self._inflight = None
            if wal_file and os.path.exists(wal_file):
                os.unlink(wal_file)

    def _flush_job(self, seqs) -> None:
        # uids are removed from ``seqs`` as they complete so a retried
        # job (requeued by _drain on failure) never replays finished
        # uids' appends/notifications
        for uid in list(seqs):
            self._flush_uid(uid, seqs[uid])
            del seqs[uid]

    def _flush_uid(self, uid: str, tid_seqs) -> None:
        # flush floor: skip dead indexes below the snapshot, keep live
        # ones (reference: start_index/smallest_live_idx truncation,
        # src/ra_log_segment_writer.erl:268-390). Entries are read from
        # the EXACT memtable table the WAL file referenced (successor
        # chains): a concurrent divergent overwrite must not change what
        # this flush persists.
        # injected flush failure: lands in _drain's retry-with-backoff
        # path (the WAL file is retained until the flush succeeds)
        faults.fire("segment_writer.flush", self.fault_scope)
        snap_idx = self.tables.snapshot_index(uid)
        live = self.tables.live_indexes(uid)
        mt = self.tables.mem_table(uid)
        new_refs: List[Tuple[str, Tuple[int, int]]] = []
        handle = self._open_segment(uid)
        wrote = 0
        flushed: List[Tuple[int, Seq]] = []
        for tid, seq in tid_seqs:
            keep = seq.floor(snap_idx + 1).union(seq.intersect(live))
            for idx in keep:
                entry = mt.get_from(tid, idx)
                if entry is None:
                    continue  # already truncated/compacted away
                if handle.is_full():
                    handle.sync()
                    handle.close()
                    if handle.range:
                        new_refs.append((os.path.basename(handle.path), handle.range))
                    handle = self._roll_segment(uid)
                handle.append(entry.index, entry.term, encode_cmd(entry.cmd))
                wrote += 1
            flushed.append((tid, seq))
        if wrote:
            handle.sync()
            self.counter.incr("entries_flushed", wrote)
        self.counter.incr("mem_tables_flushed")
        if handle.range:
            new_refs.append((os.path.basename(handle.path), handle.range))
        self.notify(uid, ("segments", flushed, new_refs))

    def _server_dir(self, uid: str) -> str:
        return os.path.join(self.data_dir, uid, "segments")

    def _open_segment(self, uid: str) -> SegmentWriterHandle:
        h = self._open.get(uid)
        if h is not None:
            return h
        d = self._server_dir(uid)
        os.makedirs(d, exist_ok=True)
        existing = self.my_segments(uid)
        if existing:
            h = retry(
                lambda: SegmentWriterHandle(
                    os.path.join(d, existing[-1]), max_count=self.max_entries
                ),
                attempts=3, delay_s=0.02,
            )
            if h.is_full():
                h.close()
                h = self._new_segment(uid, existing[-1])
        else:
            h = self._new_segment(uid, None)
        self._open[uid] = h
        return h

    def _roll_segment(self, uid: str) -> SegmentWriterHandle:
        prev = os.path.basename(self._open[uid].path)
        h = self._new_segment(uid, prev)
        self._open[uid] = h
        return h

    def _new_segment(self, uid: str, prev_name: Optional[str]) -> SegmentWriterHandle:
        n = int(prev_name.split(".")[0]) + 1 if prev_name else 1
        path = os.path.join(self._server_dir(uid), f"{n:08d}.segment")
        self.counter.incr("segments_created")
        return retry(
            lambda: SegmentWriterHandle(path, max_count=self.max_entries),
            attempts=3, delay_s=0.02,
        )
