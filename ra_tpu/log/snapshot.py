"""Snapshot subsystem: durable machine-state captures + chunked transfer.

Capability parity with the reference's ``ra_snapshot`` (``src/
ra_snapshot.erl``): a pluggable codec behaviour; three capture kinds —
``snapshot`` (replicated, truncates the log), ``checkpoint`` (local
only, promotable), ``recovery`` (orderly-shutdown state to skip replay);
directory layout ``<server_dir>/{snapshots,checkpoints,recovery}/
<Term>_<Index>/``; chunked read/accept protocol for remote installs;
CRC-validated recovery that skips corrupt captures.
"""

from __future__ import annotations

import os
import pickle
import shutil
import struct
import zlib
from typing import Any, Iterator, List, Optional, Tuple

from ra_tpu.protocol import SnapshotMeta
from ra_tpu.utils.lib import sync_dir
from ra_tpu.utils.seq import Seq

SNAPSHOT = "snapshots"
CHECKPOINT = "checkpoints"
RECOVERY = "recovery"

_TRAILER = struct.Struct("<I")


def decode_snapshot_chunks(chunks) -> Any:
    """Reassemble a transferred snapshot body. In-proc transfers may ship
    the machine state as one direct object chunk; remote transfers ship
    pickled byte chunks. Single wire-format rule for both backends."""
    if len(chunks) == 1 and not isinstance(chunks[0], (bytes, bytearray)):
        return chunks[0]
    # chunk bodies arrive over snapshot TRANSFER (untrusted bytes from a
    # peer): resolve through the wire allowlist, never plain pickle
    from ra_tpu.utils.wire import wire_loads

    return wire_loads(b"".join(chunks))


class SnapshotCodec:
    """Pluggable serialization behaviour (cf. the reference's snapshot
    behaviour callbacks: prepare/write/begin_read/read_chunk/
    begin_accept/accept_chunk/complete_accept/recover/validate)."""

    name = "pickle"

    def write(self, dir: str, meta: SnapshotMeta, machine_state: Any,
              sync_pool=None) -> None:
        """Write the capture under ``dir``. When a SyncPool is given the
        codec routes its fsyncs through it (serialized across servers,
        reference: ra_log_sync); durability on return is unchanged."""
        raise NotImplementedError

    def read(self, dir: str) -> Tuple[SnapshotMeta, Any]:
        raise NotImplementedError

    def read_meta(self, dir: str) -> SnapshotMeta:
        raise NotImplementedError

    def validate(self, dir: str) -> bool:
        raise NotImplementedError


class PickleCodec(SnapshotCodec):
    """Default codec: CRC-trailered pickle files (``meta.dat`` +
    ``snapshot.dat``)."""

    @staticmethod
    def _write_file(path: str, obj: Any, sync_pool=None) -> None:
        payload = pickle.dumps(obj)
        with open(path, "wb") as f:
            f.write(payload)
            f.write(_TRAILER.pack(zlib.crc32(payload)))
            f.flush()
            if sync_pool is None:
                os.fsync(f.fileno())
        if sync_pool is not None:
            sync_pool.sync_path(path)

    @staticmethod
    def _read_file(path: str) -> Any:
        data = open(path, "rb").read()
        if len(data) < _TRAILER.size:
            raise IOError(f"snapshot file too short: {path}")
        payload, (crc,) = data[: -_TRAILER.size], _TRAILER.unpack(data[-_TRAILER.size :])
        if crc and zlib.crc32(payload) != crc:
            raise IOError(f"snapshot crc mismatch: {path}")
        return pickle.loads(payload)

    def write(self, dir: str, meta: SnapshotMeta, machine_state: Any,
              sync_pool=None) -> None:
        self._write_file(os.path.join(dir, "meta.dat"), meta, sync_pool)
        self._write_file(os.path.join(dir, "snapshot.dat"), machine_state, sync_pool)

    def read(self, dir: str) -> Tuple[SnapshotMeta, Any]:
        return (
            self._read_file(os.path.join(dir, "meta.dat")),
            self._read_file(os.path.join(dir, "snapshot.dat")),
        )

    def read_meta(self, dir: str) -> SnapshotMeta:
        return self._read_file(os.path.join(dir, "meta.dat"))

    def validate(self, dir: str) -> bool:
        try:
            self.read(dir)
            return True
        except Exception:
            return False


class SnapshotStore:
    """Per-server snapshot/checkpoint directory manager."""

    def __init__(self, server_dir: str, codec: Optional[SnapshotCodec] = None,
                 max_checkpoints: int = 10, sync_pool=None):
        self.server_dir = server_dir
        self.codec = codec or PickleCodec()
        self.max_checkpoints = max_checkpoints
        self.sync_pool = sync_pool
        for kind in (SNAPSHOT, CHECKPOINT, RECOVERY):
            os.makedirs(os.path.join(server_dir, kind), exist_ok=True)

    # -- naming -------------------------------------------------------------

    @staticmethod
    def _dirname(meta: SnapshotMeta) -> str:
        return f"{meta.term:016X}_{meta.index:016X}"

    @staticmethod
    def _parse(dirname: str) -> Optional[Tuple[int, int]]:
        try:
            t, i = dirname.split("_")
            return int(t, 16), int(i, 16)
        except ValueError:
            return None

    def _kind_dir(self, kind: str) -> str:
        return os.path.join(self.server_dir, kind)

    def _list(self, kind: str) -> List[Tuple[int, int, str]]:
        """[(index, term, path)] ascending by index."""
        out = []
        d = self._kind_dir(kind)
        for name in os.listdir(d):
            p = self._parse(name)
            if p is None:
                continue
            term, idx = p
            out.append((idx, term, os.path.join(d, name)))
        return sorted(out)

    # -- writes -------------------------------------------------------------

    def write(self, meta: SnapshotMeta, machine_state: Any, kind: str = SNAPSHOT) -> str:
        """Durably write a capture; crash-safe via tmp dir + rename."""
        d = self._kind_dir(kind)
        final = os.path.join(d, self._dirname(meta))
        tmp = final + ".writing"
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        self.codec.write(tmp, meta, machine_state, sync_pool=self.sync_pool)
        os.replace(tmp, final)
        sync_dir(d)
        if kind == SNAPSHOT:
            # keep the previous generation as a corruption safety net
            self._prune_count(SNAPSHOT, 2)
            self._prune_older(CHECKPOINT, meta.index + 1)
        elif kind == CHECKPOINT:
            self._prune_count(CHECKPOINT, self.max_checkpoints)
        return final

    def _prune_older(self, kind: str, below_idx: int) -> None:
        for idx, term, path in self._list(kind):
            if idx < below_idx:
                shutil.rmtree(path, ignore_errors=True)

    def _prune_count(self, kind: str, max_n: int) -> None:
        entries = self._list(kind)
        while len(entries) > max_n:
            idx, term, path = entries.pop(0)
            shutil.rmtree(path, ignore_errors=True)

    # -- reads ---------------------------------------------------------------

    def current(self, kind: str = SNAPSHOT) -> Optional[SnapshotMeta]:
        for idx, term, path in reversed(self._list(kind)):
            try:
                return self.codec.read_meta(path)
            except Exception:
                continue
        return None

    def read(self, kind: str = SNAPSHOT) -> Optional[Tuple[SnapshotMeta, Any]]:
        for idx, term, path in reversed(self._list(kind)):
            try:
                return self.codec.read(path)
            except Exception:
                continue  # corrupt capture: fall back to the previous one
        return None

    def latest_checkpoint_at_or_below(self, idx: int) -> Optional[Tuple[SnapshotMeta, Any]]:
        for cidx, term, path in reversed(self._list(CHECKPOINT)):
            if cidx > idx:
                continue
            try:
                return self.codec.read(path)
            except Exception:
                continue
        return None

    def promote_checkpoint(self, idx: int) -> Optional[SnapshotMeta]:
        got = self.latest_checkpoint_at_or_below(idx)
        if got is None:
            return None
        meta, state = got
        self.write(meta, state, kind=SNAPSHOT)
        return meta

    # -- chunked transfer ------------------------------------------------------

    def begin_read(self, chunk_size: int) -> Iterator[bytes]:
        got = self.read(SNAPSHOT)
        if got is None:
            return iter(())
        meta, state = got
        blob = pickle.dumps(state)

        def chunks():
            for off in range(0, max(len(blob), 1), chunk_size):
                yield blob[off : off + chunk_size]

        return chunks()

    def accept_chunks(self, meta: SnapshotMeta, chunks: List[bytes]) -> Any:
        state = decode_snapshot_chunks(chunks)  # untrusted transfer bytes
        self.write(meta, state, kind=SNAPSHOT)
        return state

    def delete_kind(self, kind: str) -> None:
        shutil.rmtree(self._kind_dir(kind), ignore_errors=True)
        os.makedirs(self._kind_dir(kind), exist_ok=True)

    def delete_all(self) -> None:
        for kind in (SNAPSHOT, CHECKPOINT, RECOVERY):
            self.delete_kind(kind)
