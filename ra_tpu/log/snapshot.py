"""Snapshot subsystem: durable machine-state captures + chunked transfer.

Capability parity with the reference's ``ra_snapshot`` (``src/
ra_snapshot.erl``): a pluggable codec behaviour; three capture kinds —
``snapshot`` (replicated, truncates the log), ``checkpoint`` (local
only, promotable), ``recovery`` (orderly-shutdown state to skip replay);
directory layout ``<server_dir>/{snapshots,checkpoints,recovery}/
<Term>_<Index>/``; chunked read/accept protocol for remote installs;
CRC-validated recovery that skips corrupt captures.
"""

from __future__ import annotations

import os
import pickle
import shutil
import struct
import zlib
from typing import Any, Iterator, List, Optional, Tuple

from ra_tpu import faults
from ra_tpu.protocol import SnapshotMeta
from ra_tpu.utils.lib import retry, sync_dir
from ra_tpu.utils.seq import Seq

SNAPSHOT = "snapshots"
CHECKPOINT = "checkpoints"
RECOVERY = "recovery"

_TRAILER = struct.Struct("<I")


def decode_snapshot_chunks(chunks) -> Any:
    """Reassemble a transferred snapshot body. In-proc transfers may ship
    the machine state as one direct object chunk; remote transfers ship
    pickled byte chunks. Single wire-format rule for both backends."""
    if len(chunks) == 1 and not isinstance(chunks[0], (bytes, bytearray)):
        return chunks[0]
    # chunk bodies arrive over snapshot TRANSFER (untrusted bytes from a
    # peer): resolve through the wire allowlist, never plain pickle
    from ra_tpu.utils.wire import wire_loads

    return wire_loads(b"".join(chunks))


class SnapshotCodec:
    """Pluggable serialization behaviour (cf. the reference's snapshot
    behaviour callbacks: prepare/write/begin_read/read_chunk/
    begin_accept/accept_chunk/complete_accept/recover/validate)."""

    name = "pickle"

    def write(self, dir: str, meta: SnapshotMeta, machine_state: Any,
              sync_pool=None) -> None:
        """Write the capture under ``dir``. When a SyncPool is given the
        codec routes its fsyncs through it (serialized across servers,
        reference: ra_log_sync); durability on return is unchanged."""
        raise NotImplementedError

    def read(self, dir: str) -> Tuple[SnapshotMeta, Any]:
        raise NotImplementedError

    def read_meta(self, dir: str) -> SnapshotMeta:
        raise NotImplementedError

    def validate(self, dir: str) -> bool:
        raise NotImplementedError


class PickleCodec(SnapshotCodec):
    """Default codec: CRC-trailered pickle files (``meta.dat`` +
    ``snapshot.dat``)."""

    @staticmethod
    def _write_file(path: str, obj: Any, sync_pool=None) -> None:
        payload = pickle.dumps(obj)
        with open(path, "wb") as f:
            # a torn write here leaves a short body or a missing/torn
            # CRC trailer, neither of which validates — recovery falls
            # back to the previous capture generation. Two writes (not
            # one concatenation): the body can be hundreds of MB and
            # must not be copied just to append 4 trailer bytes
            faults.checked_write("snapshot.write", f, payload)
            faults.checked_write("snapshot.write", f,
                                 _TRAILER.pack(zlib.crc32(payload)))
            f.flush()
            if sync_pool is None:
                os.fsync(f.fileno())
        if sync_pool is not None:
            sync_pool.sync_path(path)

    @staticmethod
    def _read_file(path: str) -> Any:
        data = open(path, "rb").read()
        if len(data) < _TRAILER.size:
            raise IOError(f"snapshot file too short: {path}")
        payload, (crc,) = data[: -_TRAILER.size], _TRAILER.unpack(data[-_TRAILER.size :])
        if crc and zlib.crc32(payload) != crc:
            raise IOError(f"snapshot crc mismatch: {path}")
        return pickle.loads(payload)

    def write(self, dir: str, meta: SnapshotMeta, machine_state: Any,
              sync_pool=None) -> None:
        self._write_file(os.path.join(dir, "meta.dat"), meta, sync_pool)
        self._write_file(os.path.join(dir, "snapshot.dat"), machine_state, sync_pool)

    def read(self, dir: str) -> Tuple[SnapshotMeta, Any]:
        return (
            self._read_file(os.path.join(dir, "meta.dat")),
            self._read_file(os.path.join(dir, "snapshot.dat")),
        )

    def read_meta(self, dir: str) -> SnapshotMeta:
        return self._read_file(os.path.join(dir, "meta.dat"))

    def validate(self, dir: str) -> bool:
        try:
            self.read(dir)
            return True
        except Exception:
            return False


class ChunkAccept:
    """Incremental accept of a transferred snapshot body: every chunk is
    appended straight to a spool file on disk — peak extra memory is
    O(chunk), never O(snapshot) (reference: begin_accept/accept_chunk/
    complete_accept stream to disk, src/ra_snapshot.erl:742-860). On
    ``complete`` the body gets the CRC trailer, the machine state is
    decoded by a STREAMING restricted unpickle from the file, and the
    directory is promoted with the same crash-safe rename protocol as a
    local snapshot write."""

    def __init__(self, store: "SnapshotStore", meta: SnapshotMeta):
        self.store = store
        self.meta = meta
        d = store._kind_dir(SNAPSHOT)
        self.tmp = os.path.join(d, store._dirname(meta) + ".accepting")
        if os.path.exists(self.tmp):
            shutil.rmtree(self.tmp)
        os.makedirs(self.tmp)
        self.path = os.path.join(self.tmp, "snapshot.dat")
        self._f = open(self.path, "wb")
        self._crc = 0
        self.chunks_accepted = 0
        self.done = False

    def accept_chunk(self, data: bytes) -> None:
        # a torn/failed spool write leaves an .accepting dir that boot
        # clears; the in-flight accept aborts (sender restarts transfer)
        faults.checked_write("snapshot.chunk", self._f, data)
        self._crc = zlib.crc32(data, self._crc)
        self.chunks_accepted += 1

    def abort(self) -> None:
        self.done = True
        try:
            self._f.close()
        except Exception:  # noqa: BLE001
            pass
        shutil.rmtree(self.tmp, ignore_errors=True)

    def complete(self) -> Any:
        store = self.store
        self._f.write(_TRAILER.pack(self._crc))
        self._f.flush()
        if store.sync_pool is None:
            os.fsync(self._f.fileno())
        self._f.close()
        if store.sync_pool is not None:
            store.sync_pool.sync_path(self.path)
        # decode BEFORE promoting: an undecodable body (wire-allowlist
        # miss, truncation) must never become the current snapshot.
        # Streaming unpickle: the blob is never materialized as bytes.
        from ra_tpu.utils.wire import wire_load_file

        try:
            with open(self.path, "rb") as rf:
                state = wire_load_file(rf)
        except Exception:
            self.abort()
            raise
        PickleCodec._write_file(
            os.path.join(self.tmp, "meta.dat"), self.meta, store.sync_pool
        )
        d = store._kind_dir(SNAPSHOT)
        final = os.path.join(d, store._dirname(self.meta))
        if os.path.exists(final):
            shutil.rmtree(final)

        def _promote():
            faults.fire("snapshot.promote")
            os.replace(self.tmp, final)

        try:
            retry(_promote, attempts=3, delay_s=0.02)
        except OSError:
            self.abort()
            raise
        sync_dir(d)
        store._prune_count(SNAPSHOT, 2)
        store._prune_older(CHECKPOINT, self.meta.index + 1)
        self.done = True
        return state


class SnapshotStore:
    """Per-server snapshot/checkpoint directory manager."""

    def __init__(self, server_dir: str, codec: Optional[SnapshotCodec] = None,
                 max_checkpoints: int = 10, sync_pool=None):
        self.server_dir = server_dir
        self.codec = codec or PickleCodec()
        self.max_checkpoints = max_checkpoints
        self.sync_pool = sync_pool
        for kind in (SNAPSHOT, CHECKPOINT, RECOVERY):
            d = os.path.join(server_dir, kind)
            os.makedirs(d, exist_ok=True)
            # a crash mid-write/mid-accept leaves .writing/.accepting
            # (or legacy .partial) spool dirs; they are not valid
            # captures — clear them. Orphaned accept spools also count
            # against the disk watermark budget (docs/INTERNALS.md
            # §21), so boot reclaims the bytes, durably.
            cleared = False
            for name in os.listdir(d):
                if name.endswith((".writing", ".accepting", ".partial")):
                    shutil.rmtree(os.path.join(d, name), ignore_errors=True)
                    cleared = True
            if cleared:
                sync_dir(d)

    # -- naming -------------------------------------------------------------

    @staticmethod
    def _dirname(meta: SnapshotMeta) -> str:
        return f"{meta.term:016X}_{meta.index:016X}"

    @staticmethod
    def _parse(dirname: str) -> Optional[Tuple[int, int]]:
        try:
            t, i = dirname.split("_")
            return int(t, 16), int(i, 16)
        except ValueError:
            return None

    def _kind_dir(self, kind: str) -> str:
        return os.path.join(self.server_dir, kind)

    def _list(self, kind: str) -> List[Tuple[int, int, str]]:
        """[(index, term, path)] ascending by index."""
        out = []
        d = self._kind_dir(kind)
        for name in os.listdir(d):
            p = self._parse(name)
            if p is None:
                continue
            term, idx = p
            out.append((idx, term, os.path.join(d, name)))
        return sorted(out)

    # -- writes -------------------------------------------------------------

    def write(self, meta: SnapshotMeta, machine_state: Any, kind: str = SNAPSHOT) -> str:
        """Durably write a capture; crash-safe via tmp dir + rename."""
        d = self._kind_dir(kind)
        final = os.path.join(d, self._dirname(meta))
        tmp = final + ".writing"
        if os.path.exists(tmp):
            shutil.rmtree(tmp)
        os.makedirs(tmp)
        self.codec.write(tmp, meta, machine_state, sync_pool=self.sync_pool)

        def _promote():
            faults.fire("snapshot.promote")
            os.replace(tmp, final)

        retry(_promote, attempts=3, delay_s=0.02)
        sync_dir(d)
        if kind == SNAPSHOT:
            # keep the previous generation as a corruption safety net
            self._prune_count(SNAPSHOT, 2)
            self._prune_older(CHECKPOINT, meta.index + 1)
        elif kind == CHECKPOINT:
            self._prune_count(CHECKPOINT, self.max_checkpoints)
        return final

    def _prune_older(self, kind: str, below_idx: int) -> None:
        pruned = False
        for idx, term, path in self._list(kind):
            if idx < below_idx:
                shutil.rmtree(path, ignore_errors=True)
                pruned = True
        if pruned:
            # make the unlink durable: an un-fsynced directory entry can
            # resurrect the pruned capture after a crash, silently
            # re-consuming the bytes emergency reclamation just freed
            sync_dir(self._kind_dir(kind))

    def _prune_count(self, kind: str, max_n: int) -> None:
        entries = self._list(kind)
        pruned = False
        while len(entries) > max_n:
            idx, term, path = entries.pop(0)
            shutil.rmtree(path, ignore_errors=True)
            pruned = True
        if pruned:
            sync_dir(self._kind_dir(kind))

    # -- reads ---------------------------------------------------------------

    def current(self, kind: str = SNAPSHOT) -> Optional[SnapshotMeta]:
        for idx, term, path in reversed(self._list(kind)):
            try:
                return self.codec.read_meta(path)
            except Exception:
                continue
        return None

    def read(self, kind: str = SNAPSHOT) -> Optional[Tuple[SnapshotMeta, Any]]:
        for idx, term, path in reversed(self._list(kind)):
            try:
                return self.codec.read(path)
            except Exception:
                continue  # corrupt capture: fall back to the previous one
        return None

    def latest_checkpoint_at_or_below(self, idx: int) -> Optional[Tuple[SnapshotMeta, Any]]:
        for cidx, term, path in reversed(self._list(CHECKPOINT)):
            if cidx > idx:
                continue
            try:
                return self.codec.read(path)
            except Exception:
                continue
        return None

    def promote_checkpoint(self, idx: int) -> Optional[SnapshotMeta]:
        got = self.latest_checkpoint_at_or_below(idx)
        if got is None:
            return None
        meta, state = got
        self.write(meta, state, kind=SNAPSHOT)
        return meta

    # -- chunked transfer ------------------------------------------------------

    def begin_read(self, chunk_size: int) -> Iterator[bytes]:
        got = self.read(SNAPSHOT)
        if got is None:
            return iter(())
        meta, state = got
        blob = pickle.dumps(state)

        def chunks():
            for off in range(0, max(len(blob), 1), chunk_size):
                yield blob[off : off + chunk_size]

        return chunks()

    def begin_read_stream(
        self, chunk_size: int
    ) -> Optional[Tuple[SnapshotMeta, Iterator[bytes]]]:
        """Open the current snapshot body for chunked sending straight
        FROM DISK — the state object is never decoded and the blob never
        materialized (reference: begin_read/read_chunk,
        src/ra_snapshot.erl:135-210). The fd is opened here, on the
        owning thread; the iterator may then be drained from a sender
        thread (an open fd survives pruning of the directory). The CRC
        trailer is verified as the stream drains — a corrupt body raises
        before the last chunk is yielded. Returns None when no valid
        snapshot exists or the codec's file layout is not the default."""
        if type(self.codec) is not PickleCodec:
            return None  # unknown on-disk layout: caller falls back
        for idx, term, path in reversed(self._list(SNAPSHOT)):
            try:
                meta = self.codec.read_meta(path)
            except Exception:
                continue
            try:
                f = open(os.path.join(path, "snapshot.dat"), "rb")
            except OSError:
                continue
            size = os.fstat(f.fileno()).st_size - _TRAILER.size
            if size < 0:
                f.close()
                continue
            f.seek(size)
            (crc_stored,) = _TRAILER.unpack(f.read(_TRAILER.size))
            f.seek(0)

            def chunks(f=f, size=size, crc_stored=crc_stored):
                try:
                    crc = 0
                    left = size
                    pending = None  # one-chunk buffer so CRC checks
                    while left > 0:  # before the final chunk is yielded
                        buf = f.read(min(chunk_size, left))
                        if not buf:
                            raise IOError("short read streaming snapshot")
                        left -= len(buf)
                        crc = zlib.crc32(buf, crc)
                        if pending is not None:
                            yield pending
                        pending = buf
                    if crc_stored and crc != crc_stored:
                        raise IOError("snapshot crc mismatch while streaming")
                    yield pending if pending is not None else b""
                finally:
                    f.close()

            return meta, chunks()
        return None

    def begin_accept(self, meta: SnapshotMeta) -> Optional[ChunkAccept]:
        """Start an incremental disk-spooled accept (None when the codec
        is not the default — caller falls back to in-RAM accumulation)."""
        if type(self.codec) is not PickleCodec:
            return None
        return ChunkAccept(self, meta)

    def accept_chunks(self, meta: SnapshotMeta, chunks: List[bytes]) -> Any:
        state = decode_snapshot_chunks(chunks)  # untrusted transfer bytes
        self.write(meta, state, kind=SNAPSHOT)
        return state

    def delete_kind(self, kind: str) -> None:
        shutil.rmtree(self._kind_dir(kind), ignore_errors=True)
        os.makedirs(self._kind_dir(kind), exist_ok=True)

    def delete_all(self) -> None:
        for kind in (SNAPSHOT, CHECKPOINT, RECOVERY):
            self.delete_kind(kind)
