"""Log facade interface.

The consensus core only ever touches its log through this interface —
the same boundary as the reference's ``ra_log`` facade (reference:
``src/ra_log.erl:72-99`` for the event/effect types and the API surface
used from ``src/ra_server.erl``). Two implementations exist:

- ``ra_tpu.log.memory.MemoryLog`` — synchronous in-memory fake with
  controllable written-watermark, used by the oracle tests and by
  in-proc integration clusters (cf. reference test/ra_log_memory.erl);
- ``ra_tpu.log.log.Log`` — the real memtable + shared WAL + segments +
  snapshots engine.

Write model is async: ``append``/``write`` make entries *visible* for
reads immediately, but they only become *durable* (counted for
replication acks and quorum) once a ``("written", term, seq)`` event has
been handled. The server learns about durability via
``handle_event`` -> ``written_up_to``.
"""

from __future__ import annotations

from typing import Any, Callable, Iterable, List, Optional, Sequence, Tuple

from ra_tpu.protocol import Entry, SnapshotMeta


class LogApi:
    # -- writes ------------------------------------------------------------

    def append(self, entry: Entry) -> None:
        """Leader append. entry.index must equal next_index(); raises on
        gaps (crash-on-integrity-error, cf. src/ra_log.erl:541-545)."""
        raise NotImplementedError

    def append_many(self, entries: Sequence[Entry]) -> None:
        """Leader bulk append of a contiguous run starting at
        next_index(). Implementations may override with a single-pass
        version; the default loops ``append``."""
        for e in entries:
            self.append(e)

    def write(self, entries: Sequence[Entry]) -> None:
        """Follower write; may rewind/overwrite a divergent suffix."""
        raise NotImplementedError

    def set_last_index(self, idx: int) -> None:
        """Truncate the log tail down to idx (divergence handling)."""
        raise NotImplementedError

    def write_sparse(self, entry: Entry) -> None:
        """Out-of-order write of a live entry during snapshot install."""
        raise NotImplementedError

    # -- reads -------------------------------------------------------------

    def last_index_term(self) -> Tuple[int, int]:
        raise NotImplementedError

    def last_written(self) -> Tuple[int, int]:
        raise NotImplementedError

    def next_index(self) -> int:
        return self.last_index_term()[0] + 1

    def fetch(self, idx: int) -> Optional[Entry]:
        raise NotImplementedError

    def fetch_term(self, idx: int) -> Optional[int]:
        raise NotImplementedError

    def fold(self, lo: int, hi: int, fn: Callable[[Entry, Any], Any], acc: Any) -> Any:
        raise NotImplementedError

    def fetch_range(self, lo: int, hi: int) -> List[Entry]:
        """Contiguous read [lo, hi]; stops early at the first missing
        index (hot path: AER construction and the apply loop — concrete
        logs override with a batched implementation)."""
        out: List[Entry] = []
        for i in range(lo, hi + 1):
            e = self.fetch(i)
            if e is None:
                break
            out.append(e)
        return out

    def sparse_read(self, idxs: Sequence[int]) -> List[Entry]:
        raise NotImplementedError

    def exists(self, idx: int, term: int) -> bool:
        if idx == 0:
            return True
        t = self.fetch_term(idx)
        return t is not None and t == term

    # -- events ------------------------------------------------------------

    def handle_event(self, evt: Any) -> List[Any]:
        """Process a log event (e.g. ("written", term, seq)); returns
        follow-up effects for the server runtime."""
        raise NotImplementedError

    # -- snapshots ---------------------------------------------------------

    def snapshot_index_term(self) -> Optional[Tuple[int, int]]:
        raise NotImplementedError

    def snapshot_meta(self) -> Optional[SnapshotMeta]:
        raise NotImplementedError

    def install_snapshot(self, meta: SnapshotMeta, machine_state: Any) -> List[Any]:
        """Follower-side: replace log prefix with a received snapshot."""
        raise NotImplementedError

    def update_release_cursor(
        self, idx: int, cluster, machine_version: int, machine_state: Any,
        live_indexes=(),
    ) -> List[Any]:
        """Machine says state <= idx is captured in machine_state: maybe
        take a snapshot and truncate everything below except
        ``live_indexes`` (log-as-value-store retention)."""
        raise NotImplementedError

    def checkpoint(
        self, idx: int, cluster, machine_version: int, machine_state: Any,
        live_indexes=(),
    ) -> List[Any]:
        raise NotImplementedError

    def promote_checkpoint(self, idx: int) -> List[Any]:
        raise NotImplementedError

    def read_snapshot(self) -> Optional[Tuple[SnapshotMeta, Any]]:
        raise NotImplementedError

    # -- streaming snapshot transfer (reference: the snapshot behaviour's
    # begin_read/read_chunk + begin_accept/accept_chunk/complete_accept,
    # src/ra_snapshot.erl:135-210,742-860). Defaults return None: logs
    # without a disk-backed snapshot store (MemoryLog) fall back to the
    # whole-blob transfer path. ---------------------------------------------

    def begin_snapshot_read(self, chunk_size: int):
        """-> (meta, byte-chunk iterator reading from DISK) or None."""
        return None

    def begin_accept_snapshot(self, meta: SnapshotMeta):
        """-> ChunkAccept spooling chunks to disk, or None."""
        return None

    def complete_accept_snapshot(self, accept) -> Any:
        """Seal an accept started by :meth:`begin_accept_snapshot`:
        decode + promote the capture, apply the log-side bookkeeping of
        :meth:`install_snapshot`, return the machine state."""
        raise NotImplementedError

    # -- lifecycle ---------------------------------------------------------

    def close(self) -> None:
        pass

    def overview(self) -> dict:
        li, lt = self.last_index_term()
        wi, wt = self.last_written()
        return {
            "last_index": li,
            "last_term": lt,
            "last_written_index": wi,
            "last_written_term": wt,
            "snapshot": self.snapshot_index_term(),
        }
