"""Durable per-server metadata store (term / voted_for / last_applied).

File-backed successor to the reference's dets-based ``ra_log_meta``
(``src/ra_log_meta.erl``): one store per system, batched async writes for
``last_applied``, synchronous durability for term/vote changes. Format:
an append-only journal of CRC-framed pickled ``(uid, key, value)``
records, compacted to a snapshot rewrite once it grows past a threshold.
"""

from __future__ import annotations

import os
import pickle
import struct
import threading
import zlib
from typing import Any, Dict, Optional

from ra_tpu import faults
from ra_tpu.log.meta import MetaApi
from ra_tpu.utils.lib import atomic_write, retry

_FRAME = struct.Struct("<II")  # crc, len


class FileMeta(MetaApi):
    COMPACT_BYTES = 4 * 1024 * 1024

    def __init__(self, path: str):
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        # failpoint scope label; the owning node sets it to its name
        self.fault_scope = None
        self._lock = threading.Lock()
        self._tab: Dict[str, Dict[str, Any]] = {}
        self._dirty = False
        self._recover()
        self._f = open(self.path, "ab")

    # ------------------------------------------------------------------

    def _recover(self) -> None:
        base = self.path + ".base"
        if os.path.exists(base):
            try:
                self._tab = pickle.loads(open(base, "rb").read())
            except Exception:
                self._tab = {}
        if not os.path.exists(self.path):
            return
        data = open(self.path, "rb").read()
        pos, n = 0, len(data)
        while pos + _FRAME.size <= n:
            crc, ln = _FRAME.unpack_from(data, pos)
            pos += _FRAME.size
            payload = data[pos : pos + ln]
            if len(payload) < ln or (crc and zlib.crc32(payload) != crc):
                break  # torn tail
            pos += ln
            try:
                uid, key, value = pickle.loads(payload)
            except Exception:
                break
            if key == "__deleted__":
                self._tab.pop(uid, None)
            else:
                self._tab.setdefault(uid, {})[key] = value

    def _append(self, uid: str, key: str, value: Any, sync: bool) -> None:
        payload = pickle.dumps((uid, key, value))
        rec = _FRAME.pack(zlib.crc32(payload), len(payload)) + payload
        with self._lock:
            self._tab.setdefault(uid, {})[key] = value
            start = self._f.tell()
            attempt = [0]

            def _write():
                if attempt[0]:
                    # a prior partial write may have left bytes: rewind
                    # SIZE and POSITION to the pre-record offset (seek
                    # matters after compaction reopens the journal in
                    # "wb" mode — truncate alone would leave the write
                    # position past the hole and recovery would stop at
                    # the zero frame, losing the record). First attempts
                    # pay nothing.
                    self._f.truncate(start)
                    self._f.seek(start)
                attempt[0] += 1
                faults.checked_write("meta.append", self._f, rec,
                                     self.fault_scope)

            retry(_write, attempts=3, delay_s=0.02)
            if sync:
                # fdatasync is OUTSIDE the retry on purpose: a failed
                # fsync is poison (the kernel may have dropped dirty
                # pages covering EARLIER records, not just this one) —
                # it must propagate to the caller, never be retried
                # into a false "success" (same rule as Wal._sync)
                self._f.flush()
                os.fdatasync(self._f.fileno())
            else:
                self._dirty = True
            if self._f.tell() > self.COMPACT_BYTES:
                self._compact_locked()

    def _compact_locked(self) -> None:
        atomic_write(self.path + ".base", pickle.dumps(self._tab))
        self._f.close()
        self._f = open(self.path, "wb")

    # ------------------------------------------------------------------

    def store(self, uid: str, key: str, value: Any) -> None:
        self._append(uid, key, value, sync=False)

    def store_sync(self, uid: str, key: str, value: Any) -> None:
        self._append(uid, key, value, sync=True)

    def fetch(self, uid: str, key: str, default: Any = None) -> Any:
        return self._tab.get(uid, {}).get(key, default)

    def sync(self) -> None:
        with self._lock:
            if self._dirty:
                self._f.flush()
                os.fdatasync(self._f.fileno())
                self._dirty = False

    def delete(self, uid: str) -> None:
        self._append(uid, "__deleted__", True, sync=True)
        with self._lock:
            self._tab.pop(uid, None)

    def close(self) -> None:
        self.sync()
        self._f.close()
