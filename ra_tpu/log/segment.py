"""On-disk immutable-ish segment files.

Long-term home of log entries once the WAL rolls over — the counterpart
of the reference's segment files (reference: ``src/ra_log_segment.erl``
— fixed index region + data region, per-entry CRC, sparse reads via
binary search, bounded pending writes). Layout (little-endian):

    header : magic b"RTS1" | max_count u32
    index  : max_count slots of (idx u64 | term u64 | offset u64 |
             length u32 | crc u32)  — slot order = append order
    data   : concatenated payloads

Index slots are written incrementally as entries append (buffered, then
flushed+fsynced on ``sync``). An unfilled slot has idx 0 (indexes are
>= 1), so recovery simply stops at the first empty slot.
"""

from __future__ import annotations

import os
import struct
import zlib
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

from ra_tpu import faults
from ra_tpu.utils.lib import retry

MAGIC = b"RTS1"
_HDR = struct.Struct("<4sI")
_SLOT = struct.Struct("<QQQII")


class SegmentWriterHandle:
    """Append handle for one segment file."""

    def __init__(self, path: str, max_count: int = 4096, compute_checksums: bool = True):
        self.path = path
        self.max_count = max_count
        self.compute_checksums = compute_checksums
        self.count = 0
        self.range: Optional[Tuple[int, int]] = None
        exists = os.path.exists(path)
        # transient open failures (EMFILE/EAGAIN bursts) retry with
        # backoff — ra_file parity (reference: src/ra_file.erl:1-37);
        # fsync failures stay poison and are never retried
        self._f = retry(lambda: open(path, "r+b" if exists else "w+b"),
                        attempts=3, delay_s=0.02)
        if not exists or os.path.getsize(path) < _HDR.size:
            self._f.write(_HDR.pack(MAGIC, max_count))
            self._f.write(b"\x00" * (_SLOT.size * max_count))
            self._f.flush()
            self._data_end = self._data_start
        else:
            magic, mc = _HDR.unpack(self._f.read(_HDR.size))
            if magic != MAGIC:
                raise ValueError(f"bad segment magic in {path}")
            self.max_count = mc
            # scan index to find fill level
            idx_bytes = self._f.read(_SLOT.size * mc)
            end = self._data_start
            for i in range(mc):
                idx, term, off, ln, crc = _SLOT.unpack_from(idx_bytes, i * _SLOT.size)
                if idx == 0:
                    break
                self.count += 1
                self.range = (
                    (min(self.range[0], idx), max(self.range[1], idx))
                    if self.range
                    else (idx, idx)
                )
                end = max(end, off + ln)
            self._data_end = end

    @property
    def _data_start(self) -> int:
        return _HDR.size + _SLOT.size * self.max_count

    def is_full(self) -> bool:
        return self.count >= self.max_count

    def append(self, idx: int, term: int, payload: bytes) -> None:
        if self.is_full():
            raise ValueError("segment full")
        crc = zlib.crc32(payload) if self.compute_checksums else 0
        off = self._data_end
        self._f.seek(off)
        # a torn payload write leaves the index slot unwritten (idx 0),
        # so recovery stops cleanly at the previous entry; a torn SLOT
        # is caught by the per-entry CRC on read
        faults.checked_write("segment.append", self._f, payload)
        self._f.seek(_HDR.size + self.count * _SLOT.size)
        self._f.write(_SLOT.pack(idx, term, off, len(payload), crc))
        self._data_end = off + len(payload)
        self.count += 1
        # min/max (not blind extend): appends may arrive out of index
        # order across retry/recovery replays; ranges must never invert
        self.range = (
            (min(self.range[0], idx), max(self.range[1], idx))
            if self.range
            else (idx, idx)
        )

    def sync(self) -> None:
        self._f.flush()
        os.fdatasync(self._f.fileno())

    def close(self) -> None:
        try:
            self._f.flush()
        finally:
            self._f.close()


class SegmentReader:
    """Read-only view over one segment file. Two index modes (reference:
    ``src/ra_log_segment.erl:55-59``):

    - ``"map"`` (default): the whole index region is parsed into a dict
      on open — fastest lookups, O(entries) memory per open segment;
    - ``"binary"``: the raw index bytes are kept unparsed and point
      lookups binary-search the slot array (segments are written in
      ascending index order; rewritten out-of-order files detected at
      open fall back to map mode). Sparse external reads over many
      segments stay cheap in memory, and a small read-ahead caches the
      next few entries' payloads per seek (reference read-ahead,
      ``src/ra_log_segment.erl:468-505``).
    """

    READ_AHEAD = 8

    def __init__(self, path: str, compute_checksums: bool = True, mode: str = "map"):
        self.path = path
        self.compute_checksums = compute_checksums
        self.mode = mode
        # reader opens retry like the writer's (ra_file parity): sparse
        # reads race compaction renames, and a transient EMFILE burst
        # must not fail a read that would succeed a moment later
        self._f = retry(lambda: open(path, "rb"), attempts=3, delay_s=0.02)
        magic, mc = _HDR.unpack(self._f.read(_HDR.size))
        if magic != MAGIC:
            raise ValueError(f"bad segment magic in {path}")
        idx_bytes = self._f.read(_SLOT.size * mc)
        self.range: Optional[Tuple[int, int]] = None
        self._ra_cache: Dict[int, Tuple[int, bytes]] = {}
        # count filled slots + establish range/monotonicity in one scan
        n = 0
        lo = hi = None
        monotone = True
        prev = -1
        for i in range(mc):
            idx = _SLOT.unpack_from(idx_bytes, i * _SLOT.size)[0]
            if idx == 0:
                break
            n += 1
            lo = idx if lo is None else min(lo, idx)
            hi = idx if hi is None else max(hi, idx)
            if idx <= prev:
                monotone = False
            prev = idx
        self._n = n
        self._last_read = -2  # sequential-pattern detector for read-ahead
        if lo is not None:
            self.range = (lo, hi)
        if mode == "binary" and monotone:
            self._idx_bytes: Optional[bytes] = idx_bytes
            self.index = _LazyIndex(self)
        else:
            # map mode (or non-monotone rewrites: binary search invalid)
            self.mode = "map"
            self._idx_bytes = None
            self.index = {}
            for i in range(n):
                idx, term, off, ln, crc = _SLOT.unpack_from(idx_bytes, i * _SLOT.size)
                self.index[idx] = (term, off, ln, crc)

    def _slot_pos(self, idx: int) -> int:
        """Binary-search the raw slot array; returns the slot position
        or -1 (binary mode only)."""
        lo, hi = 0, self._n - 1
        b = self._idx_bytes
        while lo <= hi:
            mid = (lo + hi) // 2
            sidx = _SLOT.unpack_from(b, mid * _SLOT.size)[0]
            if sidx == idx:
                return mid
            if sidx < idx:
                lo = mid + 1
            else:
                hi = mid - 1
        return -1

    def _slot_for(self, idx: int) -> Optional[Tuple[int, int, int, int]]:
        pos = self._slot_pos(idx)
        if pos < 0:
            return None
        _sidx, term, off, ln, crc = _SLOT.unpack_from(self._idx_bytes, pos * _SLOT.size)
        return (term, off, ln, crc)

    def _entry(self, idx: int) -> Optional[Tuple[int, int, int, int]]:
        if self._idx_bytes is not None:
            return self._slot_for(idx)
        return self.index.get(idx)

    def term(self, idx: int) -> Optional[int]:
        e = self._entry(idx)
        return e[0] if e else None

    def read(self, idx: int) -> Optional[Tuple[int, bytes]]:
        hit = self._ra_cache.get(idx)
        if hit is not None:
            self._last_read = idx
            return hit
        pos = self._slot_pos(idx) if self._idx_bytes is not None else -1
        if self._idx_bytes is not None:
            if pos < 0:
                return None
            e = _SLOT.unpack_from(self._idx_bytes, pos * _SLOT.size)[1:]
        else:
            e = self.index.get(idx)
            if e is None:
                return None
        term, off, ln, crc = e
        self._f.seek(off)
        payload = self._f.read(ln)
        if self.compute_checksums and crc and zlib.crc32(payload) != crc:
            raise IOError(f"segment crc mismatch at idx {idx} in {self.path}")
        if self._idx_bytes is not None and self._last_read == idx - 1:
            # a forward walk is in progress: prefetch the next slots with
            # ONE contiguous read (slots and data are append-ordered in
            # binary mode)
            self._read_ahead(pos)
        self._last_read = idx
        return term, payload

    def _read_ahead(self, pos: int) -> None:
        self._ra_cache.clear()
        b = self._idx_bytes
        last = min(pos + self.READ_AHEAD, self._n - 1)
        if last <= pos:
            return
        slots = [
            _SLOT.unpack_from(b, i * _SLOT.size)
            for i in range(pos + 1, last + 1)
        ]
        start = slots[0][2]
        end = slots[-1][2] + slots[-1][3]
        self._f.seek(start)
        blob = self._f.read(end - start)
        for sidx, term, off, ln, crc in slots:
            payload = blob[off - start : off - start + ln]
            if len(payload) < ln:
                break
            if self.compute_checksums and crc and zlib.crc32(payload) != crc:
                break
            self._ra_cache[sidx] = (term, payload)

    def indexes(self) -> List[int]:
        if self._idx_bytes is not None:
            out = []
            for i in range(self._n):
                out.append(_SLOT.unpack_from(self._idx_bytes, i * _SLOT.size)[0])
            return out
        return sorted(self.index)

    def close(self) -> None:
        self._f.close()


class _LazyIndex:
    """Binary-mode stand-in for the parsed index dict: supports the
    mapping surface the read/compaction paths use without materializing
    every slot. Deliberately NOT a dict subclass — an unsupported dict
    method must raise, never silently answer from an empty mapping."""

    __slots__ = ("_r",)

    def __init__(self, reader: SegmentReader):
        self._r = reader

    def get(self, idx, default=None):
        e = self._r._slot_for(idx)
        return e if e is not None else default

    def __getitem__(self, idx):
        e = self._r._slot_for(idx)
        if e is None:
            raise KeyError(idx)
        return e

    def __contains__(self, idx):
        return self._r._slot_for(idx) is not None

    def __len__(self):
        return self._r._n

    def __iter__(self):
        return iter(self._r.indexes())

    def keys(self):
        return self._r.indexes()

    def values(self):
        return [self._r._slot_for(i) for i in self._r.indexes()]

    def items(self):
        return [(i, self._r._slot_for(i)) for i in self._r.indexes()]
