"""On-disk immutable-ish segment files.

Long-term home of log entries once the WAL rolls over — the counterpart
of the reference's segment files (reference: ``src/ra_log_segment.erl``
— fixed index region + data region, per-entry CRC, sparse reads via
binary search, bounded pending writes). Layout (little-endian):

    header : magic b"RTS1" | max_count u32
    index  : max_count slots of (idx u64 | term u64 | offset u64 |
             length u32 | crc u32)  — slot order = append order
    data   : concatenated payloads

Index slots are written incrementally as entries append (buffered, then
flushed+fsynced on ``sync``). An unfilled slot has idx 0 (indexes are
>= 1), so recovery simply stops at the first empty slot.
"""

from __future__ import annotations

import os
import struct
import zlib
from typing import Dict, Iterator, List, Optional, Sequence, Tuple

MAGIC = b"RTS1"
_HDR = struct.Struct("<4sI")
_SLOT = struct.Struct("<QQQII")


class SegmentWriterHandle:
    """Append handle for one segment file."""

    def __init__(self, path: str, max_count: int = 4096, compute_checksums: bool = True):
        self.path = path
        self.max_count = max_count
        self.compute_checksums = compute_checksums
        self.count = 0
        self.range: Optional[Tuple[int, int]] = None
        exists = os.path.exists(path)
        self._f = open(path, "r+b" if exists else "w+b")
        if not exists or os.path.getsize(path) < _HDR.size:
            self._f.write(_HDR.pack(MAGIC, max_count))
            self._f.write(b"\x00" * (_SLOT.size * max_count))
            self._f.flush()
            self._data_end = self._data_start
        else:
            magic, mc = _HDR.unpack(self._f.read(_HDR.size))
            if magic != MAGIC:
                raise ValueError(f"bad segment magic in {path}")
            self.max_count = mc
            # scan index to find fill level
            idx_bytes = self._f.read(_SLOT.size * mc)
            end = self._data_start
            for i in range(mc):
                idx, term, off, ln, crc = _SLOT.unpack_from(idx_bytes, i * _SLOT.size)
                if idx == 0:
                    break
                self.count += 1
                self.range = (
                    (min(self.range[0], idx), max(self.range[1], idx))
                    if self.range
                    else (idx, idx)
                )
                end = max(end, off + ln)
            self._data_end = end

    @property
    def _data_start(self) -> int:
        return _HDR.size + _SLOT.size * self.max_count

    def is_full(self) -> bool:
        return self.count >= self.max_count

    def append(self, idx: int, term: int, payload: bytes) -> None:
        if self.is_full():
            raise ValueError("segment full")
        crc = zlib.crc32(payload) if self.compute_checksums else 0
        off = self._data_end
        self._f.seek(off)
        self._f.write(payload)
        self._f.seek(_HDR.size + self.count * _SLOT.size)
        self._f.write(_SLOT.pack(idx, term, off, len(payload), crc))
        self._data_end = off + len(payload)
        self.count += 1
        # min/max (not blind extend): appends may arrive out of index
        # order across retry/recovery replays; ranges must never invert
        self.range = (
            (min(self.range[0], idx), max(self.range[1], idx))
            if self.range
            else (idx, idx)
        )

    def sync(self) -> None:
        self._f.flush()
        os.fdatasync(self._f.fileno())

    def close(self) -> None:
        try:
            self._f.flush()
        finally:
            self._f.close()


class SegmentReader:
    """Read-only view over one segment file; index parsed once on open
    (the reference's "map mode"; binary-search-on-disk mode is a later
    optimization)."""

    def __init__(self, path: str, compute_checksums: bool = True):
        self.path = path
        self.compute_checksums = compute_checksums
        self._f = open(path, "rb")
        magic, mc = _HDR.unpack(self._f.read(_HDR.size))
        if magic != MAGIC:
            raise ValueError(f"bad segment magic in {path}")
        idx_bytes = self._f.read(_SLOT.size * mc)
        # idx -> (term, offset, length, crc); later slots win (rewrites)
        self.index: Dict[int, Tuple[int, int, int, int]] = {}
        self.range: Optional[Tuple[int, int]] = None
        for i in range(mc):
            idx, term, off, ln, crc = _SLOT.unpack_from(idx_bytes, i * _SLOT.size)
            if idx == 0:
                break
            self.index[idx] = (term, off, ln, crc)
        if self.index:
            self.range = (min(self.index), max(self.index))

    def term(self, idx: int) -> Optional[int]:
        e = self.index.get(idx)
        return e[0] if e else None

    def read(self, idx: int) -> Optional[Tuple[int, bytes]]:
        e = self.index.get(idx)
        if e is None:
            return None
        term, off, ln, crc = e
        self._f.seek(off)
        payload = self._f.read(ln)
        if self.compute_checksums and crc and zlib.crc32(payload) != crc:
            raise IOError(f"segment crc mismatch at idx {idx} in {self.path}")
        return term, payload

    def indexes(self) -> List[int]:
        return sorted(self.index)

    def close(self) -> None:
        self._f.close()
