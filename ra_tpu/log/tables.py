"""Per-system registry of memtables and snapshot floor state.

Combines the roles of the reference's ``ra_log_ets`` (owner of all
memtable ETS tables so they outlive individual server crashes,
``src/ra_log_ets.erl``) and ``ra_log_snapshot_state`` (the public table
of per-UId snapshot index / smallest live index the WAL and segment
writer consult to drop dead writes, ``src/ra_log_snapshot_state.erl``).
One instance per running system; thread-safe.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from ra_tpu.log.memtable import MemTable
from ra_tpu.utils.seq import Seq


class TableRegistry:
    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._tables: Dict[str, MemTable] = {}
        # uid -> (snapshot_idx, smallest_live_idx, live_indexes Seq)
        self._snap: Dict[str, Tuple[int, int, Seq]] = {}

    # -- memtables ---------------------------------------------------------

    def mem_table(self, uid: str) -> MemTable:
        with self._lock:
            t = self._tables.get(uid)
            if t is None:
                t = MemTable(uid)
                self._tables[uid] = t
            return t

    def delete_mem_table(self, uid: str) -> None:
        with self._lock:
            self._tables.pop(uid, None)

    def uids(self) -> List[str]:
        return list(self._tables.keys())

    # -- snapshot floor state ----------------------------------------------

    def set_snapshot_state(
        self, uid: str, snapshot_idx: int, live_indexes: Seq
    ) -> None:
        smallest = live_indexes.first()
        smallest_live = smallest if smallest is not None else snapshot_idx + 1
        with self._lock:
            self._snap[uid] = (snapshot_idx, smallest_live, live_indexes)

    def snapshot_index(self, uid: str) -> int:
        return self._snap.get(uid, (0, 1, Seq.empty()))[0]

    def smallest_live_index(self, uid: str) -> int:
        """Writes below this index are dead and may be dropped by the WAL
        and skipped by the segment writer."""
        return self._snap.get(uid, (0, 1, Seq.empty()))[1]

    def live_indexes(self, uid: str) -> Seq:
        return self._snap.get(uid, (0, 1, Seq.empty()))[2]

    def delete_snapshot_state(self, uid: str) -> None:
        with self._lock:
            self._snap.pop(uid, None)
