"""In-memory log with controllable durability watermark.

The oracle's storage fake (cf. reference ``test/ra_log_memory.erl`` —
a pure map implementation of the full log API with fake async
``last_written``). With ``auto_written=True`` every write is durable
immediately; with ``auto_written=False`` the test (or in-proc runtime)
must drain ``pending_written_events()`` and feed them back through
``handle_event`` to advance the watermark — exactly how the real WAL's
written notifications behave.

Storage layout: the contiguous tail lives in a plain Python list
(``_list`` holds indexes ``[_base, _base+len)``), so the hot paths —
bulk append, ``fetch_range`` for AER construction and the apply loop —
are C-level ``extend``/slice operations instead of per-entry dict
traffic. Rare out-of-window entries (live entries kept below a
snapshot floor, sparse writes during snapshot install) go to the
``_sparse`` dict.
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ra_tpu.log.api import LogApi
from ra_tpu.protocol import Entry, SnapshotMeta
from ra_tpu.utils.seq import Seq


class MemoryLog(LogApi):
    def __init__(self, auto_written: bool = True):
        self._base = 1  # index of _list[0]
        self._list: List[Entry] = []  # contiguous run [_base, _base+len)
        self._sparse: Dict[int, Entry] = {}  # out-of-window entries
        self._last_index = 0
        self._last_term = 0
        self._written_index = 0
        self._written_term = 0
        self._first_index = 1
        self.auto_written = auto_written
        self._pending: Seq = Seq.empty()
        self._snapshot: Optional[Tuple[SnapshotMeta, Any]] = None
        self._checkpoints: List[Tuple[SnapshotMeta, Any]] = []

    # -- writes ------------------------------------------------------------

    def append(self, entry: Entry) -> None:
        if entry.index != self._last_index + 1:
            raise ValueError(
                f"non-contiguous append: {entry.index} after {self._last_index}"
            )
        self._store_run((entry,))

    def append_many(self, entries: Sequence[Entry]) -> None:
        if not entries:
            return
        if entries[0].index != self._last_index + 1:
            raise ValueError(
                f"non-contiguous append: {entries[0].index} after "
                f"{self._last_index}"
            )
        self._store_run(entries)

    def write(self, entries: Sequence[Entry]) -> None:
        if not entries:
            return
        first = entries[0].index
        if first > self._last_index + 1:
            raise ValueError(f"gap: write at {first}, last is {self._last_index}")
        if first <= self._last_index:
            # Overwrite: truncate divergent suffix, rewind watermark
            # (cf. src/ra_log.erl:560-580 last_written rewind).
            self.set_last_index(first - 1)
        self._store_run(entries)

    def _store_run(self, entries: Sequence[Entry]) -> None:
        """One-pass store of a contiguous run starting at
        ``_last_index + 1`` (callers validated the head)."""
        first = entries[0].index
        lst = self._list
        if not lst:
            self._base = first
        elif first != self._base + len(lst):
            # the contiguous window does not reach first (possible only
            # after sparse writes beyond the tail): spill the window to
            # the sparse map and restart it at first
            for e in lst:
                self._sparse[e.index] = e
            lst.clear()
            self._base = first
        lst.extend(entries)
        last = entries[-1]
        self._last_index = last.index
        self._last_term = last.term
        if self.auto_written:
            self._written_index = last.index
            self._written_term = last.term
        else:
            for e in entries:
                self._pending = self._pending.add(e.index)

    def write_sparse(self, entry: Entry) -> None:
        off = entry.index - self._base
        lst = self._list
        if 0 <= off < len(lst):
            lst[off] = entry
        elif off == len(lst) and (lst or entry.index == self._base):
            lst.append(entry)
        else:
            self._sparse[entry.index] = entry
        if entry.index > self._last_index:
            self._last_index = entry.index
            self._last_term = entry.term
            if self.auto_written:
                self._written_index = entry.index
                self._written_term = entry.term

    def set_last_index(self, idx: int) -> None:
        cut = idx - self._base + 1
        if cut < 0:
            cut = 0
        del self._list[cut:]
        if self._sparse:
            for i in [k for k in self._sparse if k > idx]:
                del self._sparse[i]
        self._last_index = idx
        t = self.fetch_term(idx)
        self._last_term = t if t is not None else 0
        if self._written_index > idx:
            self._written_index = idx
            self._written_term = self._last_term
        self._pending = self._pending.limit(idx)

    # -- durability simulation --------------------------------------------

    def pending_written_events(self) -> List[Any]:
        """Drain pending writes as ("written", term, seq) events."""
        if self._pending.is_empty():
            return []
        evts = []
        # group pending by term, preserving order
        cur_term = None
        cur: List[int] = []
        for idx in self._pending:
            e = self.fetch(idx)
            if e is None:
                continue
            if cur_term is None or e.term == cur_term:
                cur_term = e.term
                cur.append(idx)
            else:
                evts.append(("written", cur_term, Seq.from_list(cur)))
                cur_term, cur = e.term, [idx]
        if cur:
            evts.append(("written", cur_term, Seq.from_list(cur)))
        self._pending = Seq.empty()
        return evts

    def handle_event(self, evt: Any) -> List[Any]:
        if isinstance(evt, tuple) and evt and evt[0] == "written":
            _, term, seq = evt
            if seq is None:  # durability already reflected (auto mode)
                return []
            last = seq.last()
            if last is None:
                return []
            # Only advance if the entry we wrote is still the one in the
            # log at that index (it may have been overwritten since).
            e = self.fetch(last)
            if e is not None and e.term == term and last > self._written_index:
                self._written_index = last
                self._written_term = term
            return []
        return []

    # -- reads -------------------------------------------------------------

    def last_index_term(self) -> Tuple[int, int]:
        return self._last_index, self._last_term

    def last_written(self) -> Tuple[int, int]:
        return self._written_index, self._written_term

    def first_index(self) -> int:
        return self._first_index

    def fetch(self, idx: int) -> Optional[Entry]:
        off = idx - self._base
        lst = self._list
        if 0 <= off < len(lst):
            return lst[off]
        return self._sparse.get(idx)

    def fetch_term(self, idx: int) -> Optional[int]:
        e = self.fetch(idx)
        if e is not None:
            return e.term
        if self._snapshot is not None and idx == self._snapshot[0].index:
            return self._snapshot[0].term
        if idx == 0:
            return 0
        return None

    def fold(self, lo: int, hi: int, fn: Callable[[Entry, Any], Any], acc: Any) -> Any:
        for i in range(lo, hi + 1):
            e = self.fetch(i)
            if e is None:
                raise KeyError(f"missing log entry {i}")
            acc = fn(e, acc)
        return acc

    def fetch_range(self, lo: int, hi: int) -> List[Entry]:
        """Entries lo..hi inclusive, stopping at the first missing index
        (same contract as the file-backed log)."""
        off = lo - self._base
        lst = self._list
        if 0 <= off < len(lst):
            out = lst[off : hi - self._base + 1]
            nxt = lo + len(out)
            if nxt <= hi and self._sparse:
                # window ended before hi: continue through sparse runs
                fetch = self._sparse.get
                for i in range(nxt, hi + 1):
                    e = fetch(i)
                    if e is None:
                        break
                    out.append(e)
            return out
        out: List[Entry] = []
        fetch = self.fetch
        for i in range(lo, hi + 1):
            e = fetch(i)
            if e is None:
                break
            out.append(e)
        return out

    def sparse_read(self, idxs: Sequence[int]) -> List[Entry]:
        out = []
        for i in idxs:
            e = self.fetch(i)
            if e is not None:
                out.append(e)
        return out

    # -- snapshots ---------------------------------------------------------

    def snapshot_index_term(self) -> Optional[Tuple[int, int]]:
        if self._snapshot is None:
            return None
        m = self._snapshot[0]
        return (m.index, m.term)

    def snapshot_meta(self) -> Optional[SnapshotMeta]:
        return self._snapshot[0] if self._snapshot else None

    def install_snapshot(self, meta: SnapshotMeta, machine_state: Any) -> List[Any]:
        self._snapshot = (meta, machine_state)
        live = set(meta.live_indexes)
        lst = self._list
        cut = meta.index - self._base + 1
        if cut > 0:
            cut = min(cut, len(lst))
            for e in lst[:cut]:
                if e.index in live:
                    self._sparse[e.index] = e
            del lst[:cut]
            self._base = meta.index + 1
        elif not lst:
            self._base = meta.index + 1
        if self._sparse:
            for i in [
                k for k in self._sparse if k <= meta.index and k not in live
            ]:
                del self._sparse[i]
        self._first_index = meta.index + 1
        if self._last_index < meta.index:
            self._last_index = meta.index
            self._last_term = meta.term
        if self._written_index < meta.index:
            self._written_index = meta.index
            self._written_term = meta.term
        self._pending = self._pending.floor(meta.index + 1)
        return []

    def update_release_cursor(
        self, idx, cluster, machine_version, machine_state, live_indexes=()
    ) -> List[Any]:
        if idx <= (self._snapshot[0].index if self._snapshot else 0):
            return []
        t = self.fetch_term(idx)
        if t is None:
            return []
        meta = SnapshotMeta(
            index=idx, term=t, cluster=tuple(cluster), machine_version=machine_version,
            live_indexes=tuple(i for i in live_indexes if i <= idx),
        )
        return self.install_snapshot(meta, machine_state)

    def checkpoint(
        self, idx, cluster, machine_version, machine_state, live_indexes=()
    ) -> List[Any]:
        t = self.fetch_term(idx)
        if t is None:
            return []
        meta = SnapshotMeta(
            index=idx, term=t, cluster=tuple(cluster), machine_version=machine_version,
            live_indexes=tuple(i for i in live_indexes if i <= idx),
        )
        self._checkpoints.append((meta, machine_state))
        return []

    def promote_checkpoint(self, idx: int) -> List[Any]:
        eligible = [cp for cp in self._checkpoints if cp[0].index <= idx]
        if not eligible:
            return []
        meta, state = max(eligible, key=lambda cp: cp[0].index)
        self._checkpoints = [cp for cp in self._checkpoints if cp[0].index > meta.index]
        return self.install_snapshot(meta, state)

    def read_snapshot(self) -> Optional[Tuple[SnapshotMeta, Any]]:
        return self._snapshot

    # recovery checkpoints (orderly-shutdown replay skip)

    def write_recovery_checkpoint(self, meta: SnapshotMeta, machine_state: Any) -> None:
        self._recovery = (meta, machine_state)

    def read_recovery_checkpoint(self) -> Optional[Tuple[SnapshotMeta, Any]]:
        return getattr(self, "_recovery", None)

    def discard_recovery_checkpoint(self) -> None:
        self._recovery = None
