"""Durable per-server metadata store interface.

Holds ``current_term``, ``voted_for`` and ``last_applied`` per server UId
— the role the reference's dets-backed ``ra_log_meta`` plays (reference:
``src/ra_log_meta.erl:28-29``): term/vote changes are stored synchronously
(they gate correctness), ``last_applied`` asynchronously. ``InMemoryMeta``
backs the oracle tests; the durable file-backed store lives in
``ra_tpu.log.meta_store``.
"""

from __future__ import annotations

from typing import Any, Dict, Optional


class MetaApi:
    def store(self, uid: str, key: str, value: Any) -> None:
        """Async-durable store (batched; may be lost on crash)."""
        raise NotImplementedError

    def store_sync(self, uid: str, key: str, value: Any) -> None:
        """Synchronously durable store (term/vote changes)."""
        raise NotImplementedError

    def fetch(self, uid: str, key: str, default: Any = None) -> Any:
        raise NotImplementedError

    def delete(self, uid: str) -> None:
        raise NotImplementedError


class InMemoryMeta(MetaApi):
    def __init__(self) -> None:
        self._tab: Dict[str, Dict[str, Any]] = {}
        self.sync_calls = 0

    def store(self, uid: str, key: str, value: Any) -> None:
        self._tab.setdefault(uid, {})[key] = value

    def store_sync(self, uid: str, key: str, value: Any) -> None:
        self.sync_calls += 1
        self.store(uid, key, value)

    def fetch(self, uid: str, key: str, default: Any = None) -> Any:
        return self._tab.get(uid, {}).get(key, default)

    def delete(self, uid: str) -> None:
        self._tab.pop(uid, None)
