"""Session/lock-service machine: TTL leases, monitor-driven expiry,
lock acquire/release/steal with fencing tokens.

Capability model: the reference's lock/lease patterns on top of ra
(session processes monitored by the machine, leases re-armed through
machine ``Timer`` effects, locks fenced by a monotonically increasing
token so a paused ex-holder can never overwrite a newer holder's
writes). This is the workload that stresses timer effects and monitor
cleanup in ways kv/fifo cannot — which is exactly why it lands together
with the deterministic simulation plane (docs/INTERNALS.md §19) that
can explore its interleavings.

Commands:
  ("session_open", sid, ttl_ms)        -- open (or renew if open)
  ("session_renew", sid)               -- extend the lease one TTL
  ("session_close", sid)               -- clean close, locks released
  ("lock_acquire", sid, key[, "steal"]) -- grant / queue / steal
  ("lock_release", sid, key)
  ("down", sid, info)                  -- builtin monitor DOWN
  ("timeout", ("session", sid, gen))   -- builtin machine-timer fire

Determinism contract: apply NEVER reads a clock. A lease's lapse is the
arrival of its ``("timeout", ("session", sid, gen))`` command — armed
via a ``Timer`` effect whose name carries the lease GENERATION, so a
renewal (gen bump) makes any in-flight older timer a provable no-op.
Every expiry in the replicated history is therefore attributable to
exactly one cause: a matching-generation timeout command (TTL lapse) or
a ``down`` command (monitor fired) — the property the lock-safety
oracle asserts.

Fencing: every grant (acquire, steal, handoff) draws a fresh token from
a per-machine monotonic counter. "Never two live holders" is structural
(one owner per key in the map); the client-visible half is "tokens per
key strictly increase", so a stale holder's token can always be fenced.
"""

from __future__ import annotations

import dataclasses
from collections import OrderedDict, deque
from typing import Any, Dict, Optional, Set, Tuple

from ra_tpu.effects import Demonitor, Monitor, ReleaseCursor, SendMsg, Timer
from ra_tpu.machine import Machine


@dataclasses.dataclass
class Session:
    ttl_ms: int
    gen: int  # lease generation; bumped on every renew/reopen


@dataclasses.dataclass
class SessionState:
    sessions: "OrderedDict[Any, Session]" = dataclasses.field(
        default_factory=OrderedDict
    )
    # key -> (owner_sid, fencing_token)
    locks: "OrderedDict[Any, Tuple[Any, int]]" = dataclasses.field(
        default_factory=OrderedDict
    )
    # key -> waiting sids in arrival order
    waiters: Dict[Any, deque] = dataclasses.field(default_factory=dict)
    next_token: int = 0

    def clone(self) -> "SessionState":
        return SessionState(
            sessions=OrderedDict(
                (k, Session(s.ttl_ms, s.gen)) for k, s in self.sessions.items()
            ),
            locks=OrderedDict(self.locks),
            waiters={k: deque(v) for k, v in self.waiters.items()},
            next_token=self.next_token,
        )

    def held_by(self, sid) -> list:
        return [k for k, (o, _t) in self.locks.items() if o == sid]


class SessionMachine(Machine):
    """``ctr`` is an optional ``Counters`` vector (``SESSION_FIELDS``);
    only ONE instance in a replicated fold should carry it, or every
    replica's apply bumps the same event three times."""

    def __init__(self, ctr=None):
        self.ctr = ctr

    def _c(self, field: str, n: int = 1) -> None:
        if self.ctr is not None:
            self.ctr.incr(field, n)

    def init(self, config) -> SessionState:
        return SessionState()

    # -- apply ----------------------------------------------------------

    def apply(self, meta, cmd, state: SessionState):
        if not isinstance(cmd, tuple) or not cmd:
            return state, None
        op = cmd[0]
        if op == "session_open":
            return self._open(meta, cmd, state)
        if op == "session_renew":
            return self._renew(meta, cmd, state)
        if op == "session_close":
            return self._close(meta, cmd, state)
        if op == "lock_acquire":
            return self._acquire(meta, cmd, state)
        if op == "lock_release":
            return self._release(meta, cmd, state)
        if op == "down":
            _, sid, _info = cmd
            if sid in state.sessions:
                st = state.clone()
                effects = self._expire(meta, st, sid, "down")
                return st, ("ok", None), effects
            return state, ("ok", None)
        if op == "timeout":
            name = cmd[1]
            if (isinstance(name, tuple) and len(name) == 3
                    and name[0] == "session"):
                _, sid, gen = name
                sess = state.sessions.get(sid)
                if sess is not None and sess.gen == gen:
                    st = state.clone()
                    effects = self._expire(meta, st, sid, "ttl")
                    return st, ("ok", None), effects
            # stale generation (renewed since armed) or unknown: no-op
            return state, ("ok", None)
        if op in ("nodeup", "nodedown", "machine_version"):
            return state, None
        return state, ("error", "unknown_op")

    # -- session lifecycle ----------------------------------------------

    def _open(self, meta, cmd, state: SessionState):
        _, sid, ttl_ms = cmd
        st = state.clone()
        effects = []
        sess = st.sessions.get(sid)
        if sess is None:
            st.sessions[sid] = sess = Session(int(ttl_ms), 1)
            effects.append(Monitor("process", sid, "machine"))
            self._c("session_opens")
        else:
            # reopening an open session is a renewal with a new TTL
            sess.ttl_ms = int(ttl_ms)
            sess.gen += 1
            self._c("session_renews")
        effects.append(Timer(("session", sid, sess.gen), sess.ttl_ms))
        return st, ("ok", sess.gen), effects

    def _renew(self, meta, cmd, state: SessionState):
        _, sid = cmd
        sess = state.sessions.get(sid)
        if sess is None:
            return state, ("error", "unknown_session")
        st = state.clone()
        sess = st.sessions[sid]
        sess.gen += 1
        self._c("session_renews")
        return st, ("ok", sess.gen), [
            Timer(("session", sid, sess.gen), sess.ttl_ms)
        ]

    def _close(self, meta, cmd, state: SessionState):
        _, sid = cmd
        if sid not in state.sessions:
            return state, ("error", "unknown_session")
        st = state.clone()
        sess = st.sessions.pop(sid)
        effects = [
            # cancel the armed lease timer and stop watching the owner
            Timer(("session", sid, sess.gen), None),
            Demonitor("process", sid, "machine"),
        ]
        self._drop_holder(st, sid, effects)
        self._c("session_closes")
        self._maybe_release_cursor(meta, st, effects)
        return st, ("ok", None), effects

    def _expire(self, meta, st: SessionState, sid, cause: str) -> list:
        """Shared by TTL lapse and monitor DOWN — the ONLY two paths
        that may remove a session without its own close command."""
        sess = st.sessions.pop(sid)
        effects = [
            Timer(("session", sid, sess.gen), None),
            Demonitor("process", sid, "machine"),
            SendMsg(sid, ("session_expired", sid, sess.gen, cause),
                    ("ra_event",)),
        ]
        self._drop_holder(st, sid, effects)
        self._c("session_expiries_ttl" if cause == "ttl"
                else "session_expiries_down")
        self._maybe_release_cursor(meta, st, effects)
        return effects

    # -- locks -----------------------------------------------------------

    def _acquire(self, meta, cmd, state: SessionState):
        _, sid, key = cmd[:3]
        steal = len(cmd) > 3 and cmd[3] == "steal"
        if sid not in state.sessions:
            return state, ("error", "unknown_session")
        st = state.clone()
        effects = []
        held = st.locks.get(key)
        if held is None:
            token = self._grant(st, key, sid)
            self._c("session_lock_acquires")
            return st, ("ok", "acquired", token), effects
        owner, token = held
        if owner == sid:
            return st, ("ok", "held", token), effects
        if steal:
            new_token = self._grant(st, key, sid)
            # the deposed holder learns its token is fenced out
            effects.append(SendMsg(owner, ("lock_lost", key, token),
                                   ("ra_event",)))
            q = st.waiters.get(key)
            if q is not None and sid in q:
                q.remove(sid)
                if not q:
                    st.waiters.pop(key)
            self._c("session_lock_steals")
            return st, ("ok", "stolen", new_token), effects
        q = st.waiters.setdefault(key, deque())
        if sid not in q:
            q.append(sid)
        self._c("session_lock_waits")
        return st, ("ok", "queued", None), effects

    def _release(self, meta, cmd, state: SessionState):
        _, sid, key = cmd
        held = state.locks.get(key)
        if held is None or held[0] != sid:
            return state, ("error", "not_holder")
        st = state.clone()
        effects = []
        del st.locks[key]
        self._handoff(st, key, effects)
        self._c("session_lock_releases")
        self._maybe_release_cursor(meta, st, effects)
        return st, ("ok", None), effects

    def _grant(self, st: SessionState, key, sid) -> int:
        st.next_token += 1
        st.locks[key] = (sid, st.next_token)
        return st.next_token

    def _drop_holder(self, st: SessionState, sid, effects) -> None:
        """Remove a departing session from every lock and wait queue,
        handing each released key to its next live waiter."""
        for key in st.held_by(sid):
            del st.locks[key]
            self._handoff(st, key, effects)
        for key in list(st.waiters):
            q = st.waiters[key]
            if sid in q:
                q.remove(sid)
            if not q:
                st.waiters.pop(key)

    def _handoff(self, st: SessionState, key, effects) -> None:
        q = st.waiters.get(key)
        while q:
            nxt = q.popleft()
            if nxt in st.sessions:
                token = self._grant(st, key, nxt)
                effects.append(SendMsg(nxt, ("lock_granted", key, token),
                                       ("ra_event",)))
                self._c("session_lock_handoffs")
                break
        if q is not None and not q:
            st.waiters.pop(key, None)

    def _maybe_release_cursor(self, meta, st: SessionState, effects) -> None:
        # everything settled: nothing in the log before here is needed
        # to rebuild the (empty) state
        if not st.sessions and not st.locks and not st.waiters:
            effects.append(ReleaseCursor(meta["index"], st))

    # -- runtime hooks ----------------------------------------------------

    def state_enter(self, role: str, state: SessionState):
        """A fresh leader re-arms every open lease's timer and re-issues
        the monitors: machine timers and monitors are leader-local
        runtime state, lost on failover (reference: ra_machine
        state_enter effects)."""
        if role != "leader":
            return []
        effects = []
        for sid, sess in state.sessions.items():
            effects.append(Monitor("process", sid, "machine"))
            effects.append(Timer(("session", sid, sess.gen), sess.ttl_ms))
        return effects

    def overview(self, state: SessionState):
        return {
            "type": "session",
            "sessions": len(state.sessions),
            "locks": len(state.locks),
            "waiters": sum(len(q) for q in state.waiters.values()),
            "next_token": state.next_token,
        }
