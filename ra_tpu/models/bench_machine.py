"""Benchmark machine + driver.

Capability parity with the reference's ``ra_bench`` (``src/ra_bench.erl``):
a no-op apply machine that emits a release_cursor every
``RELEASE_EVERY`` entries (:48-55), plus a pipelining driver that keeps
``pipe_size`` commands in flight per client and reports ops/sec.
"""

from __future__ import annotations

import threading
import time
from typing import Any, List, Tuple

from ra_tpu.effects import ReleaseCursor
from ra_tpu.machine import Machine

RELEASE_EVERY = 100_000


class BenchMachine(Machine):
    """No-op apply; periodic release cursor (state is an entry counter)."""

    def init(self, config) -> int:
        return 0

    def apply(self, meta, cmd, state: int):
        state += 1
        if meta["index"] % RELEASE_EVERY == 0:
            return state, state, [ReleaseCursor(meta["index"], state)]
        return state, state

    def apply_many(self, meta, cmds, state):
        """O(1) batch apply for plain command runs (the pipeline hot
        path): the machine only counts entries, so a run of n commands
        is state+n — unless the run crosses a release-cursor boundary,
        where we fall back to per-entry apply so the effect still
        fires (reference: no-op apply, src/ra_bench.erl:48-55)."""
        n = len(cmds)
        hi = meta["index"]
        lo = hi - n + 1
        if (lo - 1) // RELEASE_EVERY != hi // RELEASE_EVERY:
            return None  # boundary inside the batch: per-entry path
        return state + n

    def overview(self, state):
        return {"type": "bench", "applied": state}


def run_driver(
    api_mod,
    member,
    who: str,
    node_name: str,
    target_ops: int = 10_000,
    degree: int = 5,
    pipe_size: int = 500,
    payload: bytes = b"x" * 256,
) -> Tuple[float, int]:
    """Pipelined load driver (reference defaults: DEGREE=5 concurrent
    clients, PIPE_SIZE=500 in flight, 256-byte payloads,
    src/ra_bench.erl:18-40). Returns (ops_per_sec, completed)."""
    done = threading.Event()
    completed = [0]
    lock = threading.Lock()
    # each client sends total // degree; round so completion is reachable
    total = (target_ops // degree) * degree

    def sink(_from, corrs):
        with lock:
            completed[0] += len(corrs)
            if completed[0] >= total:
                done.set()

    api_mod.register_client(node_name, who, sink)
    t0 = time.perf_counter()
    sent = [0]

    def client(k: int):
        budget = total // degree
        for i in range(budget):
            while True:
                with lock:
                    inflight = sent[0] - completed[0]
                if inflight < pipe_size:
                    break
                time.sleep(0.0005)
            api_mod.pipeline_command(member, payload, (k, i), who)
            with lock:
                sent[0] += 1

    threads = [threading.Thread(target=client, args=(k,), daemon=True) for k in range(degree)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    done.wait(timeout=120)
    dt = time.perf_counter() - t0
    return completed[0] / dt, completed[0]
