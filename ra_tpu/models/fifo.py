"""FIFO queue machine — the quorum-queue-precursor workload.

Capability model: the reference's ``test/ra_fifo.erl`` (a full FIFO queue
machine used by its nemesis/partition suites): checkout-based consumers,
per-consumer in-flight settlement, monitor-driven consumer cleanup,
release-cursor emission once everything settled.

Commands:
  ("enqueue", msg)
  ("checkout", consumer_id[, prefetch])  -- register a consumer
  ("dequeue", consumer_id)           -- one-shot take (auto-settled)
  ("settle", consumer_id, msg_id)
  ("return", consumer_id, msg_id)    -- redeliver
  ("cancel", consumer_id)
  ("purge",)                         -- drop all ready messages
  ("down", consumer_id, info)        -- builtin monitor DOWN
"""

from __future__ import annotations

import dataclasses
from collections import OrderedDict, deque
from typing import Any, Dict, Optional, Tuple

from ra_tpu.effects import Monitor, ReleaseCursor, SendMsg
from ra_tpu.machine import Machine


@dataclasses.dataclass
class FifoState:
    queue: deque = dataclasses.field(default_factory=deque)  # (msg_id, msg)
    next_msg_id: int = 1
    # consumer_id -> {msg_id: msg} in-flight
    consumers: "OrderedDict[Any, Dict[int, Any]]" = dataclasses.field(
        default_factory=OrderedDict
    )
    # consumer_id -> prefetch credit (max in-flight)
    prefetch: Dict[Any, int] = dataclasses.field(default_factory=dict)
    service_queue: deque = dataclasses.field(default_factory=deque)  # ready consumers
    low_settled_index: int = 0

    def clone(self) -> "FifoState":
        st = FifoState(
            queue=deque(self.queue),
            next_msg_id=self.next_msg_id,
            consumers=OrderedDict((k, dict(v)) for k, v in self.consumers.items()),
            prefetch=dict(self.prefetch),
            service_queue=deque(self.service_queue),
            low_settled_index=self.low_settled_index,
        )
        return st


# Test-only failpoint: re-introduces the reversed-requeue bug (a
# multi-message consumer down redelivers highest msg_id first) that the
# comment in the down/cancel branch below guards against. Exists solely
# so the simulation plane can demonstrate end-to-end that its schedule
# explorer finds the violation and the shrinker minimizes the repro
# (tests/test_sim.py, docs/INTERNALS.md §19). Never set outside tests.
SIM_BUG_REVERSED_REQUEUE = False


class FifoMachine(Machine):
    def init(self, config) -> FifoState:
        return FifoState()

    def apply(self, meta, cmd, state: FifoState):
        if not isinstance(cmd, tuple) or not cmd:
            return state, None
        st = state.clone()
        op = cmd[0]
        effects = []
        if op == "enqueue":
            msg_id = st.next_msg_id
            st.next_msg_id += 1
            st.queue.append((msg_id, cmd[1]))
            self._service(st, effects)
            return st, ("ok", msg_id), effects
        if op == "checkout":
            cid = cmd[1]
            credit = cmd[2] if len(cmd) > 2 else 1
            if cid not in st.consumers:
                st.consumers[cid] = {}
                effects.append(Monitor("process", cid, "machine"))
            st.prefetch[cid] = max(int(credit), 1)
            if cid not in st.service_queue:
                st.service_queue.append(cid)
            self._service(st, effects)
            return st, ("ok", None), effects
        if op == "dequeue":
            # one-shot take with auto-settlement (the reference's
            # dequeue/settled checkout mode)
            if not st.queue:
                return st, ("ok", None), effects
            msg_id, msg = st.queue.popleft()
            if not st.queue and all(not f for f in st.consumers.values()):
                effects.append(ReleaseCursor(meta["index"], st))
            return st, ("ok", (msg_id, msg)), effects
        if op == "purge":
            n = len(st.queue)
            st.queue.clear()
            if all(not f for f in st.consumers.values()):
                effects.append(ReleaseCursor(meta["index"], st))
            return st, ("ok", n), effects
        if op == "settle":
            _, cid, msg_id = cmd
            inflight = st.consumers.get(cid, {})
            inflight.pop(msg_id, None)
            if cid in st.consumers and cid not in st.service_queue:
                st.service_queue.append(cid)
            self._service(st, effects)
            if not st.queue and all(not f for f in st.consumers.values()):
                effects.append(ReleaseCursor(meta["index"], st))
            return st, ("ok", None), effects
        if op == "return":
            _, cid, msg_id = cmd
            inflight = st.consumers.get(cid, {})
            msg = inflight.pop(msg_id, None)
            if msg is not None:
                st.queue.appendleft((msg_id, msg))
            # the returning consumer is ready again (else the returned
            # message sits undelivered until an unrelated op services it)
            if cid in st.consumers and cid not in st.service_queue:
                st.service_queue.append(cid)
            self._service(st, effects)
            return st, ("ok", None), effects
        if op in ("cancel", "down"):
            cid = cmd[1]
            st.prefetch.pop(cid, None)
            inflight = st.consumers.pop(cid, None)
            if cid in st.service_queue:
                st.service_queue.remove(cid)
            if inflight:
                # requeue at the FRONT in original order: appendleft
                # reverses, so walk the ids highest-first — the lowest
                # msg_id must end up at the head or a multi-message down
                # (prefetch > 1) redelivers out of FIFO order
                for msg_id, msg in sorted(
                    inflight.items(), reverse=not SIM_BUG_REVERSED_REQUEUE
                ):
                    st.queue.appendleft((msg_id, msg))
                self._service(st, effects)
            return st, ("ok", None), effects
        return state, ("error", "unknown_op")

    def _service(self, st: FifoState, effects) -> None:
        """Deliver queued messages to ready consumers, up to each
        consumer's prefetch credit (reference: checkout credit)."""
        while st.queue and st.service_queue:
            cid = st.service_queue[0]
            inflight = st.consumers.get(cid)
            if inflight is None:
                st.service_queue.popleft()
                continue
            credit = st.prefetch.get(cid, 1)
            if len(inflight) >= credit:
                st.service_queue.popleft()
                continue  # at capacity
            # fill up to credit while messages remain
            while st.queue and len(inflight) < credit:
                msg_id, msg = st.queue.popleft()
                inflight[msg_id] = msg
                effects.append(
                    SendMsg(cid, ("delivery", msg_id, msg), ("ra_event",))
                )
            if len(inflight) >= credit:
                # only at capacity does the consumer leave the ready
                # queue; with spare credit it must keep receiving later
                # enqueues (the outer loop's queue check terminates)
                st.service_queue.popleft()
            else:
                break  # queue drained; consumer stays ready

    def overview(self, state: FifoState):
        return {
            "type": "fifo",
            "ready": len(state.queue),
            "consumers": len(state.consumers),
            "in_flight": sum(len(f) for f in state.consumers.values()),
            "prefetch": dict(state.prefetch),
        }
