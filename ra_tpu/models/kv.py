"""Built-in KV machine demonstrating log-as-value-store.

Capability parity with the reference's ``ra_kv`` (``src/ra_kv.erl:44-103``):
the machine state holds only ``key -> (raft_index, digest)`` — values are
NOT kept in machine state; they live in the log and are fetched on demand
through the log read path. Old values become dead log entries; the
current ones are advertised via ``live_indexes`` so compaction retains
exactly the live set.

Commands: ("put", key, value) | ("delete", key). Reads go through
``get``/aux (log fetch), not apply.
"""

from __future__ import annotations

import hashlib
import pickle
from typing import Any, Dict, Optional, Tuple

from ra_tpu.effects import ReleaseCursor
from ra_tpu.machine import Machine


def _digest(value: Any) -> bytes:
    return hashlib.blake2b(pickle.dumps(value), digest_size=8).digest()


class KvMachine(Machine):
    """State: {key: (raft_index, digest)}. Values read from the log."""

    def __init__(self, snapshot_interval: int = 256):
        self.snapshot_interval = snapshot_interval

    def init(self, config) -> Dict[str, Tuple[int, bytes]]:
        return {}

    def apply(self, meta, cmd, state):
        if not isinstance(cmd, tuple) or not cmd:
            return state, None
        op = cmd[0]
        if op == "put":
            _, key, value = cmd
            state = dict(state)
            state[key] = (meta["index"], _digest(value))
            reply = ("ok", meta["index"])
        elif op == "delete":
            _, key = cmd
            state = dict(state)
            old = state.pop(key, None)
            reply = ("ok", old[0] if old else None)
        elif op == "keys":
            return state, sorted(state.keys())
        else:
            return state, ("error", "unknown_op")
        effects = []
        if meta["index"] % self.snapshot_interval == 0:
            # state is tiny (indexes only): snapshot aggressively; live
            # indexes keep the current values in the log
            effects.append(ReleaseCursor(meta["index"], state))
        return state, reply, effects

    def live_indexes(self, state):
        return sorted(idx for idx, _ in state.values())

    def overview(self, state):
        return {"type": "kv", "keys": len(state)}


def kv_get(api_mod, member, key, timeout: float = 5.0) -> Optional[Any]:
    """Read a value: consistent-query the index map, then fetch the
    value from the log (the reference reads via aux/read plans; here the
    state query returns the index and the log read follows). Retries the
    state query when the fetch misses — a concurrent overwrite + snapshot
    may compact the index read in the first round trip."""
    for _attempt in range(3):
        out = api_mod.consistent_query(member, lambda st: st.get(key), timeout=timeout)
        if out[0] != "ok" or out[1] is None:
            return None
        idx, digest = out[1]
        entry = _fetch_log_entry(api_mod, member, idx, timeout)
        if entry is None:
            continue  # compacted under us: re-resolve the current index
        value = entry.cmd.data[2]
        if _digest(value) != digest:
            raise IOError(f"kv digest mismatch for {key!r} at idx {idx}")
        return value
    return None


def _fetch_log_entry(api_mod, member, idx, timeout):
    fut = api_mod.Future()
    if not api_mod._try_send(member, ("state_query", lambda s: s.log.fetch(idx), fut)):
        return None
    out = fut.result(timeout)
    return out[1]
