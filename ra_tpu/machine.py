"""The user state-machine behaviour.

Capability parity with the reference's ``ra_machine`` behaviour
(reference: ``src/ra_machine.erl:232-311``): mandatory ``init``/``apply``;
optional ``state_enter``, ``tick``, ``snapshot_installed``, ``overview``,
``live_indexes``, ``version``/``which_module`` (machine versioning),
aux handlers. ``apply`` receives a meta dict with at least ``index`` and
``term`` plus ``system_time`` / ``machine_version`` / ``reply_mode`` when
relevant, and returns ``(new_state, reply)`` or
``(new_state, reply, effects)``.

Builtin commands are delivered to ``apply`` as tuples:
``("down", target, info)``, ``("nodeup", node)``, ``("nodedown", node)``,
``("machine_version", from_v, to_v)``, ``("timeout", name)`` (reference:
src/ra_machine.erl:108-111).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ra_tpu.effects import Effect


class Machine:
    """Base class for user machines. Subclass and override."""

    # -- mandatory ---------------------------------------------------------

    def init(self, config: Dict[str, Any]) -> Any:
        raise NotImplementedError

    def apply(self, meta: Dict[str, Any], cmd: Any, state: Any):
        """Return (state, reply) or (state, reply, effects)."""
        raise NotImplementedError

    # -- optional ----------------------------------------------------------

    # Batched apply: an ra_tpu extension beyond the reference behaviour
    # (the per-entry ``apply`` contract is unchanged; this is the
    # vectorization hook the batch backend uses when replies and effects
    # are not needed for a run of entries). Return the final state after
    # applying ``cmds`` (a list of command payloads at consecutive
    # indexes) or None to fall back to per-entry ``apply``.
    def apply_many(
        self, meta: Dict[str, Any], cmds: List[Any], state: Any
    ) -> Optional[Any]:
        return None

    def state_enter(self, role: str, state: Any) -> List[Effect]:
        return []

    def tick(self, time_ms: int, state: Any) -> List[Effect]:
        return []

    def snapshot_installed(self, meta, state, old_meta, old_state) -> List[Effect]:
        return []

    def overview(self, state: Any) -> Dict[str, Any]:
        return {"type": type(self).__name__}

    def live_indexes(self, state: Any) -> Sequence[int]:
        return ()

    def version(self) -> int:
        return 0

    def which_module(self, version: int) -> "Machine":
        """Return the machine implementation for a given version."""
        return self

    def snapshot_module(self):
        return None  # default snapshot codec

    # -- aux machine -------------------------------------------------------

    def init_aux(self, name: str) -> Any:
        return None

    def handle_aux(self, role: str, kind: str, cmd: Any, aux_state: Any, intern):
        """kind: "cast" | "call"; intern exposes server internals
        (ra_tpu.aux.AuxContext). Return (reply, aux_state) or
        (reply, aux_state, effects)."""
        return None, aux_state


# -- machine factories -------------------------------------------------------
# Cold restart needs to reconstruct machines from persisted config alone
# (the reference stores the machine module atom in the server config and
# Erlang modules are globally addressable — src/ra_server_sup_sup.erl
# recover_config/2). The Python analog: a registered factory name or a
# "module:attr" dotted path, persisted in __server_config__ and resolved
# at boot.

_FACTORIES: Dict[str, Callable[[Dict[str, Any]], "Machine"]] = {}


def register_machine_factory(name: str, fn: Callable[[Dict[str, Any]], "Machine"]) -> None:
    _FACTORIES[name] = fn


def resolve_machine_factory(spec: str, machine_config: Optional[Dict[str, Any]] = None) -> "Machine":
    """Build a machine from a persisted factory spec: a name registered
    via ``register_machine_factory`` or an importable ``module:attr``
    callable taking the machine_config dict."""
    cfg = machine_config or {}
    fn = _FACTORIES.get(spec)
    if fn is None and ":" in spec:
        import importlib

        mod, attr = spec.split(":", 1)
        fn = getattr(importlib.import_module(mod), attr)
    if fn is None:
        raise KeyError(f"unknown machine factory {spec!r}")
    return fn(cfg)


def normalize_aux_result(res, aux_state) -> Tuple[Any, Any, List[Effect]]:
    """handle_aux contract: None | (reply, aux_state) |
    (reply, aux_state, effects) -> (reply, aux_state, effects). One
    definition shared by both execution backends."""
    if res is None:
        return None, aux_state, []
    if len(res) == 2:
        return res[0], res[1], []
    return res[0], res[1], list(res[2])


def normalize_apply_result(res) -> Tuple[Any, Any, List[Effect]]:
    if isinstance(res, tuple):
        if len(res) == 2:
            return res[0], res[1], []
        if len(res) == 3:
            return res[0], res[1], list(res[2])
    raise TypeError(f"machine apply must return a 2- or 3-tuple, got {res!r}")


class SimpleMachine(Machine):
    """Wraps a 2-arity fn as a machine (cf. ra_machine_simple,
    reference: src/ra_machine_simple.erl:12-24): state' = fn(cmd, state),
    reply is the new state."""

    def __init__(self, fn: Callable[[Any, Any], Any], initial_state: Any):
        self.fn = fn
        self.initial_state = initial_state

    def init(self, config):
        return self.initial_state

    def apply(self, meta, cmd, state):
        if isinstance(cmd, tuple) and cmd and cmd[0] in (
            "down",
            "nodeup",
            "nodedown",
            "machine_version",
            "timeout",
        ):
            return state, None  # simple machines ignore builtins
        new_state = self.fn(cmd, state)
        return new_state, new_state

    def apply_many(self, meta, cmds, state):
        fn = self.fn
        for cmd in cmds:
            if not (isinstance(cmd, tuple) and cmd and cmd[0] in (
                "down", "nodeup", "nodedown", "machine_version", "timeout",
            )):
                state = fn(cmd, state)
        return state

    def overview(self, state):
        return {"type": "simple", "state": state}


class VersionedMachine(Machine):
    """Helper for rolling machine upgrades: a registry of version ->
    machine module (reference capability: machine versioning,
    docs/internals/STATE_MACHINE_TUTORIAL.md:400-477)."""

    def __init__(self, versions: Dict[int, Machine]):
        if not versions:
            raise ValueError("need at least one version")
        self.versions = dict(versions)
        self._latest = max(versions)

    def version(self) -> int:
        return self._latest

    def which_module(self, version: int) -> Machine:
        eligible = [v for v in self.versions if v <= version]
        if not eligible:
            raise KeyError(f"no machine module for version {version}")
        return self.versions[max(eligible)]

    def init(self, config):
        return self.which_module(self._latest).init(config)

    def apply(self, meta, cmd, state):
        mv = meta.get("machine_version", self._latest)
        return self.which_module(mv).apply(meta, cmd, state)
