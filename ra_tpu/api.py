"""Public client/ops API.

The framework's counterpart of the reference's ``ra`` module
(reference: ``src/ra.erl`` — start_cluster/start_server/restart/delete,
process_command/pipeline_command, local/leader/consistent queries,
membership management, leadership transfer, overview/metrics). Operates
on in-proc nodes registered in ``ra_tpu.runtime.transport.registry()``;
server ids are ``(name, node_name)`` tuples.
"""

from __future__ import annotations

import threading
import time
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

from ra_tpu import leaderboard
from ra_tpu.machine import Machine
from ra_tpu.protocol import Command, ElectionTimeout, RA_JOIN, RA_LEAVE, ServerId, USR
from ra_tpu.runtime.node import RaNode
from ra_tpu.runtime.transport import registry as node_registry
from ra_tpu.system import SystemConfig
from ra_tpu.utils.lib import partition_parallel


class Future:
    __slots__ = ("_evt", "value")

    def __init__(self) -> None:
        self._evt = threading.Event()
        self.value: Any = None

    def set_result(self, v: Any) -> None:
        self.value = v
        self._evt.set()

    def result(self, timeout: Optional[float] = None) -> Any:
        if not self._evt.wait(timeout):
            raise TimeoutError("ra_tpu call timed out")
        return self.value

    def done(self) -> bool:
        return self._evt.is_set()


class RaError(Exception):
    pass


class StaleReadError(RaError):
    """A bounded local read (``local_query`` with ``max_staleness_s``)
    could not be served within the requested staleness bound
    (docs/INTERNALS.md §20). ``staleness`` is the replica's provable
    upper bound (``inf`` until it has applied a leader freshness
    stamp); ``leader_hint`` names where a linearizable retry can go."""

    def __init__(self, staleness: float, leader_hint):
        super().__init__(
            f"local read exceeds staleness bound: {staleness:.3f}s "
            f"(leader hint: {leader_hint})"
        )
        self.staleness = staleness
        self.leader_hint = leader_hint


class RaNoSpace(RaError):
    """Typed ``RA_NOSPACE`` backoff error (docs/INTERNALS.md §21): the
    target node is storage-degraded (space-class WAL failure or hard
    disk watermark) and kept rejecting the command for the caller's
    whole deadline. The command was provably never appended — the node
    classifies ENOSPC/EDQUOT before any log mutation — so retrying
    later is exactly-once safe. ``code`` is the stable machine-readable
    tag (always ``"RA_NOSPACE"``)."""

    code = "RA_NOSPACE"

    def __init__(self, target):
        super().__init__(
            f"RA_NOSPACE: {target} is storage-degraded (no disk space); "
            f"command was not appended — back off and retry"
        )
        self.target = target


def _node(node_name: str) -> RaNode:
    node = node_registry().get(node_name)
    if node is None:
        raise RaError(f"node {node_name!r} not running")
    return node


# ---------------------------------------------------------------------------
# system / cluster lifecycle


def start_node(name: str, config: Optional[SystemConfig] = None, **kw) -> RaNode:
    return RaNode(name, config=config, **kw)


def stop_node(name: str) -> None:
    node = node_registry().get(name)
    if node is not None:
        node.stop()


def _mgmt_route(node_name: str):
    """A callable mgmt transport for a node: local nodes are called
    directly; remote nodes are reached over any local TCP transport
    (reference: rpc:call management, src/ra_server_sup_sup.erl:33-50)."""
    node = node_registry().get(node_name)
    if node is not None:
        return node
    for local in node_registry().names():
        n = node_registry().get(local)
        t = getattr(n, "transport", None)
        if t is not None and hasattr(t, "mgmt_call"):
            return _RemoteNode(t, node_name)
    raise RaError(f"no route to node {node_name!r} (no local TCP transport)")


class _RemoteNode:
    """Duck-typed remote management handle over TcpTransport.mgmt_call."""

    def __init__(self, transport, node_name: str):
        self._t = transport
        self._node = node_name

    def start_server(self, name, cluster_name, machine, members,
                     machine_config=None, machine_factory=None, **_kw):
        if machine is not None and machine_factory is None:
            raise RaError(
                "remote start_server requires machine_factory (machine "
                "objects do not travel across nodes)"
            )
        return tuple(self._t.mgmt_call(self._node, "start_server", {
            "name": name, "cluster_name": cluster_name, "members": members,
            "machine_config": machine_config, "machine_factory": machine_factory,
        }))

    def restart_server(self, name, overrides=None, **_kw):
        return tuple(self._t.mgmt_call(
            self._node, "restart_server", {"name": name, "overrides": overrides}
        ))

    def stop_server(self, name, **_kw):
        return self._t.mgmt_call(self._node, "stop_server", {"name": name})

    def delete_server(self, name, **_kw):
        return self._t.mgmt_call(self._node, "delete_server", {"name": name})

    def trigger_election(self, name):
        return self._t.mgmt_call(self._node, "trigger_election", {"name": name})

    def overview(self):
        return self._t.mgmt_call(self._node, "overview", {})


def start_server(
    server_id: ServerId,
    cluster_name: str,
    machine: Optional[Machine],
    members: Sequence[ServerId],
    machine_config: Optional[dict] = None,
    machine_factory: Optional[str] = None,
    extra_cfg: Optional[dict] = None,
) -> ServerId:
    """``extra_cfg`` carries optional ServerConfig knobs (e.g.
    ``{"lease": True}``, docs/INTERNALS.md §20); it is persisted with
    the server config so restarts keep the same behavior. Local nodes
    only — remote management calls ignore it."""
    name, node_name = server_id
    return _mgmt_route(node_name).start_server(
        name, cluster_name, machine, tuple(members),
        machine_config=machine_config, machine_factory=machine_factory,
        _extra_cfg=extra_cfg,
    )


def start_cluster(
    cluster_name: str,
    machine_factory: Callable[[], Machine],
    server_ids: Sequence[ServerId],
    timeout: float = 5.0,
    extra_cfg: Optional[dict] = None,
) -> Tuple[List[ServerId], List[ServerId]]:
    """Start all members (in parallel, like the reference's
    partition_parallel cluster start), elect a leader, return
    (started, failed)."""
    ids = list(server_ids)
    oks, errs = partition_parallel(
        lambda sid: start_server(sid, cluster_name, machine_factory(), ids,
                                 extra_cfg=extra_cfg),
        ids,
        timeout_s=timeout,
    )
    started = [sid for sid, _ in oks]
    if started:
        trigger_election(started[0])
        wait_for_leader(cluster_name, timeout=timeout)
    return started, [sid for sid, _ in errs]


def delete_cluster(server_ids: Sequence[ServerId]) -> None:
    ids = [tuple(sid) for sid in server_ids]
    # resolve the cluster name BEFORE deleting (the directory entries
    # die with the servers): the leaderboard entry must go too, or
    # system_overview/cluster_health join against a ghost cluster and
    # clients keep getting routed at deleted members. Local deletes
    # prune per member (node.delete_server -> leaderboard.forget_member);
    # the sweep below covers members deleted on REMOTE nodes, whose
    # forget_member ran against the remote process's table, not ours.
    cluster = next(
        (c for c in (_cluster_of(sid) for sid in ids) if c), None
    )
    for name, node_name in ids:
        try:
            _mgmt_route(node_name).delete_server(name)
        except (RaError, RuntimeError, TimeoutError, OSError):
            pass  # node gone entirely (or unreachable over mgmt)
    if cluster is not None:
        got = leaderboard.snapshot().get(cluster)
        if got is not None and set(got[1]) <= set(ids):
            # every remaining recorded member was deleted: drop the
            # entry (a PARTIAL delete keeps it, minus the dead members)
            leaderboard.clear(cluster)


def restart_server(server_id: ServerId, overrides: Optional[dict] = None) -> ServerId:
    name, node_name = server_id
    return _mgmt_route(node_name).restart_server(name, overrides=overrides)


def stop_server(server_id: ServerId) -> None:
    name, node_name = server_id
    _mgmt_route(node_name).stop_server(name)


def trigger_election(server_id: ServerId) -> None:
    name, node_name = server_id
    target = _mgmt_route(node_name)
    if isinstance(target, _RemoteNode):
        target.trigger_election(name)
        return
    proc = target.procs.get(name)
    if proc is None:
        raise RaError(f"server {server_id} not running")
    proc.enqueue(ElectionTimeout())


def wait_for_leader(cluster_name: str, timeout: float = 5.0) -> ServerId:
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        leader = leaderboard.lookup_leader(cluster_name)
        if leader is not None and _is_running(leader):
            return leader
        time.sleep(0.01)
    raise RaError(f"no leader for {cluster_name!r} within {timeout}s")


def _is_running(sid: ServerId) -> bool:
    node = node_registry().get(sid[1])
    return node is not None and sid[0] in node.procs


# ---------------------------------------------------------------------------
# commands


def process_command(
    server_id: ServerId,
    data: Any,
    timeout: float = 5.0,
    retry_on_timeout: bool = False,
) -> Tuple[Any, ServerId]:
    """Synchronous command: replicated, applied, machine reply returned.
    Follows redirects to the current leader (reference: leader_call
    redirect loop src/ra_server_proc.erl:278-299).

    A timeout after the command reached a (possibly stale) leader is
    surfaced as RaError by default — the command MAY still commit later.
    ``retry_on_timeout=True`` rotates to other members instead, giving
    at-least-once semantics (duplicates possible; dedup via machine-level
    correlations, as in the reference).

    A deposed leader answers its pending commands immediately instead of
    leaving clients to hang out their timeout: ``("maybe", hint)`` when
    the entry survives in its log (it MAY still commit — surfaced as
    RaError unless ``retry_on_timeout``, exactly like the timeout case,
    but bounded and instant), or ``("redirect", hint)`` when the entry
    was truncated away (provably dead, retried here exactly-once
    safely).

    An overloaded leader replies ``("reject", "overloaded")`` (admission
    window full — see docs/INTERNALS.md §12): the command was NOT
    appended, so the retry below is exactly-once safe. Rejects (both
    backends) carry a gate waiter as a third element — a
    threading.Event the server SETS when the window releases (apply
    progress frees admission room, or an ingress-ring drain frees lane
    space) — so the retry is woken by the release itself instead of a
    fixed sleep poll;
    the bounded backoff stays only as the upper wait bound (deadline
    semantics are unchanged, and a reject never appended anything, so
    the retry remains exactly-once)."""
    deadline = time.monotonic() + timeout
    target = server_id
    tried: set = set()
    backoff = 0.01
    last_reject = None  # "overloaded" | "nospace" — types the timeout
    while time.monotonic() < deadline:
        fut = Future()
        cmd = Command(kind=USR, data=data, reply_mode="await_consensus",
                      from_ref=fut, ts=time.monotonic_ns())
        if not _try_send(target, cmd):
            target = _next_target(server_id, target, tried)
            continue
        try:
            remaining = max(0.05, deadline - time.monotonic())
            # without retries the caller's full timeout applies to this
            # attempt; with retries each attempt is bounded so a stale/
            # partitioned leader cannot absorb the whole deadline
            attempt = min(1.0, remaining) if retry_on_timeout else remaining
            reply = fut.result(timeout=attempt)
        except TimeoutError:
            if not retry_on_timeout:
                raise RaError(
                    f"command timed out against {target} (it may still commit)"
                )
            tried.add(target)
            target = _next_target(server_id, target, tried)
            continue
        if reply[0] == "ok":
            return reply[1], reply[2]
        if reply[0] in ("redirect", "maybe"):
            # "maybe": leader deposed with the entry still in its log —
            # the command may yet commit. Same contract as a timeout
            # (error out unless the caller accepted at-least-once), but
            # detected and surfaced in milliseconds, not after the full
            # client timeout (the round-5 wedge shape). "redirect" is a
            # clean never-appended verdict: always safe to re-send.
            if reply[0] == "maybe" and not retry_on_timeout:
                raise RaError(
                    f"command outcome unknown against {target} (leader "
                    f"deposed; it may still commit)"
                )
            leader = reply[1]
            tried.add(target)
            target = leader if leader is not None and leader != target else _next_target(
                server_id, target, tried
            )
            continue
        if reply[0] == "reject":
            # reject-with-backoff: the leader's admission window is
            # full ("overloaded") or its storage is degraded
            # ("nospace", docs/INTERNALS.md §21). Hold off, then retry
            # the SAME leader — the command
            # was never appended, so no duplicate risk. tried is not
            # updated: this member is healthy. When the reject carries
            # a window-release gate (both backends do), park on IT —
            # the server wakes us the moment apply progress (or a ring
            # drain) frees room, so the backoff only bounds the wait;
            # a bare 2-tuple reject falls back to the bounded sleep.
            last_reject = reply[1]
            wait_s = min(backoff, max(0.0, deadline - time.monotonic()))
            gate = reply[2] if len(reply) > 2 else None
            if gate is not None:
                gate.wait(wait_s)
            else:
                time.sleep(wait_s)
            backoff = min(backoff * 2, 0.25)
            continue
        raise RaError(f"command failed: {reply!r}")
    if last_reject == "nospace":
        raise RaNoSpace(target)
    raise RaError("command timed out")


def _try_send(sid: ServerId, msg: Any) -> bool:
    node = node_registry().get(sid[1])
    if node is None:
        return False
    return node.deliver(sid, msg, None)


def _try_send_many(sid: ServerId, msgs: list) -> int:
    """Bulk client ingress: deliver ``msgs`` to one server in a single
    handoff when the backend supports it (the batch coordinator's
    ``deliver_many`` — ONE ingress-ring slot for the whole burst,
    docs/INTERNALS.md §16), else loop ``deliver``. Returns the number
    handed to the node (an upper bound on what arrives: bulk items may
    still shed at drain under the backend's overload policy)."""
    node = node_registry().get(sid[1])
    if node is None:
        return 0
    dm = getattr(node, "deliver_many", None)
    if dm is not None:
        dm([(sid, m, None) for m in msgs])
        return len(msgs)
    n = 0
    for m in msgs:
        if node.deliver(sid, m, None):
            n += 1
    return n


def _next_target(origin: ServerId, current: ServerId, tried: set) -> ServerId:
    cluster = leaderboard.lookup_members(_cluster_of(origin) or "")
    for sid in cluster:
        if sid not in tried and sid != current and _is_running(sid):
            return sid
    time.sleep(0.02)
    return origin


def _cluster_of(sid: ServerId) -> Optional[str]:
    node = node_registry().get(sid[1])
    if node is None:
        return None
    d = getattr(node, "directory", None)
    if d is None:
        # batch coordinators have no directory; groups carry their
        # cluster name directly
        g = getattr(node, "by_name", {}).get(sid[0])
        return getattr(g, "cluster_name", None)
    uid = d.uid_of(sid[0])
    return d.cluster_of(uid) if uid else None


class AdmissionWindow:
    """Client-side in-flight command window: bounds how many commands a
    client keeps outstanding against apply progress instead of queueing
    unbounded work into the cluster (the client half of the flow-control
    design in docs/INTERNALS.md §12; servers enforce their own
    ``max_command_backlog`` and reject past it).

    Usage::

        win = AdmissionWindow(64)
        if win.acquire(timeout=1.0):      # blocks while the window is full
            try:  ... issue the command ...
            finally: win.release()        # on ack/timeout/reject

    Counters (``("admission", name)`` in ra_tpu.counters): ``admitted``,
    ``throttled`` (acquire had to wait), ``in_flight`` gauge."""

    FIELDS = [
        ("admitted", "counter", "commands admitted through the window"),
        ("throttled", "counter", "acquisitions that had to wait"),
        ("in_flight", "gauge", "commands currently outstanding"),
    ]

    def __init__(self, limit: int, name: str = "client"):
        from ra_tpu import counters as _counters

        if limit <= 0:
            raise ValueError("admission window limit must be positive")
        self.limit = limit
        self._sem = threading.BoundedSemaphore(limit)
        self._n = 0
        self._n_lock = threading.Lock()
        self.counters = _counters.new(("admission", name), self.FIELDS)

    def acquire(self, timeout: Optional[float] = None) -> bool:
        if not self._sem.acquire(blocking=False):
            self.counters.incr("throttled")
            if not self._sem.acquire(timeout=timeout):
                return False
        with self._n_lock:
            self._n += 1
            self.counters.put("in_flight", self._n)
        self.counters.incr("admitted")
        return True

    def release(self) -> None:
        with self._n_lock:
            self._n -= 1
            self.counters.put("in_flight", self._n)
        self._sem.release()


def pipeline_command(
    server_id: ServerId, data: Any, correlation: Any, who: Any,
    priority: str = "normal",
) -> bool:
    """Async command: the applied notification arrives on the client sink
    registered as ``who`` (reference: ra:pipeline_command + {applied,
    Corrs} ra_events). ``priority="low"`` buffers the command behind
    normal traffic, drained in bounded slices.

    At-most-once: an overloaded leader may shed the command past its
    admission window (counted in ``commands_dropped_overload``) — the
    applied notification then never arrives, and the caller must
    resend by correlation, exactly as with a lost message (the
    reference gives pipeline_command the same non-guarantee)."""
    cmd = Command(kind=USR, data=data, reply_mode=("notify", correlation, who),
                  priority=priority, ts=time.monotonic_ns())
    return _try_send(server_id, cmd)


def register_client(node_name: str, who: Any, cb: Callable[[ServerId, list], None]) -> None:
    _node(node_name).register_client_sink(who, cb)


# ---------------------------------------------------------------------------
# queries


# leader-bound queries chase at most this many member-supplied
# redirect hints before falling back to the leaderboard; during churn
# two deposed members can point at each other indefinitely otherwise
MAX_REDIRECT_HOPS = 4


def local_query(server_id: ServerId, fn: Callable[[Any], Any], timeout: float = 5.0,
                max_staleness_s: Optional[float] = None):
    """Query any member's machine state directly (possibly stale).

    ``max_staleness_s`` bounds the staleness instead of accepting any:
    the member answers only when its leader-stamped freshness floor
    proves its applied state is at most that many (leader wall-clock)
    seconds old, and raises ``StaleReadError`` otherwise
    (docs/INTERNALS.md §20). Requires the cluster to run with leases
    enabled — lease-off leaders never stamp, so every bounded read
    then fails conservatively."""
    fut = Future()
    msg = (
        ("local_query", fn, fut) if max_staleness_s is None
        else ("local_query", fn, fut, max_staleness_s)
    )
    if not _try_send(server_id, msg):
        raise RaError(f"server {server_id} unreachable")
    out = fut.result(timeout)
    if out[0] == "stale":
        raise StaleReadError(out[1], out[2])
    return out


def leader_query(server_id: ServerId, fn: Callable[[Any], Any], timeout: float = 5.0):
    """Query the leader's (uncommitted-read) machine state."""
    deadline = time.monotonic() + timeout
    cluster = _cluster_of(server_id)
    target = leaderboard.lookup_leader(cluster or "") or server_id
    for hop in range(MAX_REDIRECT_HOPS + 1):
        fut = Future()
        if not _try_send(target, ("leader_query", fn, fut)):
            raise RaError(f"leader {target} unreachable")
        out = fut.result(max(0.05, deadline - time.monotonic()))
        if out[0] != "redirect":
            return out
        if out[1] is None:
            raise RaError("no leader")
        # hop 1 trusts the member's hint; after that the hints have
        # proven stale — re-consult the leaderboard before giving up
        if hop >= 1 and cluster:
            target = leaderboard.lookup_leader(cluster) or out[1]
        else:
            target = out[1]
    raise RaError(
        f"leader_query exceeded {MAX_REDIRECT_HOPS} redirect hops"
    )


def consistent_query(
    server_id: ServerId, fn: Callable[[Any], Any], timeout: float = 5.0
):
    """Linearizable read: served locally under a valid leader lease,
    otherwise the leader confirms leadership with a quorum heartbeat
    round before answering (reference: heartbeat query_index protocol;
    docs/INTERNALS.md §20)."""
    deadline = time.monotonic() + timeout
    cluster = _cluster_of(server_id)
    target = leaderboard.lookup_leader(cluster or "") or server_id
    hops = 0
    while time.monotonic() < deadline:
        fut = Future()
        if not _try_send(target, ("consistent_query", fn, fut)):
            time.sleep(0.02)
            target = leaderboard.lookup_leader(cluster or "") or server_id
            continue
        out = fut.result(max(0.05, deadline - time.monotonic()))
        if out[0] == "redirect":
            hops += 1
            if hops > MAX_REDIRECT_HOPS:
                # stale hints chasing each other during churn: pause a
                # beat, then restart routing from the leaderboard
                hops = 0
                time.sleep(0.02)
                target = (
                    leaderboard.lookup_leader(cluster or "") or server_id
                )
                continue
            target = out[1] or leaderboard.lookup_leader(cluster or "") \
                or target
            continue
        return out
    raise RaError("consistent_query timed out")


def members(server_id: ServerId, timeout: float = 5.0) -> Tuple[List[ServerId], ServerId]:
    def get_members(s):
        # Server exposes members() as a method; coordinator GroupHost as
        # a plain attribute
        m = s.members
        return list(m() if callable(m) else m)

    fut = Future()
    if not _try_send(server_id, ("state_query", get_members, fut)):
        raise RaError(f"server {server_id} unreachable")
    out = fut.result(timeout)
    return out[1], out[2]


def member_overview(server_id: ServerId, timeout: float = 5.0) -> dict:
    fut = Future()
    if not _try_send(server_id, ("state_query", lambda s: s.overview(), fut)):
        raise RaError(f"server {server_id} unreachable")
    return fut.result(timeout)[1]


def key_metrics(server_id: ServerId, timeout: float = 5.0) -> dict:
    def km(s):
        li, lt = s.log.last_index_term()
        return {
            "state": s.role,
            "leader": s.leader_id,
            "term": s.current_term,
            "commit_index": s.commit_index,
            "last_applied": s.last_applied,
            "last_index": li,
            "machine_version": s.effective_machine_version,
        }

    fut = Future()
    if not _try_send(server_id, ("state_query", km, fut)):
        raise RaError(f"server {server_id} unreachable")
    return fut.result(timeout)[1]


# ---------------------------------------------------------------------------
# membership / leadership


def _leader_control(server_id: ServerId, msg_builder, timeout: float = 5.0):
    deadline = time.monotonic() + timeout
    cluster = _cluster_of(server_id)
    target = leaderboard.lookup_leader(cluster or "") or server_id
    tried: set = set()
    while time.monotonic() < deadline:
        fut = Future()
        if not _try_send(target, msg_builder(fut)):
            tried.add(target)
            target = _next_target(server_id, target, tried)
            continue
        try:
            out = fut.result(max(0.05, deadline - time.monotonic()))
        except TimeoutError:
            break
        if isinstance(out, tuple) and out and out[0] in ("redirect", "maybe"):
            # membership commands are self-deduplicating (a re-sent
            # join/leave resolves to already_member/not_member), so a
            # "maybe" deposition verdict is safe to retry here
            tried.add(target)
            target = out[1] or _next_target(server_id, target, tried)
            continue
        if isinstance(out, tuple) and out and out[0] == "reject":
            time.sleep(min(0.05, max(0.0, deadline - time.monotonic())))
            continue  # admission window full: back off, same leader
        return out
    raise RaError("leader control call timed out")


def add_member(server_id: ServerId, new_member: ServerId, voter: bool = True,
               timeout: float = 5.0):
    return _leader_control(
        server_id,
        lambda fut: Command(kind=RA_JOIN, data=(new_member, voter),
                            reply_mode="await_consensus", from_ref=fut),
        timeout,
    )


def remove_member(server_id: ServerId, member: ServerId, timeout: float = 5.0):
    return _leader_control(
        server_id,
        lambda fut: Command(kind=RA_LEAVE, data=member,
                            reply_mode="await_consensus", from_ref=fut),
        timeout,
    )


def transfer_leadership(server_id: ServerId, target: ServerId, timeout: float = 5.0):
    return _leader_control(
        server_id, lambda fut: ("transfer_leadership", target, fut), timeout
    )


def force_shrink_members_to_current_member(server_id: ServerId, timeout: float = 5.0):
    """DANGEROUS disaster-recovery escape hatch: rewrite the member's
    cluster to itself alone and elect it (reference:
    ra:force_shrink_members_to_current_member)."""
    fut = Future()
    if not _try_send(server_id, ("force_shrink", fut)):
        raise RaError(f"server {server_id} unreachable")
    return fut.result(timeout)


def read_entries(server_id: ServerId, indexes, timeout: float = 5.0):
    """External sparse log read (reference: ra_log_read_plan — read log
    entries outside the server's apply path)."""
    idxs = list(indexes)
    fut = Future()
    if not _try_send(
        server_id, ("state_query", lambda s: s.log.sparse_read(idxs), fut)
    ):
        raise RaError(f"server {server_id} unreachable")
    return fut.result(timeout)[1]


def read_plan(server_id: ServerId, indexes, timeout: float = 5.0):
    """Capture a ReadPlan from the server (a tiny in-proc query), to be
    EXECUTED by the caller outside the server process (reference:
    ra_log_read_plan.erl:10-31 — partial_read in-proc, exec_read_plan
    external). Use ``plan.execute()`` (or ``exec_read_plan``) on any
    thread; the consensus path is never blocked by the reads."""
    from ra_tpu.log.read_plan import ReadPlan

    idxs = tuple(indexes)
    fut = Future()

    def capture(s):
        return (s.cfg.uid, getattr(s.log, "server_dir", ""))

    if not _try_send(server_id, ("state_query", capture, fut)):
        raise RaError(f"server {server_id} unreachable")
    uid, server_dir = fut.result(timeout)[1]
    return ReadPlan(uid=uid, node_name=server_id[1], server_dir=server_dir,
                    indexes=idxs)


# caller-side plan execution (one definition, re-exported)
from ra_tpu.log.read_plan import exec_read_plan  # noqa: E402,F401


def aux_command(server_id: ServerId, cmd: Any, timeout: float = 5.0):
    fut = Future()
    if not _try_send(server_id, ("aux", "call", cmd, fut)):
        raise RaError(f"server {server_id} unreachable")
    return fut.result(timeout)


# ---------------------------------------------------------------------------


def overview(node_name: str) -> dict:
    return _mgmt_route(node_name).overview()


def counters_overview() -> dict:
    """All registered counters/gauges (reference: ra_counters:overview)."""
    from ra_tpu import counters as _counters

    return _counters.overview()


def cluster_commit_rates() -> Dict[str, dict]:
    """Per-cluster leader + members + smoothed commit rate, joined from
    the leaderboard and the li-driven ``commit_rate`` gauges (per-server
    counters on the actor backend; the coordinator-aggregate gauge on
    the batch backend, reported with ``"scope": "node"``). The single
    data source for placement / leader balancing (ROADMAP item 1)."""
    from ra_tpu import counters as _counters

    out: Dict[str, dict] = {}
    for cluster, (leader, members) in leaderboard.snapshot().items():
        rate: Optional[int] = None
        scope = None
        if leader is not None:
            c = _counters.fetch((cluster, leader))
            if c is not None:
                rate = c.get("commit_rate")
                scope = "server"
            else:
                cc = _counters.fetch(("coordinator", leader[1]))
                if cc is not None:
                    # batch-backed leader: groups share one coordinator-
                    # aggregate gauge (no per-group counter vectors)
                    rate = cc.get("commit_rate")
                    scope = "node"
        out[cluster] = {
            "leader": leader,
            "members": list(members),
            "commit_rate": rate,
            "commit_rate_scope": scope,
        }
    return out


def system_overview(node_name: str, last_events: int = 100) -> dict:
    """One-call observability surface for a node (parity with the
    reference's ``ra:overview/1``, extended with the histogram/trace
    machinery of docs/INTERNALS.md §13): the node overview, every
    registered counter vector WITH field kind/help, latency-histogram
    percentiles (wave phases, commit stages, WAL), per-cluster commit
    rates, the node's per-group health scan (§14), and the most recent
    flight-recorder events."""
    from ra_tpu import counters as _counters
    from ra_tpu import health as _health
    from ra_tpu import obs as _obs

    return {
        "node": node_name,
        "overview": _mgmt_route(node_name).overview(),
        "counters": _counters.registry().describe_overview(),
        "histograms": _obs.histograms().overview(),
        "clusters": cluster_commit_rates(),
        "health": _health.node_health(node_name),
        "events": _obs.flight_recorder().events(last=last_events),
    }


def cluster_health(last_events: int = 0) -> dict:
    """Machine-readable cluster health feed (docs/INTERNALS.md §14) —
    the data source the placement/rebalancing layer (ROADMAP item 1)
    consumes, and what ``scripts/ra_top.py`` renders. Merges every
    registered node health scanner with the leaderboard:

    - ``nodes``     — per-node scan summaries (anomaly counts, the
      scans/fetches pair that proves the single-fetch discipline);
    - ``clusters``  — leaderboard leader/members joined with every
      replica's per-group gauge row (keyed ``group@node``);
    - ``anomalies`` — all non-quiet rows, worst first (severity, then
      the largest gap) — the top-of-the-pager view;
    - ``events``    — optionally, the most recent flight-recorder
      events (health transitions line up with elections/WAL failures).
    """
    from ra_tpu import health as _health
    from ra_tpu import obs as _obs

    nodes: Dict[str, dict] = {}
    by_cluster: Dict[str, Dict[str, dict]] = {}
    anomalies: List[dict] = []
    for node, sc in sorted(_health.scanners().items()):
        nodes[node] = sc.summary()
        for row in sc.rows():
            by_cluster.setdefault(row["cluster"], {})[
                f"{row['group']}@{node}"
            ] = row
            if row["state"] != "quiet":
                anomalies.append(row)
    anomalies.sort(
        key=lambda r: (
            # severity is the scanner's state code (health.py: severity
            # == code, higher worse) — one encoding, no parallel table
            r["severity"],
            max(r["commit_gap"], r["backlog"], r["match_gap"]),
        ),
        reverse=True,
    )
    lb = leaderboard.snapshot()
    clusters = {}
    for cl in set(lb) | set(by_cluster):
        leader, members = lb.get(cl, (None, ()))
        clusters[cl] = {
            "leader": leader,
            "members": list(members),
            "groups": by_cluster.get(cl, {}),
        }
    out = {"nodes": nodes, "clusters": clusters, "anomalies": anomalies}
    if last_events:
        out["events"] = _obs.flight_recorder().events(last=last_events)
    return out


def dump_trace(path: str) -> int:
    """Write the recorded wave-phase spans as Chrome/Perfetto trace
    JSON (load via chrome://tracing or ui.perfetto.dev). Tracing is off
    by default: call ``obs.trace_buffer().enable()`` (or run
    ``profile_wave.py --trace out.json``) first. Returns the number of
    span events written."""
    from ra_tpu import obs as _obs

    return _obs.trace_buffer().dump(path)


def prometheus_metrics() -> str:
    """Prometheus text exposition of every counter and histogram
    (scrape surface; see scripts/obs_smoke.sh for the CI check)."""
    from ra_tpu import obs as _obs

    return _obs.prometheus_text()
