"""Aux-machine context: server internals exposed to ``handle_aux``.

Capability parity with the reference's ``ra_aux`` (``src/ra_aux.erl:
8-23``): from inside an aux callback a machine can read its own machine
state, members, indexes, log entries and overview without going through
the client API. Instances wrap a live ``Server`` and are only valid for
the duration of one ``handle_aux`` call.
"""

from __future__ import annotations

from typing import Any, List, Optional, Sequence, Tuple

from ra_tpu.protocol import Entry, ServerId


class AuxContext:
    __slots__ = ("_server",)

    def __init__(self, server) -> None:
        self._server = server

    # -- machine / membership ---------------------------------------------

    def machine_state(self) -> Any:
        return self._server.machine_state

    def members(self) -> List[ServerId]:
        return self._server.members()

    def leader_id(self) -> Optional[ServerId]:
        return self._server.leader_id

    def current_term(self) -> int:
        return self._server.current_term

    # -- indexes ------------------------------------------------------------

    def commit_index(self) -> int:
        return self._server.commit_index

    def last_applied(self) -> int:
        return self._server.last_applied

    def last_index_term(self) -> Tuple[int, int]:
        return self._server.log.last_index_term()

    def snapshot_index_term(self) -> Optional[Tuple[int, int]]:
        return self._server.log.snapshot_index_term()

    # -- log reads -----------------------------------------------------------

    def log_fetch(self, idx: int) -> Optional[Entry]:
        return self._server.log.fetch(idx)

    def log_sparse_read(self, idxs: Sequence[int]) -> List[Entry]:
        return self._server.log.sparse_read(list(idxs))

    def overview(self) -> dict:
        return self._server.overview()
