"""Failpoint-driven disk/infra fault injection.

A process-global registry of **named injection sites** threaded through
the storage and infra stack (WAL batch write/fsync/recovery, segment
flush, compaction copy/rename, snapshot spool/promote, meta append, TCP
send/frame, writer-thread loops). Tests and the nemesis harness *arm* a
site with a deterministic trigger and an action; production code pays a
single dict miss per site check when nothing is armed — no lock, no
allocation (the BlackWater-style discipline: storage faults must be as
scriptable as partitions, arxiv 2203.07920).

Grammar (tuples, so nemesis scripts can carry them verbatim):

triggers
    ("one_shot",)        fire on the 1st hit, then disarm
    ("one_shot", n)      fire on the nth hit (1-based), then disarm
    ("every", n)         fire on every nth hit
    ("prob", p)          fire each hit with probability p (armed seed)
    ("always",)          fire on every hit

actions
    ("raise", name)      raise OSError(errno.<NAME>) — "enospc", "eio",
                         "eagain", "emfile" (or any errno name)
    ("torn", frac)       at a data site: write only the first
                         ``int(len(data) * frac)`` bytes, then raise
                         EIO — a torn/short write with the prefix on
                         disk (recovery must truncate or reject it)
    ("latency", secs)    sleep, then continue normally
    ("crash",)           raise ThreadCrash (a BaseException): kills the
                         hosting thread the way a real thread death
                         does, so supervision paths are exercised

Sites may be **scoped**: arming with ``scope="nodeA"`` only fires for
call sites that pass the same scope (multi-node tests target one node's
storage). An unscoped armed failpoint fires for every scope.

Site inventory (kept in docs/INTERNALS.md "Fault injection"):
    wal.write  wal.fsync  wal.open  wal.recover_read  wal.thread
    segment_writer.flush  segment_writer.thread  segment.append
    segments.compact_copy  segments.compact_rename
    snapshot.write  snapshot.chunk  snapshot.promote
    meta.append  tcp.send  tcp.frame
"""

from __future__ import annotations

import errno as _errno
import random
import threading
import time
from typing import Any, Dict, List, Optional, Tuple


class ThreadCrash(BaseException):
    """Injected thread death. Deliberately a BaseException: the WAL and
    segment-writer loops catch ``Exception`` (failure episodes) but let
    this propagate and kill the thread, so the node's infra supervisor
    restart path is what recovers — same shape as a real VM thread
    death."""


class _Failpoint:
    __slots__ = ("site", "action", "trigger", "scope", "rng", "lock",
                 "hit_count", "fire_count")

    def __init__(self, site: str, action: Tuple, trigger: Tuple,
                 seed: int, scope: Optional[str]):
        self.site = site
        self.action = tuple(action)
        self.trigger = tuple(trigger)
        self.scope = scope
        self.rng = random.Random(seed)
        self.lock = threading.Lock()
        self.hit_count = 0
        self.fire_count = 0

    def _should_fire(self) -> bool:
        """Called under self.lock; advances counters/rng deterministically."""
        self.hit_count += 1
        kind = self.trigger[0]
        if kind == "one_shot":
            n = self.trigger[1] if len(self.trigger) > 1 else 1
            return self.hit_count == n
        if kind == "every":
            return self.hit_count % self.trigger[1] == 0
        if kind == "prob":
            return self.rng.random() < self.trigger[1]
        if kind == "always":
            return True
        raise ValueError(f"unknown trigger {self.trigger!r}")


_lock = threading.Lock()
_armed: Dict[str, _Failpoint] = {}

# arm-wakers: callbacks invoked after every arm() (docs/INTERNALS.md
# §16). Event-driven idle loops (the WAL writer's untimed wait) need a
# nudge when a failpoint is armed against an IDLE thread — a parked
# loop re-checks its armed sites on wake, so a crash_thread nemesis
# still bites within one wakeup even with zero traffic. Callbacks must
# be cheap and never raise; registration is idempotent per callback.
_arm_wakers: List = []


def on_arm(cb) -> None:
    """Register ``cb()`` to run after every ``arm()``."""
    with _lock:
        if cb not in _arm_wakers:
            _arm_wakers.append(cb)


def off_arm(cb) -> None:
    with _lock:
        try:
            _arm_wakers.remove(cb)
        except ValueError:
            pass

# built-in sites whose call sites DO NOT pass a scope label: arming
# them with a scope would be a silent no-op (the _take scope filter
# would reject every hit), so arm() refuses. tcp.* sites ARE scoped,
# by the transport's node_name — a "host:port" string, not a RaNode
# name. Unknown/custom sites accept any scope.
UNSCOPED_SITES = frozenset({
    "segment.append", "segments.compact_copy", "segments.compact_rename",
    "snapshot.write", "snapshot.chunk", "snapshot.promote",
})


def arm(site: str, action: Tuple, trigger: Tuple = ("one_shot",),
        seed: int = 0, scope: Optional[str] = None) -> None:
    """Arm ``site``. Re-arming replaces the previous failpoint."""
    fp = _Failpoint(site, action, trigger, seed, scope)
    if fp.action[0] not in ("raise", "torn", "latency", "crash"):
        raise ValueError(f"unknown action {action!r}")
    if fp.trigger[0] not in ("one_shot", "every", "prob", "always"):
        raise ValueError(f"unknown trigger {trigger!r}")
    if scope is not None and site in UNSCOPED_SITES:
        raise ValueError(
            f"site {site!r} does not support scoping (its call sites "
            "pass no scope label — a scoped failpoint would never fire)"
        )
    if fp.action[0] == "crash" and not site.endswith(".thread"):
        # ThreadCrash is only recoverable where a supervisor watches the
        # hosting thread (the *.thread loop sites); anywhere else it
        # would silently wedge an arbitrary caller thread
        raise ValueError(
            f"('crash',) is only valid at *.thread sites, not {site!r}"
        )
    with _lock:
        _armed[site] = fp
        wakers = list(_arm_wakers)
    for cb in wakers:
        try:
            cb()
        except Exception:  # noqa: BLE001 — a waker must never block arming
            pass


def disarm(site: str) -> None:
    with _lock:
        _armed.pop(site, None)


def disarm_all() -> None:
    with _lock:
        _armed.clear()


def armed_sites() -> Dict[str, Tuple[Tuple, Tuple]]:
    with _lock:
        return {s: (fp.action, fp.trigger) for s, fp in _armed.items()}


def any_armed(*sites: str) -> bool:
    """True when any of ``sites`` has a live failpoint. Lock-free (the
    production fast path: hot loops route around accelerated paths only
    while injection is actually armed)."""
    if not _armed:
        return False
    return any(s in _armed for s in sites)


def anything_armed() -> bool:
    """True when ANY failpoint is live, regardless of site. The generic
    native-path gate: paths whose fault surface is the whole item flow
    (drain-classify, mailbox pack) rather than a named site fall back to
    Python whenever injection is running at all. Note ``any_armed()``
    with no sites returns False by design — this is the distinct
    'is a nemesis active' question."""
    return bool(_armed)


def stats(site: str) -> Tuple[int, int]:
    """(hits, fires) for an armed site; (0, 0) when not armed."""
    fp = _armed.get(site)
    if fp is None:
        return (0, 0)
    with fp.lock:
        return (fp.hit_count, fp.fire_count)


def _errno_exc(name: str) -> OSError:
    code = getattr(_errno, name.upper(), _errno.EIO)
    return OSError(code, f"injected: {name} at failpoint")


def _take(fp: _Failpoint, scope: Optional[str]) -> Optional[Tuple]:
    """Trigger evaluation; returns the action to perform or None."""
    if fp.scope is not None and scope != fp.scope:
        return None
    with fp.lock:
        fired = fp._should_fire()
        if not fired:
            return None
        fp.fire_count += 1
        one_shot = fp.trigger[0] == "one_shot"
    if one_shot:
        with _lock:
            if _armed.get(fp.site) is fp:
                del _armed[fp.site]
    # a firing failpoint is a flight-recorder event: post-mortem traces
    # of nemesis runs must show WHEN each injected fault bit relative
    # to the role changes/depositions around it
    from ra_tpu import obs as _obs

    _obs.record_event(
        "failpoint", node=scope,
        detail=f"{fp.site} -> {fp.action!r} (fire #{fp.fire_count})",
    )
    return fp.action


def fire(site: str, scope: Optional[str] = None) -> None:
    """The site check. Fast path (nothing armed): one dict miss."""
    fp = _armed.get(site)
    if fp is None:
        return
    act = _take(fp, scope)
    if act is None:
        return
    kind = act[0]
    if kind == "raise":
        raise _errno_exc(act[1])
    if kind == "latency":
        time.sleep(act[1])
        return
    if kind == "crash":
        raise ThreadCrash(f"injected thread crash at {site}")
    if kind == "torn":
        # a torn action at a no-data site degrades to a plain I/O error
        raise _errno_exc("eio")


def checked_write(site: str, f, data, scope: Optional[str] = None) -> None:
    """``f.write(data)`` with torn-write support: a torn action writes
    only a prefix of ``data`` (leaving it on disk) and then raises EIO,
    so recovery sees exactly what a power cut mid-write leaves behind.
    Fast path (nothing armed): one dict miss + the write."""
    fp = _armed.get(site)
    if fp is None:
        f.write(data)
        return
    act = _take(fp, scope)
    if act is None:
        f.write(data)
        return
    kind = act[0]
    if kind == "torn":
        cut = int(len(data) * act[1])
        if cut > 0:
            f.write(data[:cut])
            try:
                f.flush()
            except (OSError, ValueError):
                pass
        raise _errno_exc("eio")
    if kind == "latency":
        time.sleep(act[1])
        f.write(data)
        return
    if kind == "raise":
        raise _errno_exc(act[1])
    if kind == "crash":
        raise ThreadCrash(f"injected thread crash at {site}")


def mangle(site: str, data: bytes, scope: Optional[str] = None) -> bytes:
    """Corrupt in-flight bytes (wire frames): a torn action truncates,
    a raise action flips the first byte (the receiver's MAC/CRC must
    reject either). Latency sleeps; crash raises."""
    fp = _armed.get(site)
    if fp is None:
        return data
    act = _take(fp, scope)
    if act is None:
        return data
    kind = act[0]
    if kind == "torn":
        return data[: int(len(data) * act[1])]
    if kind == "raise":
        if not data:
            return data
        return bytes([data[0] ^ 0xFF]) + data[1:]
    if kind == "latency":
        time.sleep(act[1])
        return data
    raise ThreadCrash(f"injected thread crash at {site}")
