"""Pallas TPU kernel: masked quorum scan over the peer axis.

The per-step hot op of the batch backend is ``agreed_commit`` — for every
group, the majority-replicated index = the (nvoters//2)-th largest of the
voter-masked match vector (reference semantics: agreed_commit
src/ra_server.erl:3684-3688; scalar spec: ra_tpu.ops.decisions).

Layout: the peer axis (P <= 8) maps onto VPU sublanes and groups onto
lanes, so one (8, 128) register tile holds 128 groups' full match
vectors. A fixed odd-even transposition network (P passes of
compare-exchange between adjacent sublanes) sorts every lane
simultaneously — no data-dependent control flow, no cross-lane traffic.
The majority row is then selected per-lane by comparing a sublane iota
against ``P - 1 - nvoters // 2``.

``agreed_commit_pallas`` is numerically identical to the ``jnp.sort``
path used inside ``consensus_step`` (asserted by parity tests, which run
the kernel in interpret mode on CPU); swap it in with
``ra_tpu.ops.consensus.configure(quorum_backend="pallas")`` before the
first step. XLA already fuses the sort path well — this
kernel exists for the configurations where the sort's O(P log P)
generality loses to the fixed P-pass network and to keep the scan inside
one VMEM-resident fusion as P grows.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

LANES = 128
MAX_P = 8


def _quorum_kernel(match_ref, voting_ref, nvoters_ref, out_ref):
    # tile: (MAX_P, LANES) — peers on sublanes, groups on lanes
    m = jnp.where(voting_ref[...], match_ref[...], -1)
    # odd-even transposition sort along the sublane (peer) axis,
    # ascending: after MAX_P passes every lane is sorted
    for p in range(MAX_P):
        start = p % 2
        rolled = jnp.roll(m, -1, axis=0)
        lo = jnp.minimum(m, rolled)
        hi = jnp.maximum(m, rolled)
        rows = jax.lax.broadcasted_iota(jnp.int32, m.shape, 0)
        take_lo = (rows % 2 == start) & (rows < MAX_P - 1)
        take_hi = jnp.roll(take_lo, 1, axis=0)
        m = jnp.where(take_lo, lo, jnp.where(take_hi, jnp.roll(hi, 1, axis=0), m))
    # majority row per lane: ascending position MAX_P - 1 - nvoters // 2
    rows = jax.lax.broadcasted_iota(jnp.int32, m.shape, 0)
    pos = MAX_P - 1 - nvoters_ref[...] // 2  # (1, LANES) broadcast row
    sel = rows == pos
    out_ref[...] = jnp.max(jnp.where(sel, m, -(2 ** 31 - 1)), axis=0, keepdims=True)


@functools.partial(jax.jit, static_argnames=("interpret",))
def agreed_commit_pallas(
    match: jax.Array,  # i32[G, P]
    voting: jax.Array,  # bool[G, P]
    nvoters: jax.Array,  # i32[G]
    interpret: bool = False,
) -> jax.Array:
    """Per-group agreed commit index (majority-replicated match)."""
    g, p = match.shape
    assert p <= MAX_P, f"peer width {p} exceeds {MAX_P}"
    gp = ((g + LANES - 1) // LANES) * LANES
    # transpose to (P, G): peers on sublanes, groups on lanes; pad peers
    # with -1 (never selected) and groups to a lane multiple
    mt = jnp.full((MAX_P, gp), -1, jnp.int32)
    mt = mt.at[:p, :g].set(match.T)
    vt = jnp.zeros((MAX_P, gp), jnp.bool_)
    vt = vt.at[:p, :g].set(voting.T)
    nv = jnp.zeros((1, gp), jnp.int32).at[0, :g].set(nvoters)

    out = pl.pallas_call(
        _quorum_kernel,
        grid=(gp // LANES,),
        in_specs=[
            pl.BlockSpec((MAX_P, LANES), lambda i: (0, i)),
            pl.BlockSpec((MAX_P, LANES), lambda i: (0, i)),
            pl.BlockSpec((1, LANES), lambda i: (0, i)),
        ],
        out_specs=pl.BlockSpec((1, LANES), lambda i: (0, i)),
        out_shape=jax.ShapeDtypeStruct((1, gp), jnp.int32),
        interpret=interpret,
    )(mt, vt, nv)
    return out[0, :g]


def agreed_commit_reference(match, voting, nvoters):
    """The exact formulation consensus_step's sort backend executes —
    shared, so parity tests cover the production path."""
    from ra_tpu.ops.consensus import agreed_commit_sort

    return agreed_commit_sort(match, voting, nvoters)
