"""Vectorized consensus kernels over a raft-group batch axis.

The TPU execution backend for the consensus decision hot path: per-group
scalar state lives in HBM as int32 structure-of-arrays indexed by
group-id, and the three north-star decisions run as one fused, jitted
step over *all* groups at once:

- AppendEntries accept (term/prev-log matching) — mirrors
  ``decisions.aer_decision`` (reference behavior: src/ra_server.erl
  handle_follower :1283-1429);
- RequestVote / PreVote grant — mirrors ``decisions.vote_decision`` /
  ``decisions.pre_vote_decision`` (reference: :1489-1529, :2926-2984);
- match_index -> commit_index quorum scan — mirrors
  ``decisions.agreed_commit`` (reference: :3633-3688).

Log *contents* stay host-side; the device keeps a ring-buffer window of
recent entry terms (``term_suffix``, indexed by ``idx % K``) so prev-term
matching and commit-term gating run without host round-trips. Groups
whose lookup falls outside the window raise a ``needs_host`` flag and are
resolved by the scalar oracle on the host (rare: deep backfill).

TPU-first design notes:
- everything is fixed-shape int32/bool; no data-dependent control flow —
  each step processes "at most one message per group" mailboxes, masked
  by ``msg_type``;
- the group axis is embarrassingly parallel: shard it over a
  ``jax.sharding.Mesh`` axis ("groups") and every kernel runs without
  collectives; only host ingress/egress crosses the boundary;
- P (replica slots) is a small static width; quorum scan is a sort along
  that axis (lane-local, VPU-friendly).
"""

from __future__ import annotations

import functools
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp

# message type tags for the per-group mailbox
MSG_NONE = 0
MSG_AER = 1  # AppendEntries request (follower path)
MSG_AER_REPLY = 2  # AppendEntries reply (leader path)
MSG_VOTE_REQ = 3
MSG_VOTE_REPLY = 4
MSG_PREVOTE_REQ = 5
MSG_PREVOTE_REPLY = 6

# quorum-scan backend: "sort" (jnp.sort; XLA fuses it well) or "pallas"
# (the fixed odd-even network kernel in ra_tpu.ops.pallas_quorum).
# Switch with configure(quorum_backend=...) BEFORE the first step — it
# clears the jit caches so the choice takes effect.
_QUORUM_BACKEND = "sort"


def configure(quorum_backend: str = None) -> None:
    global _QUORUM_BACKEND
    if quorum_backend is not None:
        if quorum_backend not in ("sort", "pallas"):
            raise ValueError(f"unknown quorum_backend {quorum_backend!r}")
        _QUORUM_BACKEND = quorum_backend
        consensus_step.clear_cache()
        consensus_step_packed.clear_cache()
        consensus_step_packed_sub.clear_cache()


# roles
R_FOLLOWER = 0
R_PRE_VOTE = 1
R_CANDIDATE = 2
R_LEADER = 3

# AER decision codes (must match ra_tpu.ops.decisions)
AER_STALE = 0
AER_OK = 1
AER_MISMATCH = 2
AER_BEHIND_SNAPSHOT = 3


class GroupState(NamedTuple):
    """Per-group consensus state, shape [G] or [G, P]. ``self_slot`` is
    this coordinator's slot in each group's member table."""

    current_term: jax.Array  # i32[G]
    voted_for: jax.Array  # i32[G], peer slot or -1
    commit_index: jax.Array  # i32[G]
    last_applied: jax.Array  # i32[G]
    last_index: jax.Array  # i32[G] last visible log index
    last_term: jax.Array  # i32[G]
    written_index: jax.Array  # i32[G] durable watermark
    snapshot_index: jax.Array  # i32[G]
    snapshot_term: jax.Array  # i32[G]
    role: jax.Array  # i32[G]
    leader_slot: jax.Array  # i32[G], -1 unknown
    self_slot: jax.Array  # i32[G]
    machine_version: jax.Array  # i32[G] effective machine version
    match_index: jax.Array  # i32[G, P]
    next_index: jax.Array  # i32[G, P]
    voting: jax.Array  # bool[G, P]
    active: jax.Array  # bool[G, P]
    votes: jax.Array  # bool[G, P]
    pre_votes: jax.Array  # bool[G, P]
    term_suffix: jax.Array  # i32[G, K] ring buffer of entry terms
    # inclusive interval of indexes whose ring slots are stale (multi-
    # entry accepts record only the tail term until the host reconciles
    # via record_appended); empty when lo > hi
    unknown_lo: jax.Array  # i32[G]
    unknown_hi: jax.Array  # i32[G]
    # pre-vote round counter: bumped on every pre-vote entry so stale
    # grants from an earlier round can't combine with the current one
    # (mirrors Server.pre_vote_token; reference: token ref in
    # src/ra_server.erl call_for_election :2900-2924)
    pre_vote_token: jax.Array  # i32[G]


class Mailbox(NamedTuple):
    """At most one inbound message per group per step (dense)."""

    msg_type: jax.Array  # i32[G]
    sender_slot: jax.Array  # i32[G]
    term: jax.Array  # i32[G]
    # AER request fields
    prev_idx: jax.Array  # i32[G]
    prev_term: jax.Array  # i32[G]
    num_entries: jax.Array  # i32[G]
    entries_last_term: jax.Array  # i32[G] term of last entry in the batch
    leader_commit: jax.Array  # i32[G]
    # reply fields (AER reply) / vote fields
    success: jax.Array  # bool[G] (AER reply / vote granted)
    reply_next_idx: jax.Array  # i32[G]
    reply_last_idx: jax.Array  # i32[G]
    reply_last_term: jax.Array  # i32[G]
    cand_last_idx: jax.Array  # i32[G]
    cand_last_term: jax.Array  # i32[G]
    cand_machine_version: jax.Array  # i32[G]
    # host-resolved term cache: when a previous step flagged needs_host,
    # the host re-submits the message with the term it read from its log
    # at host_term_idx (-1 = no override)
    host_term_idx: jax.Array  # i32[G]
    host_term_val: jax.Array  # i32[G]
    # pre-vote reply round token (must match state.pre_vote_token to count)
    token: jax.Array  # i32[G]


class Egress(NamedTuple):
    """Per-group outbound decision for the host to serialize."""

    send_reply: jax.Array  # bool[G] reply to sender?
    reply_type: jax.Array  # i32[G] echoes request type
    reply_to: jax.Array  # i32[G] sender slot
    term: jax.Array  # i32[G]
    success: jax.Array  # bool[G]
    next_index: jax.Array  # i32[G]
    last_index: jax.Array  # i32[G]
    last_term: jax.Array  # i32[G]
    aer_code: jax.Array  # i32[G] accept decision (write entries iff OK)
    became_leader: jax.Array  # bool[G]
    became_candidate: jax.Array  # bool[G]
    commit_advanced_to: jax.Array  # i32[G] new commit index (== old if not)
    needs_host: jax.Array  # bool[G] fall back to scalar oracle
    term_or_vote_changed: jax.Array  # bool[G] host must persist term/vote
    # post-step mirror for the host (role/leader/current term/agreed idx)
    role: jax.Array  # i32[G]
    leader_slot: jax.Array  # i32[G]
    agreed_idx: jax.Array  # i32[G] quorum match point (for host term lookup)
    voted_for: jax.Array  # i32[G] post-step vote (slot or -1) for persistence


def make_group_state(num_groups: int, num_peers: int, suffix_k: int = 32) -> GroupState:
    g, p, k = num_groups, num_peers, suffix_k
    zi = lambda *s: jnp.zeros(s, dtype=jnp.int32)  # noqa: E731
    zb = lambda *s: jnp.zeros(s, dtype=jnp.bool_)  # noqa: E731
    return GroupState(
        current_term=zi(g),
        voted_for=jnp.full((g,), -1, jnp.int32),
        commit_index=zi(g),
        last_applied=zi(g),
        last_index=zi(g),
        last_term=zi(g),
        written_index=zi(g),
        snapshot_index=zi(g),
        snapshot_term=zi(g),
        role=zi(g),
        leader_slot=jnp.full((g,), -1, jnp.int32),
        self_slot=zi(g),
        machine_version=zi(g),
        match_index=zi(g, p),
        next_index=jnp.ones((g, p), jnp.int32),
        voting=jnp.ones((g, p), jnp.bool_),
        active=jnp.ones((g, p), jnp.bool_),
        votes=zb(g, p),
        pre_votes=zb(g, p),
        term_suffix=zi(g, k),
        unknown_lo=jnp.ones((g,), jnp.int32),
        unknown_hi=zi(g),
        pre_vote_token=zi(g),
    )


def empty_mailbox(num_groups: int) -> Mailbox:
    g = num_groups
    zi = lambda: jnp.zeros((g,), jnp.int32)  # noqa: E731
    return Mailbox(
        msg_type=zi(),
        sender_slot=zi(),
        term=zi(),
        prev_idx=zi(),
        prev_term=zi(),
        num_entries=zi(),
        entries_last_term=zi(),
        leader_commit=zi(),
        success=jnp.zeros((g,), jnp.bool_),
        reply_next_idx=zi(),
        reply_last_idx=zi(),
        reply_last_term=zi(),
        cand_last_idx=zi(),
        cand_last_term=zi(),
        cand_machine_version=zi(),
        host_term_idx=jnp.full((g,), -1, jnp.int32),
        host_term_val=jnp.full((g,), -1, jnp.int32),
        token=zi(),
    )


# ---------------------------------------------------------------------------
# device-side term lookup


def agreed_commit_sort(
    match: jax.Array, voting: jax.Array, nvoters: jax.Array
) -> jax.Array:
    """Quorum scan, jnp.sort formulation — the single shared
    implementation (the pallas kernel's parity reference and the default
    in-step backend)."""
    p = match.shape[-1]
    eff = jnp.where(voting, match, -1)
    srt = jnp.sort(eff, axis=-1)  # ascending; non-voters (-1) first
    pos = jnp.clip(p - 1 - nvoters // 2, 0, p - 1)
    return jnp.take_along_axis(srt, pos[:, None], axis=-1).squeeze(-1)


def term_at(state: GroupState, idx: jax.Array) -> Tuple[jax.Array, jax.Array]:
    """(term, known) — term of the entry at ``idx`` from the ring-buffer
    window / snapshot boundary. known=False → host fallback needed."""
    k = state.term_suffix.shape[-1]
    in_window = (idx > jnp.maximum(state.last_index - k, state.snapshot_index)) & (
        idx <= state.last_index
    )
    ring = jnp.take_along_axis(
        state.term_suffix, (idx % k)[..., None], axis=-1
    ).squeeze(-1)
    is_snap = idx == state.snapshot_index
    is_zero = idx <= 0
    stale = (idx >= state.unknown_lo) & (idx <= state.unknown_hi)
    term = jnp.where(is_zero, 0, jnp.where(is_snap, state.snapshot_term, ring))
    known = is_zero | is_snap | (in_window & ~stale)
    return term.astype(jnp.int32), known


def _log_up_to_date(our_idx, our_term, cand_idx, cand_term):
    return (cand_term > our_term) | ((cand_term == our_term) & (cand_idx >= our_idx))


# ---------------------------------------------------------------------------
# the fused step


def consensus_step_impl(state: GroupState, mbox: Mailbox) -> Tuple[GroupState, Egress]:
    """One decision step over all groups: classify at most one inbound
    message per group, update consensus bookkeeping, run the quorum scan.
    Pure function of (state, mailbox) — host performs all I/O."""
    G, P = state.match_index.shape
    gids = jnp.arange(G)

    is_aer = mbox.msg_type == MSG_AER
    is_aer_reply = mbox.msg_type == MSG_AER_REPLY
    is_vote_req = mbox.msg_type == MSG_VOTE_REQ
    is_vote_reply = mbox.msg_type == MSG_VOTE_REPLY
    is_prevote_req = mbox.msg_type == MSG_PREVOTE_REQ
    is_prevote_reply = mbox.msg_type == MSG_PREVOTE_REPLY
    has_msg = mbox.msg_type != MSG_NONE

    term0 = state.current_term
    voted0 = state.voted_for
    role0 = state.role

    # -- universal higher-term handling (pre-vote requests excluded: they
    #    probe without dethroning; pre-vote *replies* carry real terms)
    bumps_term = has_msg & ~is_prevote_req & (mbox.term > term0)
    term1 = jnp.where(bumps_term, mbox.term, term0)
    voted1 = jnp.where(bumps_term, -1, voted0)
    role1 = jnp.where(bumps_term, R_FOLLOWER, role0)
    leader1 = jnp.where(bumps_term, -1, state.leader_slot)

    # ---------------- AER (follower accept path) ----------------
    local_prev_term, prev_known = term_at(state, mbox.prev_idx)
    # host-resolved override (deep backfill outside the device window)
    prev_override = (mbox.host_term_idx == mbox.prev_idx) & (mbox.host_term_val >= 0)
    local_prev_term = jnp.where(prev_override, mbox.host_term_val, local_prev_term)
    prev_known = prev_known | prev_override
    aer_stale = mbox.term < term1
    aer_behind = mbox.prev_idx < state.snapshot_index
    aer_match = prev_known & (local_prev_term == mbox.prev_term)
    aer_code = jnp.where(
        aer_stale,
        AER_STALE,
        jnp.where(
            aer_behind,
            AER_BEHIND_SNAPSHOT,
            jnp.where(aer_match, AER_OK, AER_MISMATCH),
        ),
    ).astype(jnp.int32)
    aer_ok = is_aer & (aer_code == AER_OK)
    aer_fail_next = jnp.where(
        aer_behind,
        state.snapshot_index + 1,
        jnp.where(
            state.last_index < mbox.prev_idx,
            state.last_index + 1,
            state.commit_index + 1,
        ),
    )
    # host fallback when prev-term unknown on device (deep backfill)
    aer_needs_host = is_aer & ~aer_stale & ~aer_behind & ~prev_known

    # accepting an AER names the sender leader and becomes follower
    role2 = jnp.where(aer_ok, R_FOLLOWER, role1)
    leader2 = jnp.where(aer_ok, mbox.sender_slot, leader1)

    # log tail bookkeeping for accepted entries (host writes the bytes;
    # device tracks the resulting tail). Overwrite of a divergent suffix
    # rewinds last_index to prev+n.
    new_last = mbox.prev_idx + mbox.num_entries
    takes_entries = aer_ok & (mbox.num_entries > 0)
    last_index2 = jnp.where(takes_entries, new_last, state.last_index)
    last_term2 = jnp.where(takes_entries, mbox.entries_last_term, state.last_term)
    # record the accepted tail term in the ring so back-to-back device
    # steps can prev-match without host reconciliation (exact for the
    # batch's last entry; the host's record_appended covers the rest of
    # a multi-entry batch)
    kk = state.term_suffix.shape[-1]
    tail_slot = (new_last % kk)[:, None]
    term_suffix2 = jnp.where(
        (jnp.arange(kk)[None, :] == tail_slot) & takes_entries[:, None],
        mbox.entries_last_term[:, None],
        state.term_suffix,
    )
    # only the batch tail's term is exact: mark intermediate indexes of a
    # multi-entry accept stale until the host record_appended reconciles
    multi = takes_entries & (mbox.num_entries > 1)
    had_inv = state.unknown_lo <= state.unknown_hi
    unknown_lo2 = jnp.where(
        multi,
        jnp.where(had_inv, jnp.minimum(state.unknown_lo, mbox.prev_idx + 1),
                  mbox.prev_idx + 1),
        state.unknown_lo,
    )
    unknown_hi2 = jnp.where(
        multi, jnp.maximum(state.unknown_hi, new_last - 1), state.unknown_hi
    )
    # followers' commit index: min(leader_commit, last entry index)
    commit2 = jnp.where(
        aer_ok,
        jnp.maximum(state.commit_index, jnp.minimum(mbox.leader_commit, new_last)),
        state.commit_index,
    )

    # ---------------- votes ----------------
    fresh_term = mbox.term > term0
    free_to_vote = fresh_term | (voted1 == -1) | (voted1 == mbox.sender_slot)
    up_to_date = _log_up_to_date(
        last_index2, last_term2, mbox.cand_last_idx, mbox.cand_last_term
    )
    vote_grant = is_vote_req & (mbox.term >= term1) & free_to_vote & up_to_date
    voted2 = jnp.where(vote_grant, mbox.sender_slot, voted1)
    leader3 = jnp.where(vote_grant, -1, leader2)

    prevote_grant = (
        is_prevote_req
        & (mbox.term >= term1)
        & (mbox.cand_machine_version >= state.machine_version)
        & up_to_date
    )

    # ---------------- vote replies (candidate/pre_vote path) ----------------
    count_vote = is_vote_reply & (role1 == R_CANDIDATE) & mbox.success & (mbox.term == term1)
    votes2 = jnp.where(
        (count_vote[:, None] & (jnp.arange(P)[None, :] == mbox.sender_slot[:, None]))
        | state.votes,
        True,
        False,
    )
    votes2 = jnp.where(role1[:, None] == R_CANDIDATE, votes2, False)
    count_prevote = (
        is_prevote_reply
        & (role1 == R_PRE_VOTE)
        & mbox.success
        & (mbox.term <= term1)
        & (mbox.token == state.pre_vote_token)
    )
    pre_votes2 = jnp.where(
        (count_prevote[:, None] & (jnp.arange(P)[None, :] == mbox.sender_slot[:, None]))
        | state.pre_votes,
        True,
        False,
    )
    pre_votes2 = jnp.where(role1[:, None] == R_PRE_VOTE, pre_votes2, False)

    n_voters = jnp.sum(state.voting & state.active, axis=-1)
    quorum = n_voters // 2 + 1
    self_vote = jnp.take_along_axis(
        state.voting & state.active, state.self_slot[:, None], axis=-1
    ).squeeze(-1)
    n_votes = jnp.sum(votes2 & state.voting & state.active, axis=-1) + jnp.where(
        self_vote & (role1 == R_CANDIDATE), 1, 0
    )
    n_prevotes = jnp.sum(pre_votes2 & state.voting & state.active, axis=-1) + jnp.where(
        self_vote & (role1 == R_PRE_VOTE), 1, 0
    )
    became_leader = (role1 == R_CANDIDATE) & (n_votes >= quorum)
    became_candidate = (role1 == R_PRE_VOTE) & (n_prevotes >= quorum)

    role3 = jnp.where(became_leader, R_LEADER, role2)
    role3 = jnp.where(became_candidate, R_CANDIDATE, role3)
    # candidate promotion bumps the term and votes for self
    term2 = jnp.where(became_candidate, term1 + 1, term1)
    voted3 = jnp.where(became_candidate, state.self_slot, voted2)
    leader4 = jnp.where(became_leader, state.self_slot, leader3)
    votes3 = jnp.where(became_candidate[:, None], False, votes2)
    pre_votes3 = jnp.where(became_candidate[:, None], False, pre_votes2)

    # new leader resets peer bookkeeping
    match2 = jnp.where(became_leader[:, None], 0, state.match_index)
    next2 = jnp.where(
        became_leader[:, None], (last_index2 + 1)[:, None], state.next_index
    )

    # ---------------- AER replies (leader path) ----------------
    lead_ok = is_aer_reply & (role3 == R_LEADER) & (mbox.term == term2)
    sender_onehot = jnp.arange(P)[None, :] == mbox.sender_slot[:, None]
    succ = (lead_ok & mbox.success)[:, None] & sender_onehot
    match3 = jnp.where(succ, jnp.maximum(match2, mbox.reply_last_idx[:, None]), match2)
    next3 = jnp.where(
        succ, jnp.maximum(next2, mbox.reply_last_idx[:, None] + 1), next2
    )
    fail = (lead_ok & ~mbox.success)[:, None] & sender_onehot
    fail_hint = jnp.maximum(
        jnp.minimum(mbox.reply_next_idx, mbox.reply_last_idx + 1)[:, None], match3 + 1
    )
    next4 = jnp.where(fail, jnp.maximum(fail_hint, 1), next3)

    # ---------------- quorum commit scan (leaders, every step) ----------------
    is_self = jnp.arange(P)[None, :] == state.self_slot[:, None]
    eff_match = jnp.where(is_self, state.written_index[:, None], match3)
    if _QUORUM_BACKEND == "pallas" and P <= 8:
        from ra_tpu.ops.pallas_quorum import agreed_commit_pallas

        agreed = agreed_commit_pallas(
            eff_match,
            state.voting & state.active,
            n_voters,
            # interpret only where no TPU compiler exists; note the real
            # chip's platform name here is "axon", not "tpu"
            interpret=jax.default_backend() == "cpu",
        )
    else:
        # P > 8 exceeds the pallas kernel's sublane width: sort fallback
        agreed = agreed_commit_sort(eff_match, state.voting & state.active, n_voters)
    agreed_term, agreed_known = term_at(
        state._replace(
            last_index=last_index2,
            last_term=last_term2,
            term_suffix=term_suffix2,
            unknown_lo=unknown_lo2,
            unknown_hi=unknown_hi2,
        ),
        agreed,
    )
    agreed_override = (mbox.host_term_idx == agreed) & (mbox.host_term_val >= 0)
    agreed_term = jnp.where(agreed_override, mbox.host_term_val, agreed_term)
    agreed_known = agreed_known | agreed_override
    can_commit = (
        (role3 == R_LEADER)
        & (agreed > commit2)
        & agreed_known
        & (agreed_term == term2)
    )
    commit3 = jnp.where(can_commit, agreed, commit2)
    quorum_needs_host = (role3 == R_LEADER) & (agreed > commit2) & ~agreed_known

    # ---------------- egress ----------------
    reply_success = jnp.where(
        is_aer,
        aer_code == AER_OK,
        jnp.where(is_vote_req, vote_grant, jnp.where(is_prevote_req, prevote_grant, False)),
    )
    # AER success replies report the durable watermark (host may defer the
    # actual send until fsync when entries were written)
    wi = jnp.where(aer_ok, state.written_index, last_index2)
    reply_next = jnp.where(
        is_aer & (aer_code != AER_OK), aer_fail_next, wi + 1
    )
    egress = Egress(
        # a needs_host AER is resolved entirely by the host oracle — the
        # device must not also emit its (bogus) mismatch rejection
        send_reply=has_msg & ((is_aer & ~aer_needs_host) | is_vote_req | is_prevote_req),
        reply_type=mbox.msg_type,
        reply_to=mbox.sender_slot,
        term=term2,
        success=reply_success,
        next_index=reply_next,
        last_index=jnp.where(is_aer & aer_ok, wi, last_index2),
        last_term=last_term2,
        aer_code=jnp.where(is_aer, aer_code, -1),
        became_leader=became_leader,
        became_candidate=became_candidate,
        commit_advanced_to=commit3,
        needs_host=aer_needs_host | quorum_needs_host,
        term_or_vote_changed=(term2 != term0) | (voted3 != voted0),
        role=role3,
        leader_slot=leader4,
        agreed_idx=agreed,
        voted_for=voted3,
    )
    new_state = state._replace(
        current_term=term2,
        voted_for=voted3,
        commit_index=commit3,
        last_index=last_index2,
        last_term=last_term2,
        role=role3,
        leader_slot=leader4,
        match_index=match3,
        next_index=next4,
        votes=votes3,
        pre_votes=pre_votes3,
        term_suffix=term_suffix2,
        unknown_lo=unknown_lo2,
        unknown_hi=unknown_hi2,
    )
    return new_state, egress


# The production entry point: jitted with the state buffers donated so the
# G-sized arrays update in place in HBM.
consensus_step = jax.jit(consensus_step_impl, donate_argnums=(0,))


# Packed interface: the host coordinator ships the whole mailbox as ONE
# (len(MBOX_FIELDS), G) int32 array and receives the egress as ONE
# (len(EGRESS_FIELDS), G) int32 array — a single transfer each way per
# step instead of ~35 small ones. reply_to is intentionally omitted from
# the egress pack (hosts address replies via the consumed message's
# sender).
MBOX_FIELDS = [
    "msg_type", "sender_slot", "term", "prev_idx", "prev_term",
    "num_entries", "entries_last_term", "leader_commit", "success",
    "reply_next_idx", "reply_last_idx", "reply_last_term", "cand_last_idx",
    "cand_last_term", "cand_machine_version", "host_term_idx",
    "host_term_val", "token",
]
EGRESS_FIELDS = [
    "send_reply", "reply_type", "term", "success", "next_index",
    "last_index", "last_term", "aer_code", "became_leader",
    "became_candidate", "commit_advanced_to", "needs_host",
    "term_or_vote_changed", "role", "leader_slot", "agreed_idx",
    "voted_for",
]


# packed lists must track the namedtuples: a drifted field name would be
# silently dropped on the host side
assert set(MBOX_FIELDS) == set(Mailbox._fields), (
    set(MBOX_FIELDS) ^ set(Mailbox._fields)
)
assert set(EGRESS_FIELDS) == set(Egress._fields) - {"reply_to"}, (
    set(EGRESS_FIELDS) ^ (set(Egress._fields) - {"reply_to"})
)


def _consensus_step_packed_impl(state: GroupState, packed: jax.Array):
    rows = {name: packed[i] for i, name in enumerate(MBOX_FIELDS)}
    rows["success"] = rows["success"] != 0
    mbox = Mailbox(**rows)
    new_state, eg = consensus_step_impl(state, mbox)
    out = jnp.stack(
        [
            getattr(eg, name).astype(jnp.int32)
            for name in EGRESS_FIELDS
        ]
    )
    return new_state, out


consensus_step_packed = jax.jit(_consensus_step_packed_impl, donate_argnums=(0,))


def _consensus_step_packed_sub_impl(
    state: GroupState, packed: jax.Array, gidx: jax.Array
):
    """Active-set step: gather ONLY the rows named by ``gidx`` (an i32
    vector padded to a power of two with out-of-range ids), run the
    fused step over the compact sub-batch, scatter results back. Step
    cost scales with *activity*, not capacity — the batch backend's
    analog of the reference's per-group process waking only on messages
    (reference: src/ra_server_proc.erl:457-530). Pad rows gather a
    clamped row's state but their writes are dropped on the scatter, so
    they cannot perturb any real group."""
    sub = jax.tree.map(lambda a: a[gidx], state)
    rows = {name: packed[i] for i, name in enumerate(MBOX_FIELDS)}
    rows["success"] = rows["success"] != 0
    mbox = Mailbox(**rows)
    sub_new, eg = consensus_step_impl(sub, mbox)
    out = jnp.stack(
        [getattr(eg, name).astype(jnp.int32) for name in EGRESS_FIELDS]
    )
    new_state = jax.tree.map(
        lambda full, s: full.at[gidx].set(s, mode="drop"), state, sub_new
    )
    return new_state, out


consensus_step_packed_sub = jax.jit(
    _consensus_step_packed_sub_impl, donate_argnums=(0,)
)


# Scatter-fused packed interface (docs/INTERNALS.md §15): the host's
# queued log-tail updates ride the SAME packed array as the mailbox —
# six extra rows after MBOX_FIELDS — and are applied on-device at the
# START of the step, before the quorum scan. One transfer and one
# dispatch per step instead of separate record_appended_runs /
# record_written calls (each with its own column uploads): on a CPU
# host the per-call dispatch overhead was a top cost of the unloaded
# commit wave. Pad entries carry an out-of-range gid (>= capacity);
# scatters drop them. a_* rows are contiguous same-term appended runs
# (one per group, gids unique); w_* rows are durable watermarks.
# NOT for sharded state: the mailbox shards column-wise, which would
# split the scatter rows across devices — sharded coordinators keep
# the separate record_* calls.
MBOX_SCAT_FIELDS = ["a_gid", "a_lo", "a_hi", "a_term", "w_gid", "w_idx"]


def _apply_packed_scatters(state: GroupState, packed: jax.Array) -> GroupState:
    # row-space form of record_appended_runs + record_written: every
    # temporary is (rows, k)-shaped, never (G, ...)-shaped, so the
    # per-step cost scales with the mailbox width, not capacity (the
    # full-state jnp.where variant cost O(G*k) per step at 10k groups).
    # Semantics match record_appended_runs exactly: tails advance by
    # max, ring slots in [lo, hi] take the run term, last_term re-reads
    # the updated ring at the (possibly unmoved) tail, staleness
    # clears; pad rows (gid >= G) drop on every scatter.
    base = len(MBOX_FIELDS)
    gids = packed[base]
    los = packed[base + 1]
    his = packed[base + 2]
    terms = packed[base + 3]
    k = state.term_suffix.shape[-1]
    los_c = jnp.maximum(los, his - (k - 1))
    slots = jnp.arange(k)[None, :]
    # largest index i <= hi with i % k == slot
    idx_at_slot = his[:, None] - ((his[:, None] - slots) % k)
    mask = idx_at_slot >= los_c[:, None]
    cur = state.term_suffix.at[gids].get(mode="fill", fill_value=0)
    rows = jnp.where(mask, terms[:, None], cur)
    ts = state.term_suffix.at[gids].set(rows, mode="drop")
    old_last = state.last_index.at[gids].get(mode="fill", fill_value=0)
    new_last = jnp.maximum(old_last, his)
    last_index = state.last_index.at[gids].set(new_last, mode="drop")
    ring_at_tail = jnp.take_along_axis(
        rows, (new_last % k)[:, None], axis=-1
    ).squeeze(-1)
    last_term = state.last_term.at[gids].set(ring_at_tail, mode="drop")
    unknown_lo = state.unknown_lo.at[gids].set(
        jnp.ones_like(gids), mode="drop"
    )
    unknown_hi = state.unknown_hi.at[gids].set(
        jnp.zeros_like(gids), mode="drop"
    )
    return state._replace(
        term_suffix=ts,
        last_index=last_index,
        last_term=last_term,
        unknown_lo=unknown_lo,
        unknown_hi=unknown_hi,
        written_index=state.written_index.at[packed[base + 4]].max(
            packed[base + 5], mode="drop"
        ),
    )


def _consensus_step_packed_scat_impl(state: GroupState, packed: jax.Array):
    state = _apply_packed_scatters(state, packed)
    return _consensus_step_packed_impl(state, packed)


consensus_step_packed_scat = jax.jit(
    _consensus_step_packed_scat_impl, donate_argnums=(0,)
)


def _consensus_step_packed_sub_scat_impl(
    state: GroupState, packed: jax.Array, gidx: jax.Array
):
    # scatters apply to the FULL state before the active-set gather
    # (every appended/written group is in the active set by
    # construction, so the gathered sub-batch sees the new tails)
    state = _apply_packed_scatters(state, packed)
    return _consensus_step_packed_sub_impl(state, packed, gidx)


consensus_step_packed_sub_scat = jax.jit(
    _consensus_step_packed_sub_scat_impl, donate_argnums=(0,)
)


# ---------------------------------------------------------------------------
# host-side helpers for log-tail maintenance


@jax.jit
def record_appended(
    state: GroupState, group_ids: jax.Array, idxs: jax.Array, terms: jax.Array
) -> GroupState:
    """Record host-appended entries (scatter into the term ring buffer and
    advance the tails of the named groups). A batch may carry several
    entries for one group; (group, idx) pairs must be unique."""
    k = state.term_suffix.shape[-1]
    ts = state.term_suffix.at[group_ids, idxs % k].set(terms)
    # .max is order-independent under duplicate group indices...
    last_index = state.last_index.at[group_ids].max(idxs)
    # ...and last_term is then read back from the ring at the new tail
    # (a duplicate-index .set of terms would have implementation-defined
    # order for multi-entry batches spanning a term change)
    touched = jnp.zeros_like(state.last_index, dtype=jnp.bool_).at[group_ids].set(True)
    ring_at_tail = jnp.take_along_axis(ts, (last_index % k)[:, None], axis=-1).squeeze(-1)
    last_term = jnp.where(touched, ring_at_tail, state.last_term)
    # the host has reconciled these groups' rings exactly: clear staleness
    unknown_lo = jnp.where(touched, 1, state.unknown_lo)
    unknown_hi = jnp.where(touched, 0, state.unknown_hi)
    return state._replace(
        term_suffix=ts,
        last_index=last_index,
        last_term=last_term,
        unknown_lo=unknown_lo,
        unknown_hi=unknown_hi,
    )


@jax.jit
def record_appended_runs(
    state: GroupState,
    group_ids: jax.Array,
    los: jax.Array,
    his: jax.Array,
    terms: jax.Array,
) -> GroupState:
    """Record contiguous same-term appended runs — ONE row per group
    instead of one per entry (steady-state leaders append whole command
    batches in their current term). ``group_ids`` must be unique within
    the call (pad with an out-of-range gid). Ring slots covered by
    [lo, hi] are filled with ``term``; tails/staleness update as in
    ``record_appended``."""
    k = state.term_suffix.shape[-1]
    los_c = jnp.maximum(los, his - (k - 1))
    slots = jnp.arange(k)[None, :]
    # largest index i <= hi with i % k == slot
    idx_at_slot = his[:, None] - ((his[:, None] - slots) % k)
    mask = idx_at_slot >= los_c[:, None]
    cur = state.term_suffix.at[group_ids].get(mode="fill", fill_value=0)
    rows = jnp.where(mask, terms[:, None], cur)
    ts = state.term_suffix.at[group_ids].set(rows, mode="drop")
    last_index = state.last_index.at[group_ids].max(his, mode="drop")
    touched = (
        jnp.zeros_like(state.last_index, dtype=jnp.bool_)
        .at[group_ids].set(True, mode="drop")
    )
    ring_at_tail = jnp.take_along_axis(
        ts, (last_index % k)[:, None], axis=-1
    ).squeeze(-1)
    last_term = jnp.where(touched, ring_at_tail, state.last_term)
    unknown_lo = jnp.where(touched, 1, state.unknown_lo)
    unknown_hi = jnp.where(touched, 0, state.unknown_hi)
    return state._replace(
        term_suffix=ts,
        last_index=last_index,
        last_term=last_term,
        unknown_lo=unknown_lo,
        unknown_hi=unknown_hi,
    )


@jax.jit
def record_written(state: GroupState, group_ids: jax.Array, idxs: jax.Array) -> GroupState:
    """Advance durable watermarks after WAL fsync."""
    return state._replace(written_index=state.written_index.at[group_ids].max(idxs))


@jax.jit
def record_snapshot(
    state: GroupState, group_ids: jax.Array, idxs: jax.Array, terms: jax.Array
) -> GroupState:
    """Host installed snapshots for the named groups: move the snapshot
    boundary, advance tails/watermarks/commit, clear ring staleness."""
    touched = jnp.zeros_like(state.role, dtype=jnp.bool_).at[group_ids].set(True)
    snap_idx = state.snapshot_index.at[group_ids].set(idxs)
    snap_term = state.snapshot_term.at[group_ids].set(terms)
    last_index = state.last_index.at[group_ids].max(idxs)
    at_snap = last_index == snap_idx
    last_term = jnp.where(touched & at_snap, snap_term, state.last_term)
    written = state.written_index.at[group_ids].max(idxs)
    commit = state.commit_index.at[group_ids].max(idxs)
    unknown_lo = jnp.where(touched, 1, state.unknown_lo)
    unknown_hi = jnp.where(touched, 0, state.unknown_hi)
    return state._replace(
        snapshot_index=snap_idx,
        snapshot_term=snap_term,
        last_index=last_index,
        last_term=last_term,
        written_index=written,
        commit_index=commit,
        unknown_lo=unknown_lo,
        unknown_hi=unknown_hi,
    )


@jax.jit
def force_elections(state: GroupState, group_ids: jax.Array) -> GroupState:
    """Leadership-transfer fast path: the named groups become candidates
    IMMEDIATELY — term+1, vote for self, tallies cleared — skipping the
    pre-vote round. A TimeoutNow recipient must start a real election at
    once (Raft §3.10; reference: leadership transfer sends
    #timeout_now{} and the recipient calls an election directly,
    src/ra_server.erl handle_follower timeout_now). The host persists
    the bumped term/self-vote before any vote request leaves."""
    touched = (
        jnp.zeros_like(state.role, dtype=jnp.bool_)
        .at[group_ids].set(True, mode="drop")
    )
    return state._replace(
        role=jnp.where(touched, R_CANDIDATE, state.role),
        current_term=jnp.where(
            touched, state.current_term + 1, state.current_term
        ),
        voted_for=jnp.where(touched, state.self_slot, state.voted_for),
        leader_slot=jnp.where(touched, -1, state.leader_slot),
        votes=jnp.where(touched[:, None], False, state.votes),
        pre_votes=jnp.where(touched[:, None], False, state.pre_votes),
    )


@jax.jit
def set_roles(state: GroupState, group_ids: jax.Array, roles: jax.Array) -> GroupState:
    """Host-driven role transitions (election initiation and similar rare
    paths): scatter new roles and clear election tallies for the named
    groups."""
    role = state.role.at[group_ids].set(roles)
    touched = jnp.zeros_like(state.role, dtype=jnp.bool_).at[group_ids].set(True)
    votes = jnp.where(touched[:, None], False, state.votes)
    pre_votes = jnp.where(touched[:, None], False, state.pre_votes)
    # entering pre-vote opens a new round: bump the token so replies from
    # earlier rounds are ignored (the host mirrors this in
    # GroupHost.pre_vote_token)
    tok = state.pre_vote_token.at[group_ids].add(
        jnp.where(roles == R_PRE_VOTE, 1, 0)
    )
    return state._replace(
        role=role, votes=votes, pre_votes=pre_votes, pre_vote_token=tok
    )
