"""Scalar consensus decision functions — the oracle spec.

These are the *pure decision kernels* of Raft, extracted from the server
so that (a) the scalar server core and (b) the vectorized JAX kernels in
``ra_tpu.ops.consensus`` implement exactly the same math and can be
checked trace-for-trace against each other. They correspond to the three
north-star hot paths of the reference:

- AppendEntries term/prev-log matching (reference: src/ra_server.erl
  handle_follower :1283-1429, has_log_entry_or_snapshot :3168);
- RequestVote / PreVote grant logic (reference: src/ra_server.erl
  :1489-1529, process_pre_vote :2926-2984, is_candidate_log_up_to_date
  :3159-3165);
- match_index -> commit_index quorum scan (reference: src/ra_server.erl
  evaluate_quorum/increment_commit_index/agreed_commit :3633-3688).

Everything here is branch-light integer math over small tuples so the
vectorized versions are direct transcriptions.
"""

from __future__ import annotations

from typing import Sequence, Tuple

# AER accept decision codes
AER_STALE = 0  # rpc.term < current_term: reject, keep ours
AER_OK = 1  # prev matches: accept/append
AER_MISMATCH = 2  # prev missing or term conflict: reject with hint
AER_BEHIND_SNAPSHOT = 3  # prev_idx below our snapshot: leader is behind us


def log_up_to_date(
    our_last_idx: int, our_last_term: int, cand_last_idx: int, cand_last_term: int
) -> bool:
    """Raft 5.4.1: candidate's log is at least as up-to-date as ours."""
    return (cand_last_term > our_last_term) or (
        cand_last_term == our_last_term and cand_last_idx >= our_last_idx
    )


def aer_decision(
    current_term: int,
    rpc_term: int,
    prev_idx: int,
    prev_term: int,
    local_prev_term: int,  # term of our entry at prev_idx, -1 if absent
    snapshot_idx: int,  # our snapshot index, 0 if none
) -> int:
    """Classify an AppendEntries RPC. ``local_prev_term`` must be -1 when
    we have no entry at prev_idx (and prev_idx is not our snapshot index —
    callers fold the snapshot term into local_prev_term when it applies;
    prev_idx == 0 always matches with local_prev_term == 0)."""
    if rpc_term < current_term:
        return AER_STALE
    if prev_idx < snapshot_idx:
        return AER_BEHIND_SNAPSHOT
    if local_prev_term >= 0 and local_prev_term == prev_term:
        return AER_OK
    return AER_MISMATCH


def aer_failure_next_index(
    commit_index: int, our_last_idx: int, prev_idx: int, snapshot_idx: int
) -> int:
    """next_index hint carried in a failed AppendEntries reply.

    - behind-snapshot: point the leader past our snapshot;
    - short log: ask from our tail;
    - term conflict: back off to the first unknown-good index; committed
      entries always match, so commit_index + 1 is safe and live.
    """
    if prev_idx < snapshot_idx:
        return snapshot_idx + 1
    if our_last_idx < prev_idx:
        return our_last_idx + 1
    return commit_index + 1


def vote_decision(
    current_term: int,
    voted_for: int,  # peer slot we voted for this term; -1 = none
    candidate: int,  # candidate's peer slot
    rpc_term: int,
    cand_last_idx: int,
    cand_last_term: int,
    our_last_idx: int,
    our_last_term: int,
) -> Tuple[bool, int]:
    """RequestVote: returns (grant, new_current_term). A higher rpc term
    always bumps our term (even when the vote is denied); voted_for
    persistence is the caller's job."""
    term = max(current_term, rpc_term)
    if rpc_term < current_term:
        return False, term
    fresh_term = rpc_term > current_term
    free_to_vote = fresh_term or voted_for < 0 or voted_for == candidate
    grant = free_to_vote and log_up_to_date(
        our_last_idx, our_last_term, cand_last_idx, cand_last_term
    )
    return grant, term


def pre_vote_decision(
    current_term: int,
    rpc_term: int,
    cand_machine_version: int,
    our_machine_version: int,
    cand_last_idx: int,
    cand_last_term: int,
    our_last_idx: int,
    our_last_term: int,
) -> bool:
    """PreVote grant: no term change, no persistence. Granted iff the
    candidate's term is not behind ours, its log is up to date, and it
    supports at least our effective machine version (reference gating:
    src/ra_server.erl:2926-2984)."""
    return (
        rpc_term >= current_term
        and cand_machine_version >= our_machine_version
        and log_up_to_date(our_last_idx, our_last_term, cand_last_idx, cand_last_term)
    )


def agreed_commit(match_indexes: Sequence[int]) -> int:
    """Highest index replicated on a quorum: sort descending, take the
    majority-th element (reference: agreed_commit src/ra_server.erl:
    3684-3688). ``match_indexes`` must contain one entry per *voter*,
    including the leader's own durable watermark."""
    srt = sorted(match_indexes, reverse=True)
    quorum = len(srt) // 2  # 0-based index of the majority-th element
    return srt[quorum]


def new_commit_index(
    match_indexes: Sequence[int],
    current_commit: int,
    term_at_agreed: int,
    current_term: int,
) -> int:
    """Commit-index advance: only entries from the current term may
    commit by counting (Raft 5.4.2). ``term_at_agreed`` is the log term
    at ``agreed_commit(match_indexes)``."""
    agreed = agreed_commit(match_indexes)
    if agreed > current_commit and term_at_agreed == current_term:
        return agreed
    return current_commit
