"""ra_tpu — a TPU-native multi-raft state machine replication framework.

Capabilities follow rabbitmq/ra (persistent fault-tolerant replicated
state machines; thousands of Raft groups sharing one WAL), re-designed
TPU-first: the consensus decision hot path runs as vectorized JAX kernels
over group-id-indexed device arrays, while log/WAL/snapshot I/O stays on
the host.
"""

__version__ = "0.1.0"
