"""Named "systems": isolated instances of the whole stack.

Capability parity with the reference's ``ra_system`` (reference:
``src/ra_system.erl:32-62,162-183``): a system bundles a data directory,
its own WAL / segment writer / meta store / registry, and a config map;
multiple isolated systems can run in one process. Config has three tiers
(reference: README.md:250-380):

  1. process-global defaults (``default_config``),
  2. per-system overrides (``SystemConfig``),
  3. per-server config (``ra_tpu.server.ServerConfig``), persisted with
     the server and partially mutable on restart.
"""

from __future__ import annotations

import dataclasses
import logging
import os
import threading
from typing import Dict, Optional

logger = logging.getLogger("ra_tpu")

DEFAULT_SYSTEM = "default"

# Defaults mirror the reference's tuning constants (src/ra.hrl:214-228,
# src/ra_server.hrl:7-9, src/ra_log.erl:65-67) — same knobs, same units.
WAL_MAX_SIZE_BYTES = 256 * 1024 * 1024
WAL_MAX_BATCH_SIZE = 8192
SEGMENT_MAX_ENTRIES = 4096
SEGMENT_MAX_SIZE_BYTES = 64 * 1024 * 1024
SNAPSHOT_CHUNK_SIZE = 1024 * 1024
MIN_SNAPSHOT_INTERVAL = 4096
MIN_CHECKPOINT_INTERVAL = 16384
DEFAULT_MAX_PIPELINE_COUNT = 4096
DEFAULT_AER_BATCH_SIZE = 128
RESEND_WINDOW_SECONDS = 20
SNAPSHOT_INSTALL_TIMEOUT_S = 120


@dataclasses.dataclass
class Names:
    """Well-known per-system component names (cf. ra_system:names/0)."""

    system: str
    wal: str
    segment_writer: str
    log_meta: str
    directory: str
    log_ets: str
    sync_pool: str

    @staticmethod
    def derive(system: str) -> "Names":
        p = f"ra_{system}"
        return Names(
            system=system,
            wal=f"{p}_wal",
            segment_writer=f"{p}_segment_writer",
            log_meta=f"{p}_meta",
            directory=f"{p}_directory",
            log_ets=f"{p}_log_tables",
            sync_pool=f"{p}_sync_pool",
        )


@dataclasses.dataclass
class SystemConfig:
    name: str = DEFAULT_SYSTEM
    data_dir: str = ""
    wal_max_size_bytes: int = WAL_MAX_SIZE_BYTES
    wal_max_batch_size: int = WAL_MAX_BATCH_SIZE
    wal_compute_checksums: bool = True
    wal_sync_method: str = "datasync"  # datasync | sync | none
    # adaptive group commit (docs/INTERNALS.md §15): hold a small flush
    # open up to this bound while a burst is still arriving so it pays
    # one fsync; 0 disables. The wait is only entered when the smoothed
    # arrival rate predicts >= wal_group_commit_min_gain more entries
    # inside the bound — an idle write never waits on a timer.
    wal_group_commit_max_delay_s: float = 0.002
    wal_group_commit_min_gain: int = 8
    segment_max_entries: int = SEGMENT_MAX_ENTRIES
    # "map": parse segment indexes on open (fastest lookups);
    # "binary": binary-search raw slots + read-ahead (low memory for
    # sparse reads over many segments; reference index modes,
    # src/ra_log_segment.erl:55-59)
    segment_index_mode: str = "map"
    segment_max_size_bytes: int = SEGMENT_MAX_SIZE_BYTES
    segment_compute_checksums: bool = True
    snapshot_chunk_size: int = SNAPSHOT_CHUNK_SIZE
    default_max_pipeline_count: int = DEFAULT_MAX_PIPELINE_COUNT
    # client admission window (appended-but-unapplied backlog cap per
    # group; see docs/INTERNALS.md §12 flow control)
    default_max_command_backlog: int = DEFAULT_MAX_PIPELINE_COUNT
    default_max_append_entries_rpc_batch_size: int = DEFAULT_AER_BATCH_SIZE
    min_snapshot_interval: int = MIN_SNAPSHOT_INTERVAL
    min_checkpoint_interval: int = MIN_CHECKPOINT_INTERVAL
    resend_window_seconds: int = RESEND_WINDOW_SECONDS
    snapshot_install_timeout_s: int = SNAPSHOT_INSTALL_TIMEOUT_S
    # registered: restart every registered server on system start.
    server_recovery_strategy: str = "none"  # none | registered
    # log-infra supervision intensity (the OTP supervisor analog): more
    # than ``infra_restart_intensity`` WAL/segment-writer restart
    # episodes inside ``infra_restart_window_s`` seconds marks the
    # node's storage infra DOWN — servers stay in await_condition and
    # the operator must intervene (a disk that fails every few seconds
    # is not healing; endless restarts would just churn)
    infra_restart_intensity: int = 5
    infra_restart_window_s: float = 10.0
    # storage-pressure survival plane (docs/INTERNALS.md §21): byte
    # watermarks over the node's data dir (WAL + segments + snapshots
    # + accept spools). Soft triggers emergency reclamation (forced
    # snapshots -> release cursors -> major compaction -> snapshot
    # prunes) BEFORE ENOSPC fires; hard pre-empts client admission
    # (typed RA_NOSPACE rejects). 0 = unlimited (watermarks off).
    disk_soft_limit_bytes: int = 0
    disk_hard_limit_bytes: int = 0
    disk_check_interval_s: float = 1.0
    # slow-disk brownout (li-smoothed mean WAL fsync latency, us):
    # `streak` consecutive checks past enter sheds leaderships via
    # transfer_leadership; the same streak under exit un-marks
    brownout_enter_us: float = 200_000.0
    brownout_exit_us: float = 50_000.0
    brownout_streak: int = 3
    # receiver-paced snapshot chunk credit window (flow-controlled
    # snapshot streaming); receivers grant 0 while storage-blocked
    snapshot_credit_window: int = 4
    # all: bump machine version when leader supports it; quorum: when a
    # quorum of members support it (reference: src/ra_server.erl:223-233).
    machine_upgrade_strategy: str = "all"
    # NOTE (async command plane, docs/INTERNALS.md §16): the tpu_batch
    # command-plane knobs — lock-free ingress rings on/off, per-lane
    # slot count, dedicated egress sender thread — are constructor
    # kwargs of runtime.coordinator.BatchCoordinator (``rings``,
    # ``ingress_ring_slots``, ``egress_async``), surfaced as
    # ``bench.py --rings`` and ``kv_harness --rings``. They are NOT
    # SystemConfig fields: nothing constructs a BatchCoordinator from
    # a SystemConfig today, and a config field nothing reads would be
    # a silent no-op trap for operators.
    # Server execution backend: per_group_actor (scalar oracle path) or
    # tpu_batch (batching coordinator with device-resident decision state).
    server_impl: str = "per_group_actor"
    names: Names = None  # type: ignore[assignment]

    def __post_init__(self) -> None:
        from ra_tpu.utils.lib import validate_name

        if not validate_name(self.name):
            raise ValueError(f"invalid system name {self.name!r}")
        if not self.data_dir:
            self.data_dir = default_data_dir(self.name)
        if self.names is None:
            self.names = Names.derive(self.name)

    def server_data_dir(self, uid: str) -> str:
        return os.path.join(self.data_dir, uid)


def default_data_dir(system: str = DEFAULT_SYSTEM) -> str:
    base = os.environ.get("RA_TPU_DATA_DIR", os.path.join(os.getcwd(), "ra_data"))
    return os.path.join(base, system)


class _SystemRegistry:
    """Running systems in this process (cf. persistent_term storage in the
    reference, src/ra_system.erl:176-183)."""

    def __init__(self) -> None:
        self._lock = threading.Lock()
        self._systems: Dict[str, object] = {}  # name -> runtime System object

    def put(self, name: str, system: object) -> None:
        with self._lock:
            if name in self._systems:
                raise RuntimeError(f"system {name!r} already running")
            self._systems[name] = system

    def get(self, name: str) -> Optional[object]:
        return self._systems.get(name)

    def pop(self, name: str) -> Optional[object]:
        with self._lock:
            return self._systems.pop(name, None)

    def names(self):
        return list(self._systems.keys())


_registry = _SystemRegistry()


def registry() -> _SystemRegistry:
    return _registry


def default_config(data_dir: Optional[str] = None) -> SystemConfig:
    return SystemConfig(name=DEFAULT_SYSTEM, data_dir=data_dir or "")
