"""Leaky integrator: smoothed commit-rate gauge.

The role of the reference's ``ra_li`` (``src/ra_li.erl``, driving the
``commit_rate`` overview gauge): an exponentially-decayed rate estimate
updated from (count, dt) samples. :class:`VectorLeakyIntegrator` is the
batched form — one EWMA lane per raft group, updated from numpy count
vectors so the health plane smooths thousands of per-group commit rates
in one vector op per tick (no per-group Python loop).
"""

from __future__ import annotations

import numpy as np


class LeakyIntegrator:
    __slots__ = ("alpha", "rate")

    def __init__(self, alpha: float = 0.3):
        self.alpha = alpha
        self.rate = 0.0

    def sample(self, count: int, dt_s: float) -> float:
        if dt_s <= 0:
            return self.rate
        inst = count / dt_s
        self.rate = self.alpha * inst + (1 - self.alpha) * self.rate
        return self.rate


class VectorLeakyIntegrator:
    """Per-slot leaky integrators over a fixed capacity, updated in one
    vectorized pass: ``rate[i] = a*inst[i] + (1-a)*rate[i]`` for the
    slots named by an index array. Slots not in the update set keep
    their last estimate (they decay only when sampled — matching the
    scalar integrator, which is also only fed when its owner ticks)."""

    __slots__ = ("alpha", "rate")

    def __init__(self, capacity: int, alpha: float = 0.3):
        self.alpha = alpha
        self.rate = np.zeros(capacity, np.float64)

    def grow(self, capacity: int) -> None:
        if capacity > len(self.rate):
            new = np.zeros(capacity, np.float64)
            new[: len(self.rate)] = self.rate
            self.rate = new

    def sample(self, slots: np.ndarray, counts: np.ndarray,
               dt_s: float) -> np.ndarray:
        """Fold ``counts/dt_s`` into the integrators at ``slots``;
        returns the updated rates for those slots."""
        if dt_s <= 0:
            return self.rate[slots]
        inst = counts / dt_s
        upd = self.alpha * inst + (1 - self.alpha) * self.rate[slots]
        self.rate[slots] = upd
        return upd

    def reset(self, slots: np.ndarray) -> None:
        self.rate[slots] = 0.0
