"""Leaky integrator: smoothed commit-rate gauge.

The role of the reference's ``ra_li`` (``src/ra_li.erl``, driving the
``commit_rate`` overview gauge): an exponentially-decayed rate estimate
updated from (count, dt) samples.
"""

from __future__ import annotations


class LeakyIntegrator:
    __slots__ = ("alpha", "rate")

    def __init__(self, alpha: float = 0.3):
        self.alpha = alpha
        self.rate = 0.0

    def sample(self, count: int, dt_s: float) -> float:
        if dt_s <= 0:
            return self.rate
        inst = count / dt_s
        self.rate = self.alpha * inst + (1 - self.alpha) * self.rate
        return self.rate
