"""Clock-bound leader leases: the pure math (docs/INTERNALS.md §20).

A leader that has heard a quorum of acks recently enough may serve
linearizable reads locally, because the same quorum promises (via
pre-vote leader stickiness) not to elect a replacement until a full
election timeout of silence has passed on their own clocks. The lease
window must therefore be strictly shorter than that promise:

    expiry = basis + election_timeout * safety_factor - drift_epsilon

with ``safety_factor < 1`` and ``drift_epsilon`` absorbing bounded
clock-RATE drift between nodes over one window (no absolute clock
agreement is assumed — every comparison is leader-local monotonic
time through the ``runtime/clock.py`` seam, so the sim backend can
skew it adversarially).

``basis`` is NOT the ack receive time. An ack proves the follower was
alive at some moment between our send and our receive; crediting
receive time would over-credit by the one-way return latency, which an
adversarial network can stretch arbitrarily. Each tracker therefore
stamps the OLDEST outstanding send per peer and credits that stamp
when any response at the leader's term arrives — always a lower bound
on the follower's true last-contact time (ra_tpu mirror of the
send-basis rule in "Paxos vs Raft", arxiv 2004.05074 §4.3).

The quorum basis is the k-th largest per-voter basis (self counts at
``now``): at least k voters heard from us at or after it, and any
future election quorum intersects them in ≥1 voter whose stickiness
promise outlives our (shorter) lease.

Two consumers share this module: the actor backend's per-server
``LeaseTracker`` and the batch coordinator's vectorized ``(G, P)``
stamp arrays (``quorum_bases``). Both funnel the final horizon through
``lease_expiry`` so the safety arithmetic lives in exactly one place.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Sequence, Tuple

import numpy as np

# Defaults: the window is deliberately a fraction of the follower
# promise (election_timeout), with a small absolute epsilon on top for
# clock-rate drift. 0.8/2ms keeps leases comfortably renewable by
# read-triggered rounds at the repo's 0.15 s test election timeout.
DEFAULT_SAFETY_FACTOR = 0.8
DEFAULT_DRIFT_EPSILON_S = 0.002

# Test-only failpoint (PR-8 style, see models/fifo.py
# SIM_BUG_REVERSED_REQUEUE): when flipped on, the drift bound is
# mis-derived — the margin terms ADD to the window instead of
# shrinking it, so a lease can outlive the follower promise and a
# deposed leader will serve stale reads. The sim oracle must catch
# this on every seed (tests/test_sim.py).
SIM_BUG_DRIFT_BOUND = False


def lease_expiry(basis, election_timeout_s: float,
                 safety_factor: float = DEFAULT_SAFETY_FACTOR,
                 drift_epsilon_s: float = DEFAULT_DRIFT_EPSILON_S):
    """Safe lease horizon for a quorum ack basis. Elementwise over
    numpy arrays (the batch backend passes a ``(G,)`` basis column)."""
    if SIM_BUG_DRIFT_BOUND:
        # planted bug: margins flipped to extensions — the lease
        # outlives the follower stickiness promise
        return basis + election_timeout_s * (1.0 + safety_factor) \
            + drift_epsilon_s
    return basis + election_timeout_s * safety_factor - drift_epsilon_s


@dataclass(frozen=True)
class LeaseConfig:
    """Lease knobs. ``enabled`` defaults OFF everywhere: leader
    stickiness changes election behavior (a follower with recent
    leader contact refuses pre-votes), which existing churn tests
    trigger deliberately; harness/bench/sim opt in explicitly."""

    enabled: bool = False
    election_timeout_s: float = 0.15
    safety_factor: float = DEFAULT_SAFETY_FACTOR
    drift_epsilon_s: float = DEFAULT_DRIFT_EPSILON_S

    def expiry(self, basis: float) -> float:
        return lease_expiry(basis, self.election_timeout_s,
                            self.safety_factor, self.drift_epsilon_s)

    @property
    def window_s(self) -> float:
        """Nominal lease length from a fresh basis."""
        return self.expiry(0.0)


class LeaseTracker:
    """Scalar lease state for one actor-backend leader.

    The owner stamps ``record_send`` on every quorum-bearing outbound
    (AER, heartbeat), credits ``record_ack`` on every same-term
    response, and calls ``refresh`` to fold the credited bases into a
    monotonically-advancing expiry. ``revoke`` clears BOTH the expiry
    and the stamps: acks already in flight at deposition time must not
    resurrect a lease for a leadership we no longer hold.
    """

    __slots__ = ("cfg", "expiry", "_sent", "_basis")

    def __init__(self, cfg: LeaseConfig):
        self.cfg = cfg
        self.expiry = 0.0
        self._sent: Dict[object, float] = {}
        self._basis: Dict[object, float] = {}

    def record_send(self, peer, now: float) -> None:
        """Stamp the oldest outstanding send to ``peer`` (later sends
        before an ack keep the older, more conservative stamp)."""
        self._sent.setdefault(peer, now)

    def record_ack(self, peer) -> bool:
        """Credit a same-term response from ``peer`` against its
        oldest outstanding send. Unsolicited responses (no send on
        record — e.g. a duplicate ack) credit nothing: under-crediting
        is always safe. Returns True if a basis advanced."""
        basis = self._sent.pop(peer, None)
        if basis is None:
            return False
        if basis > self._basis.get(peer, 0.0):
            self._basis[peer] = basis
            return True
        return False

    def refresh(self, voters: Sequence, self_id, now: float) -> bool:
        """Recompute the expiry from the current per-voter bases
        (self credits at ``now``). Returns True when the lease
        horizon advanced (it never moves backwards: an older quorum's
        promise is not withdrawn by a newer minority)."""
        n = len(voters)
        if n == 0:
            return False
        k = n // 2 + 1
        bases = sorted(
            (now if v == self_id else self._basis.get(v, 0.0)
             for v in voters),
            reverse=True,
        )
        basis = bases[k - 1]
        if basis <= 0.0:
            return False
        e = self.cfg.expiry(basis)
        if e > self.expiry:
            self.expiry = e
            return True
        return False

    def valid(self, now: float) -> bool:
        return now < self.expiry

    def remaining(self, now: float) -> float:
        return max(0.0, self.expiry - now)

    def revoke(self) -> bool:
        """Drop the lease AND the stamps (in-flight pre-revocation
        acks must not resurrect it). Returns True if a live-or-past
        lease existed (callers count revocations only when one did)."""
        had = self.expiry > 0.0
        self.expiry = 0.0
        self._sent.clear()
        self._basis.clear()
        return had


def quorum_bases(bases: np.ndarray, voter_mask: np.ndarray,
                 quorum: np.ndarray) -> np.ndarray:
    """Vectorized per-group quorum basis for the batch backend.

    ``bases``: (G, P) float64 per-slot ack bases, with each group's
    self slot already set to "now"; ``voter_mask``: (G, P) bool;
    ``quorum``: (G,) int voter-majority sizes. Returns the (G,) k-th
    largest voter basis; groups with no quorum (or no positive basis
    at the quorum rank) get 0.0.
    """
    masked = np.where(voter_mask, bases, -np.inf)
    order = -np.sort(-masked, axis=1)  # descending per row
    k = np.clip(quorum - 1, 0, bases.shape[1] - 1).astype(np.int64)
    out = np.take_along_axis(order, k[:, None], axis=1)[:, 0]
    return np.where(np.isfinite(out) & (quorum >= 1) & (out > 0.0),
                    out, 0.0)
